"""Batched consolidation-candidate evaluation on the accelerator.

The TPU reformulation of the disruption engine's candidate simulation
(HOT LOOP #3, SURVEY.md section 3.2: for each candidate node (set), "can its
pods reschedule onto the remaining nodes, plus at most one strictly cheaper
new node?"). The reference evaluates candidates one at a time against a full
scheduling simulation (designs/consolidation.md); here every candidate set
is evaluated simultaneously:

- the repack simulation is a vmap over candidate sets of a lax.scan over
  FFD-ordered pod classes; the carry is the per-node remaining headroom
  [N, R], and first-fit spill across nodes uses the same exclusive-cumsum
  trick as the provisioning solver (solver/ffd.py)
- node-level feasibility (labels, taints) is a [C, N] boolean mask computed
  host-side from concrete node labels (nodes are few and labels are
  concrete -- no bitset vocabulary needed on this side)
- the one-new-node replacement search reduces to: which instance types are
  compatible with EVERY leftover class and large enough for their aggregate
  -- a masked min over the staged (type, zone, captype) price tensor

Scope: candidate sets whose pods carry stateful constraints (hard topology
spread, affinity terms, multi-term node affinity) are routed to the Python
oracle by the disruption controller; for everything else this evaluator is
differentially equivalent to oracle.Scheduler (tests/test_consolidate.py).

Verdicts are *decisions* for deletion (equivalence is exact) and a
*pre-filter plus price* for replacement: the controller re-derives the
replacement group through the oracle for the one candidate it acts on,
so N-candidate scans cost one device call instead of N full simulations.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from karpenter_tpu.apis import NodePool, Pod, labels as wk
from karpenter_tpu.scheduling import Resources, tolerates_all
from karpenter_tpu.scheduling import resources as res
from karpenter_tpu.solver import encode
from karpenter_tpu.solver.encode import CatalogTensors
from karpenter_tpu.solver.oracle import ExistingNode

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# XLA backend at import (see solver/ffd.py _INF)
_INF = np.float32(np.inf)

_bucket = encode.bucket


# -- device kernels ----------------------------------------------------------

@functools.partial(jax.jit, static_argnames=())
def _repack(
    headroom0: jax.Array,   # [N, R] f32 remaining capacity of surviving nodes
    feas: jax.Array,        # [C, N] bool class-on-node feasibility
    req: jax.Array,         # [C, R] f32 per-pod request (includes pods=1)
    member: jax.Array,      # [S, C] i32 pods of class c in candidate set s
    excl: jax.Array,        # [S, N] bool node n is being deleted by set s
) -> Tuple[jax.Array, jax.Array]:
    """([S, C] i32 leftovers, [S, C, N] i32 per-node placements): pods of
    class c in set s packed first-fit-decreasing onto the surviving nodes
    (node order = oracle order); leftover did not fit anywhere."""

    def one_set(member_s: jax.Array, excl_s: jax.Array):
        hr0 = jnp.where(excl_s[:, None], 0.0, headroom0)          # [N, R]

        def step(hr, xs):
            req_c, feas_c, count_c = xs
            safe = jnp.where(req_c > 0, req_c, 1.0)               # [R]
            per_axis = jnp.where(
                req_c[None, :] > 0, jnp.floor(hr / safe[None, :]), _INF
            )                                                     # [N, R]
            fit = jnp.maximum(jnp.min(per_axis, axis=-1), 0.0)    # [N]
            fit = jnp.where(feas_c, fit, 0.0).astype(jnp.int32)
            cum_before = jnp.cumsum(fit) - fit
            take = jnp.clip(count_c - cum_before, 0, fit)         # [N]
            hr2 = hr - take[:, None].astype(jnp.float32) * req_c[None, :]
            return hr2, (count_c - jnp.sum(take), take)

        _, (leftover, takes) = jax.lax.scan(step, hr0, (req, feas, member_s))
        return leftover, takes                                    # [C], [C, N]

    return jax.vmap(one_set)(member, excl)


@functools.partial(jax.jit, static_argnames=())
def _replacement_search(
    leftover: jax.Array,    # [S, C] i32
    req: jax.Array,         # [C, R] f32
    compat: jax.Array,      # [C, K] bool class-type compat (pool ctx included)
    azone: jax.Array,       # [C, Z] bool
    acap: jax.Array,        # [C, CT] bool
    cap: jax.Array,         # [K, R] f32
    price: jax.Array,       # [K, Z, CT] f32 (+inf when unavailable)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cheapest single new node that absorbs every leftover pod of each set.
    Returns (best_price [S], best_od_price [S], best_type [S] i32, -1 none).
    A type qualifies iff it is compatible with every leftover class and its
    capacity covers the aggregate leftover request; the offering must sit in
    a zone/captype admitted by every leftover class."""
    need = leftover > 0                                           # [S, C]
    agg = jnp.einsum("sc,cr->sr", leftover.astype(jnp.float32), req)
    ok_type = ~jnp.einsum("sc,ck->sk", need, ~compat)             # [S, K] no violator
    fits = jnp.all(cap[None, :, :] >= agg[:, None, :], axis=-1)   # [S, K]
    ok_type = ok_type & fits & jnp.any(need, axis=-1)[:, None]
    zone_ok = ~jnp.einsum("sc,cz->sz", need, ~azone)              # [S, Z]
    cap_ok = ~jnp.einsum("sc,ct->st", need, ~acap)                # [S, CT]
    masked = jnp.where(
        ok_type[:, :, None, None]
        & zone_ok[:, None, :, None]
        & cap_ok[:, None, None, :],
        price[None, :, :, :],
        _INF,
    )                                                             # [S, K, Z, CT]
    S, K, Z, CTn = masked.shape
    flat = masked.reshape(S, -1)
    best_price = jnp.min(flat, axis=-1)
    best_type = jnp.where(
        jnp.isfinite(best_price), (jnp.argmin(flat, axis=-1) // (Z * CTn)).astype(jnp.int32), -1
    )
    od = encode.CAPTYPE_INDEX[wk.CAPACITY_TYPE_ON_DEMAND]
    best_od_price = jnp.min(masked[:, :, :, od].reshape(S, -1), axis=-1)
    return best_price, best_od_price, best_type


# -- host-side encoding + evaluator ------------------------------------------

@dataclass
class SetVerdict:
    """Device verdict for one candidate set."""

    can_delete: bool
    leftover: int                      # pods that did not fit existing nodes
    replace_price: float               # cheapest single-new-node price (inf none)
    replace_od_price: float            # cheapest on-demand-only price (inf none)
    replace_type: Optional[str]        # instance type name (None when inf)
    nodepool: Optional[str]            # pool the replacement came from


def _node_feasibility(
    classes: Sequence[encode.PodClass], nodes: Sequence[ExistingNode],
    class_zone_pins: bool = False,
) -> np.ndarray:
    """[C, N] bool: a pod of class c may land on node n (labels + taints).
    Mirrors oracle._try_existing's compatibility gate. With
    `class_zone_pins`, a SPREAD SUB-CLASS's pinned zone (the split pass
    marks these env_count == 0) additionally gates the node's zone -- the
    oracle's pinned-zone node-packing rule. Ordinary classes stay
    pool-agnostic: a pool-derived zone requirement must not block packing
    onto live capacity the oracle would use."""
    C, N = len(classes), len(nodes)
    out = np.zeros((C, N), dtype=bool)
    for ci, pc in enumerate(classes):
        pod = pc.pods[0]
        zreq = (
            pc.requirements.get(wk.ZONE_LABEL)
            if class_zone_pins and pc.env_count == 0
            else None
        )
        for ni, node in enumerate(nodes):
            if not tolerates_all(pod.tolerations, node.taints):
                continue
            if zreq is not None:
                node_zone = node.labels.get(wk.ZONE_LABEL)
                if node_zone is None or not zreq.matches(node_zone):
                    continue
            out[ci, ni] = any(
                alt.matches_labels(node.labels) for alt in pod.scheduling_requirements()
            )
    return out


class ConsolidationEvaluator:
    """Evaluates many consolidation candidate sets in one device dispatch.

    Replacement context comes from the nodepools in weight order: the first
    pool whose catalog admits a feasible replacement wins (the oracle's
    pool-iteration order in _open_group)."""

    def __init__(self, mesh=None):
        # optional jax.sharding.Mesh: candidate sets are data-parallel
        # across devices (parallel/mesh.sharded_repack); None = single chip
        self.mesh = mesh
        # keyed by object identity; holds the items list so the id stays valid
        self._catalog_cache: Dict[int, Tuple[list, CatalogTensors]] = {}

    def _catalog_tensors(self, items: list) -> CatalogTensors:
        key = id(items)
        hit = self._catalog_cache.get(key)
        if hit is None:
            if len(self._catalog_cache) > 8:  # bound it; evict oldest entry
                self._catalog_cache.pop(next(iter(self._catalog_cache)))
            hit = self._catalog_cache[key] = (items, encode.encode_catalog(items))
        return hit[1]

    def evaluate(
        self,
        nodes: Sequence[ExistingNode],
        sets: Sequence[Tuple[Sequence[Pod], Sequence[str]]],
        pools: Sequence[NodePool] = (),
        catalogs: Optional[Dict[str, list]] = None,
        daemon_overhead: Optional[Dict[str, "Resources"]] = None,
    ) -> List[SetVerdict]:
        """nodes: surviving-capacity snapshot (oracle node order).
        sets: per candidate set, (pods to repack, names of excluded nodes).
        pools/catalogs: replacement context (optional; omit for delete-only).
        daemon_overhead: per-pool fresh-node reserve (apis/daemonset) --
        a replacement node must fit the leftovers PLUS its daemonsets.

        On the jax-discipline hot-path manifest (DEVICE_HOT_PATH) and a
        SANCTIONED_FETCH site: the np.asarray fetches below are this
        path's designed host barriers (async-prefetched); any other sync
        added here is a lint violation.
        """
        if not sets:
            return []
        all_pods = [p for pods, _ in sets for p in pods]
        if not all_pods:
            return [
                SetVerdict(True, 0, float("inf"), float("inf"), None, None) for _ in sets
            ]
        classes = encode.group_pods(all_pods)
        key_of = {pc.key: i for i, pc in enumerate(classes)}

        C = _bucket(len(classes))
        N = _bucket(max(1, len(nodes)), lo=16)
        S = _bucket(len(sets))
        if self.mesh is not None and S % self.mesh.size:
            # the sharded set axis must divide evenly across devices
            S = ((S + self.mesh.size - 1) // self.mesh.size) * self.mesh.size
        R = encode.R

        req = np.zeros((C, R), dtype=np.float32)
        for i, pc in enumerate(classes):
            req[i] = pc.requests
        feas = np.zeros((C, N), dtype=bool)
        feas[: len(classes), : len(nodes)] = _node_feasibility(classes, nodes)
        headroom = np.zeros((N, R), dtype=np.float32)
        for ni, node in enumerate(nodes):
            headroom[ni] = encode.scale_vector(node.remaining().to_vector())

        member = np.zeros((S, C), dtype=np.int32)
        excl = np.zeros((S, N), dtype=bool)
        name_to_idx = {n.name: i for i, n in enumerate(nodes)}
        for si, (pods, excluded) in enumerate(sets):
            for p in pods:
                pc_reqs = p.scheduling_requirements()[0]
                k = encode._class_key(p, pc_reqs)
                member[si, key_of[k]] += 1
            for name in excluded:
                ni = name_to_idx.get(name)
                if ni is not None:
                    excl[si, ni] = True

        if self.mesh is not None:
            from karpenter_tpu.parallel.mesh import sharded_repack

            leftover, _ = sharded_repack(self.mesh, headroom, feas, req, member, excl)
        else:
            leftover, _ = _repack(headroom, feas, req, member, excl)
        if hasattr(leftover, "copy_to_host_async"):
            # one async D2H issued at dispatch (a synchronous fetch over a
            # tunneled device costs a flat ~64 ms RTT; see service.solve)
            leftover.copy_to_host_async()
        leftover = np.asarray(leftover)
        left_total = leftover.sum(axis=1)

        verdicts = [
            SetVerdict(
                can_delete=bool(left_total[si] == 0),
                leftover=int(left_total[si]),
                replace_price=float("inf"),
                replace_od_price=float("inf"),
                replace_type=None,
                nodepool=None,
            )
            for si in range(len(sets))
        ]

        # replacement search per pool, weight order, first feasible pool wins
        pending = [si for si in range(len(sets)) if left_total[si] > 0]
        if not pending or not pools or not catalogs:
            return verdicts
        for pool in sorted(pools, key=lambda p: -p.weight):
            items = catalogs.get(pool.name) or []
            if not items:
                continue
            catalog = self._catalog_tensors(items)
            cs = encode.encode_classes(
                _with_pool_requirements(classes, pool), catalog,
                # template.taints ONLY: startup taints lift before pods land
                # (provisioner.py:68), and the oracle's _open_group gates on
                # exactly this set -- including startup taints here would
                # wrongly report inf replacement price for pods that do not
                # tolerate them (ADVICE round 1, medium)
                pool_taints=list(pool.template.taints),
                c_pad=C,
            )
            compat = encode.compat_matrix(catalog, cs)
            cap_eff = catalog.cap
            ovh = (daemon_overhead or {}).get(pool.name)
            if ovh is not None:
                ovh_vec = encode.scale_vector(ovh.to_vector()).astype(np.float32)
                if np.any(ovh_vec):
                    cap_eff = np.maximum(cap_eff - ovh_vec[None, :], np.float32(0.0))
            out = _replacement_search(
                jnp.asarray(leftover), jnp.asarray(cs.req), jnp.asarray(compat),
                jnp.asarray(cs.azone), jnp.asarray(cs.acap),
                jnp.asarray(cap_eff), jnp.asarray(catalog.price),
            )
            for x in out:
                if hasattr(x, "copy_to_host_async"):
                    x.copy_to_host_async()  # overlap the three fetches
            best, best_od, best_k = (np.asarray(x) for x in out)
            still = []
            for si in pending:
                if np.isfinite(best[si]):
                    verdicts[si] = SetVerdict(
                        can_delete=False,
                        leftover=int(left_total[si]),
                        replace_price=float(best[si]),
                        replace_od_price=float(best_od[si]),
                        replace_type=catalog.names[int(best_k[si])],
                        nodepool=pool.name,
                    )
                else:
                    still.append(si)
            pending = still
            if not pending:
                break
        return verdicts


def _with_pool_requirements(classes: Sequence[encode.PodClass], pool: NodePool) -> List[encode.PodClass]:
    """Re-derive each class's requirements merged with the pool's (the class
    set was grouped pool-agnostically; replacement compat is per-pool).
    One shared implementation with the provisioning path -- merge
    orientation is immaterial because Requirement.intersect is commutative
    in every branch (set ops + symmetric min/max windows)."""
    return encode.with_extra_requirements(classes, pool.requirements())


def device_eligible(pods: Sequence[Pod]) -> bool:
    """True when every pod is free of the stateful constraints the batch
    evaluator does not model (routing mirror of solver/service.py)."""
    for p in pods:
        if p.affinity_terms or p.preferred_node_affinity_terms or p.preferred_affinity_terms:
            return False
        if any(t.hard() for t in p.topology_spread):
            return False
        if len(p.scheduling_requirements()) != 1:
            return False
    return True
