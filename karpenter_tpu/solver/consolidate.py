"""Back-compat shim: the batched consolidation evaluator moved to
``karpenter_tpu/solver/disrupt/`` (the device-resident consolidation
subsystem: kernels in ``disrupt/kernel.py``, host orchestration + the
``solve_disrupt`` wire route in ``disrupt/engine.py``).

``ConsolidationEvaluator`` remains the historical name for
``DisruptEngine`` -- same constructor, same ``evaluate`` contract -- so
existing callers and tests keep working unchanged.
"""
from karpenter_tpu.solver.disrupt.engine import (  # noqa: F401
    DisruptEngine,
    SetVerdict,
    _node_feasibility,
    _with_pool_requirements,
    device_eligible,
)

ConsolidationEvaluator = DisruptEngine
