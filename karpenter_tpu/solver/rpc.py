"""Solver service boundary: the decision plane as a network sidecar.

SURVEY.md section 2.4/5 maps the reference's cloud-RPC seam (aws-sdk over
HTTPS with batching) to an RPC boundary between the host-side reconcilers
and the solver process on the TPU VM. This module implements that boundary
as a dependency-free length-prefixed binary protocol (the image ships no
grpc; the frame layout below is trivially portable to gRPC streaming
messages later):

    frame := u32 header_len | header_json | payload_bytes
    header := {"op"|"ok": ..., meta..., "tensors": [{name, dtype, shape}]}
    payload := the tensors' raw little-endian buffers, concatenated

Security posture (round 4, mirroring the reference's HTTPS+SigV4 seams,
`pkg/operator/operator.go:97-98`):

- the DEFAULT transport is a UNIX domain socket (mode 0600) -- filesystem
  permissions are the trust boundary, exactly right for the sidecar
  topology where reconcilers and solver share a pod;
- a TCP listener REQUIRES a shared token (constructor arg or
  KARPENTER_TPU_SOLVER_TOKEN) unless `insecure_tcp=True` is an explicit
  operator decision; the client proves it with an `auth` frame -- the
  FIRST frame on the connection, compared constant-time -- before any
  other op is dispatched;
- TCP can additionally be wrapped in TLS (`ssl_context` on both ends).

Design constraints carried over from the in-process solver (SURVEY.md
section 7 hard part #6 -- the 100 ms budget leaves no room for re-shipping
state): the catalog tensors are staged on the server ONCE per catalog
seqnum (`stage` op); each `solve` ships only the pod-class tensors
(~100 KB at 50k-pod scale) and returns the solve outputs; connections are
persistent (one socket, many solves).

Server-side compute = the same jitted kernels the in-process path uses
(solver/ffd.py), so differential guarantees carry over unchanged.
"""
from __future__ import annotations

import hmac
import json
import os
import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu import failpoints, metrics, tracing
from karpenter_tpu.solver import encode, ffd

TOKEN_ENV = "KARPENTER_TPU_SOLVER_TOKEN"
# kill switch for delta class shipping (solve_delta): the client defaults
# to delta-on whenever the server advertises the feature; "0" forces every
# solve back to the full class-tensor ship
DELTA_ENV = "KARPENTER_TPU_DELTA"

# the per-class tensors delta shipping can patch row-wise. node_overhead
# ([R], whole-set) always ships in full; open_allowed/join_allowed ([C, K]
# merged-multipool masks) bypass the delta path entirely -- they dominate
# the payload when present and the merged shape re-derives them per tick.
PER_CLASS_TENSORS = (
    "req", "count", "env_count", "allowed", "num_lo", "num_hi",
    "azone", "acap", "schedulable",
)
# never ship a delta when more than this fraction of rows changed: the
# row-index header plus per-row framing overtakes the dense ship
DELTA_MAX_DIRTY_FRACTION = 0.5

# connection ESTABLISHMENT budget (TCP/UNIX connect + TLS handshake +
# auth), split from the solve/read budget: a dead sidecar must fail a
# degraded tick in ~1s, not eat the whole 30s solve budget per call
DEFAULT_CONNECT_TIMEOUT = 1.0


def default_socket_path() -> str:
    """Default sidecar socket location (PURE -- no filesystem side
    effects; callers that will bind/connect run ensure_socket_dir).
    Without XDG_RUNTIME_DIR the fallback is a PER-USER directory, never
    bare /tmp: a predictable world-writable path invites local socket
    squatting (an attacker pre-binds it and serves forged decisions)."""
    base = os.environ.get("XDG_RUNTIME_DIR") or f"/tmp/karpenter-tpu-{os.getuid()}"
    return os.path.join(base, "karpenter-tpu-solver.sock")


def ensure_socket_dir(path: str) -> None:
    """Create the socket's parent as mode 0700 and enforce ownership
    loudly: chmod on another user's squatted directory raises EPERM
    instead of silently trusting it."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, mode=0o700, exist_ok=True)
    if parent not in ("/tmp", "/run", "."):
        os.chmod(parent, 0o700)

_LEN = struct.Struct("<I")
MAX_FRAME = 256 * 1024 * 1024


# -- framing -----------------------------------------------------------------

def _send_frame(sock: socket.socket, header: dict, tensors: Sequence[Tuple[str, np.ndarray]] = ()) -> None:
    failpoints.eval("rpc.send")
    header = dict(header)
    header["tensors"] = [
        {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)} for name, a in tensors
    ]
    payload = [np.ascontiguousarray(a).tobytes() for _, a in tensors]
    if payload:
        # payload integrity: one crc32 over the concatenated tensor bytes.
        # A flipped bit in a decision tensor would otherwise decode into a
        # silently WRONG placement; with the checksum it surfaces as a
        # ConnectionError and the caller degrades through the ladder to a
        # recomputed (correct) decision. Old peers ignore the extra header
        # field; frames from old peers simply skip the check.
        crc = 0
        for p in payload:
            crc = zlib.crc32(p, crc)
        header["crc"] = crc
    hb = json.dumps(header).encode()
    data = b"".join([_LEN.pack(len(hb)), hb] + payload)
    # chaos site: deterministic single-byte corruption past the length
    # prefix (failpoints.py); the receiver's JSON/CRC checks must detect it
    data = failpoints.corrupt("rpc.frame.corrupt", data)
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(
    sock: socket.socket, limit: int = MAX_FRAME
) -> Tuple[dict, Dict[str, np.ndarray]]:
    failpoints.eval("rpc.recv")
    (hlen,) = _LEN.unpack(_recv_exact(sock, 4))
    if hlen > limit:
        raise ConnectionError(f"oversized header ({hlen} bytes)")
    # a corrupted frame must surface as a CONNECTION error, not a stray
    # JSONDecodeError/TypeError escaping into the solve: the stream is
    # desynchronized either way, and ConnectionError is what every caller
    # (reconnect ladders, the breaker) already handles
    try:
        header = json.loads(_recv_exact(sock, hlen))
        if not isinstance(header, dict):
            raise ValueError("frame header is not an object")
    except ValueError as e:
        raise ConnectionError(f"corrupt frame header: {e}") from None
    tensors: Dict[str, np.ndarray] = {}
    total = 0
    crc = 0
    try:
        for spec in header.get("tensors", ()):
            dtype = np.dtype(spec["dtype"])
            shape = [int(s) for s in spec["shape"]]
            if any(s < 0 for s in shape):
                raise ConnectionError(f"negative dimension in {spec}")
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            nbytes = count * dtype.itemsize
            total += nbytes
            # bound the payload BEFORE allocating: a hostile header must not be
            # able to make the sidecar allocate unbounded buffers
            if nbytes > limit or total > limit:
                raise ConnectionError(f"oversized tensor payload ({total} bytes)")
            raw = _recv_exact(sock, nbytes)
            crc = zlib.crc32(raw, crc)
            tensors[spec["name"]] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    except (TypeError, ValueError, KeyError) as e:
        raise ConnectionError(f"corrupt tensor spec: {e}") from None
    want = header.get("crc")
    if want is not None and tensors and crc != int(want):
        raise ConnectionError("frame payload crc mismatch")
    return header, tensors


# -- server ------------------------------------------------------------------

class _StagedEntry:
    def __init__(self, staged, offsets, words):
        self.staged = staged
        self.offsets = offsets
        self.words = words


class SolverServer:
    """Serves auth/stage/solve/ping over persistent connections. One staged
    catalog per seqnum (bounded LRU of 4: catalogs change 12-hourly).

    Transports: `path` -> UNIX domain socket (mode 0600, the default
    deployment); `host`/`port` -> TCP, which REQUIRES a shared token
    unless `insecure_tcp=True`; `ssl_context` optionally wraps accepted
    TCP connections in TLS."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, *,
        path: Optional[str] = None, token: Optional[str] = None,
        insecure_tcp: bool = False, ssl_context=None,
        handshake_timeout: float = 30.0,
    ):
        self._staged: Dict[str, _StagedEntry] = {}
        # class-tensor epochs (solve_delta): epoch id -> {name: np array},
        # the full class tensor set as of that epoch, patched row-wise by
        # delta solves. Same bounded-LRU discipline as the catalog staging.
        self._epochs: Dict[str, Dict[str, np.ndarray]] = {}
        # eviction accounting (the LRUs used to evict silently): mirrored
        # into karpenter_solver_staged_evictions_total and served by the
        # "debug" op for the true sidecar topology
        self._evictions = {"catalog": 0, "class_epoch": 0}
        self._lock = threading.Lock()
        # TLS-handshake budget (was a hardcoded 30s): a peer stalling the
        # handshake holds one daemon thread, never the accept loop, but the
        # bound should still be an operator decision
        self._handshake_timeout = handshake_timeout
        self._token = token if token is not None else os.environ.get(TOKEN_ENV)
        # an empty token is UNSET, not a guessable one-value secret: it
        # must neither satisfy the TCP guard nor be compared against
        if not self._token:
            self._token = None
        if path is None and self._token is None and not insecure_tcp:
            raise ValueError(
                "a TCP solver listener requires a shared token (token= or "
                f"${TOKEN_ENV}); pass insecure_tcp=True only as an explicit "
                "operator decision, or use a UNIX socket (path=)"
            )
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # per-connection auth state: with a token configured, the
                # FIRST frame must be a valid auth op; anything else closes
                # the connection (no op is dispatched unauthenticated).
                # Pre-auth frames are capped at 4 KB -- an unauthenticated
                # peer must not be able to force MAX_FRAME allocations.
                authed = outer._token is None
                try:
                    if ssl_context is not None:
                        # handshake in THIS per-connection thread, never in
                        # the accept loop (a stalled handshake must not
                        # wedge the server), and bounded by a timeout
                        self.request.settimeout(outer._handshake_timeout)
                        self.request = ssl_context.wrap_socket(
                            self.request, server_side=True
                        )
                        self.request.settimeout(None)
                    while True:
                        # chaos site: a connection-drop here closes the
                        # stream mid-conversation (the handler's except
                        # path), the wedge/kill shapes the chaos soak arms
                        failpoints.eval("rpc.server.conn")
                        header, tensors = _recv_frame(
                            self.request,
                            limit=MAX_FRAME if authed else 4096,
                        )
                        op = header.get("op")
                        if op == "auth":
                            supplied = str(header.get("token", ""))
                            if outer._token is None or hmac.compare_digest(
                                supplied, outer._token
                            ):
                                authed = True
                                _send_frame(self.request, {"ok": True})
                                continue
                            _send_frame(
                                self.request, {"ok": False, "error": "unauthenticated"}
                            )
                            return
                        if not authed:
                            _send_frame(
                                self.request, {"ok": False, "error": "unauthenticated"}
                            )
                            return
                        outer._dispatch(self.request, header, tensors)
                except (ConnectionError, OSError, ValueError):
                    return

        if path is not None:
            class Server(socketserver.ThreadingUnixStreamServer):
                daemon_threads = True

            try:
                os.unlink(path)
            except OSError:
                pass
            # bind under a restrictive umask: chmod-after-bind leaves a
            # window where any local user could connect and keep the
            # (tokenless) connection past the chmod
            old_umask = os.umask(0o177)
            try:
                self._server = Server(path, Handler)
            finally:
                os.umask(old_umask)
            os.chmod(path, 0o600)
            self.address = path
            self.path = path
        else:
            class Server(socketserver.ThreadingTCPServer):
                allow_reuse_address = True
                daemon_threads = True

            self._server = Server((host, port), Handler)
            self.address = self._server.server_address
            self.path = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SolverServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, sock, header: dict, tensors: Dict[str, np.ndarray]) -> None:
        op = header.get("op")
        # trace propagation (tracing.py): a request carrying a "trace"
        # context gets its server-side stages timed and ECHOED in the
        # reply header, so the client can graft them into the dispatching
        # tick's span tree; untraced requests pay nothing and the reply
        # is byte-identical to the pre-tracing protocol
        wt = tracing.WireTrace(header.get("trace"))
        try:
            # chaos site INSIDE the try: an injected error crosses the wire
            # as an error frame (an erroring solver); injected latency
            # models a wedged solver holding the reply
            failpoints.eval("rpc.server.dispatch")
            if op == "ping":
                # features lets a NEWER client decide whether semantics it
                # depends on exist server-side: an older server omits the
                # field (or errors on a future op), and the client falls
                # back -- e.g. taint-gated merged batches to the oracle
                # (service._try_solve_merged) rather than silently packing
                # without the join_allowed gate
                _send_frame(
                    sock,
                    {"ok": True, "features": ["join_allowed", "trace_echo", "solve_delta"]},
                )
            elif op == "stage":
                self._op_stage(sock, header, tensors)
            elif op == "solve":
                self._op_solve(sock, header, tensors, wt)
            elif op == "solve_compact":
                self._op_solve_compact(sock, header, tensors, wt)
            elif op == "solve_delta":
                self._op_solve_delta(sock, header, tensors, wt)
            elif op == "debug":
                self._op_debug(sock)
            else:
                _send_frame(sock, {"ok": False, "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 -- errors cross the wire
            _send_frame(sock, {"ok": False, "error": f"{type(e).__name__}: {e}"})

    def _op_stage(self, sock, header: dict, t: Dict[str, np.ndarray]) -> None:
        seqnum = str(header["seqnum"])
        words = tuple(int(w) for w in header["words"])
        catalog = encode.CatalogTensors(
            names=list(header["names"]), k_real=int(header["k_real"]),
            k_pad=int(t["cap"].shape[0]), cap=t["cap"], tcode=t["tcode"],
            tnum=t["tnum"], tnum_present=t["tnum_present"], tzone=t["tzone"],
            tcap=t["tcap"], price=t["price"], vocabs=[], zones=list(header["zones"]),
            words=list(words),
        )
        staged, offsets, words = ffd.stage_catalog(catalog)
        with self._lock:
            if len(self._staged) >= 4 and seqnum not in self._staged:
                self._staged.pop(next(iter(self._staged)))
                self._evictions["catalog"] += 1
                metrics.SOLVER_STAGED_EVICTIONS.inc(kind="catalog")
            self._staged[seqnum] = _StagedEntry(staged, offsets, words)
        _send_frame(sock, {"ok": True, "seqnum": seqnum})

    def _op_debug(self, sock) -> None:
        """Staging observability: what the LRUs hold and how often they
        evicted (the /debug/solver endpoint surfaces this in-process; this
        op serves the true sidecar topology where the server's counters
        live in another process)."""
        with self._lock:
            doc = {
                "ok": True,
                "staged_seqnums": list(self._staged),
                "class_epochs": list(self._epochs),
                "evictions": dict(self._evictions),
            }
        _send_frame(sock, doc)

    def _op_solve_delta(self, sock, header: dict, t: Dict[str, np.ndarray],
                        wt: Optional[tracing.WireTrace] = None) -> None:
        """Compact solve whose class tensors are staged server-side under a
        class-EPOCH id, the per-tick analogue of the per-seqnum catalog
        staging. base=None ships the full tensor set and establishes the
        epoch; base=<epoch> ships only the dirty rows (header "rows") and
        patches a copy of the base epoch. An unknown base is an
        "unknown-epoch" error -- the client full-restages, mirroring the
        unknown-seqnum contract -- so sync, pipelined, and breaker-open
        paths all stay bit-identical to a full encode."""
        # catalog gap first: a restarted sidecar lost BOTH stagings, and
        # reporting the seqnum gap lets the client restage catalog + epoch
        # in one ladder pass instead of two error roundtrips
        with self._lock:
            known = str(header["seqnum"]) in self._staged
        if not known:
            _send_frame(sock, {"ok": False, "error": "unknown-seqnum"})
            return
        full = self._resolve_epoch(sock, header, t)
        if full is None:
            return
        self._op_solve_compact(sock, header, full, wt)

    def _resolve_epoch(self, sock, header: dict, t: Dict[str, np.ndarray]):
        """The full class tensor dict for this solve_delta request, staged
        under header["epoch"], or None after sending the unknown-epoch
        error. Patching happens on a private copy outside the lock; the
        stored epoch dicts are never mutated in place (a concurrent solve
        reading a base must see a consistent snapshot)."""
        epoch = str(header["epoch"])
        base = header.get("base")
        ent = None
        if base is not None:
            with self._lock:
                ent = self._epochs.get(str(base))
                if ent is not None:
                    # LRU touch, same discipline as the catalog staging
                    self._epochs.pop(str(base))
                    self._epochs[str(base)] = ent
            if ent is None:
                _send_frame(sock, {"ok": False, "error": "unknown-epoch"})
                return None
            full = {name: arr.copy() for name, arr in ent.items()}
            rows = np.asarray([int(r) for r in header.get("rows", ())], dtype=np.int64)
            for name, arr in t.items():
                if name not in PER_CLASS_TENSORS:
                    full[name] = np.array(arr)  # whole-set tensors replace
                elif rows.size:
                    full[name][rows] = arr
        else:
            # frombuffer tensors are read-only views over the frame; own
            # writable copies so later deltas can patch them
            full = {name: np.array(arr) for name, arr in t.items()}
        with self._lock:
            if base is not None:
                # the patched base is superseded: each client chain diffs
                # against its LAST acknowledged epoch, so the base can be
                # referenced at most by a rare error-recovery resend (which
                # the unknown-epoch ladder absorbs). Consuming it here
                # keeps the LRU at one epoch per live chain and makes the
                # eviction counter mean PRESSURE, not routine supersession.
                self._epochs.pop(str(base), None)
            self._epochs[epoch] = full
            while len(self._epochs) > 4:
                self._epochs.pop(next(iter(self._epochs)))
                self._evictions["class_epoch"] += 1
                metrics.SOLVER_STAGED_EVICTIONS.inc(kind="class_epoch")
        return full

    def _staged_inputs(self, sock, header: dict, t: Dict[str, np.ndarray]):
        """(entry, SolveInputs) for the staged catalog named by the header's
        seqnum (LRU-touched), or None after sending the unknown-seqnum error
        (the client re-stages on that contract)."""
        seqnum = str(header["seqnum"])
        with self._lock:
            entry = self._staged.get(seqnum)
            if entry is not None:
                # LRU touch: re-insert so eviction pops the least recently
                # USED catalog, not the oldest staged
                self._staged.pop(seqnum)
                self._staged[seqnum] = entry
        if entry is None:
            _send_frame(sock, {"ok": False, "error": "unknown-seqnum"})
            return None
        inp = ffd.SolveInputs(
            cap=entry.staged.cap, tcode=entry.staged.tcode, tnum=entry.staged.tnum,
            tnum_present=entry.staged.tnum_present, tzone=entry.staged.tzone,
            tcap=entry.staged.tcap, price=entry.staged.price,
            req=t["req"], count=t["count"], env_count=t["env_count"],
            allowed=t["allowed"], num_lo=t["num_lo"], num_hi=t["num_hi"],
            azone=t["azone"], acap=t["acap"], schedulable=t["schedulable"],
            # older clients do not send the per-node daemonset reserve;
            # zeros preserves their semantics exactly
            node_overhead=t.get(
                "node_overhead", np.zeros((t["req"].shape[1],), dtype=np.float32)
            ),
            # ones preserves pre-multipool clients: open anywhere compat allows
            open_allowed=t.get(
                "open_allowed",
                np.ones((t["req"].shape[0], entry.staged.cap.shape[0]), dtype=bool),
            ),
            # ones preserves clients without per-pool-taints gating
            join_allowed=t.get(
                "join_allowed",
                np.ones((t["req"].shape[0], entry.staged.cap.shape[0]), dtype=bool),
            ),
        )
        return entry, inp

    def _op_solve(self, sock, header: dict, t: Dict[str, np.ndarray],
                  wt: Optional[tracing.WireTrace] = None) -> None:
        import jax

        wt = wt or tracing.WireTrace(None)
        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        with wt.stage("device", op="solve"):
            out = ffd.ffd_solve(
                inp, g_max=int(header["g_max"]),
                word_offsets=entry.offsets, words=entry.words,
                objective=str(header.get("objective", "price")),
            )
            if wt.ctx is not None:
                # jit dispatch is ASYNC: without a barrier the XLA compute
                # would block inside device_get and the echo would claim
                # device~=0, fetch=everything. Traced requests sync here so
                # the stages attribute honestly; untraced requests keep
                # the overlapped dispatch->fetch path untouched.
                jax.block_until_ready(out)
        with wt.stage("fetch"):
            arrays = jax.device_get(tuple(out))
        names = ffd.SolveOutputs._fields
        _send_frame(
            sock, {"ok": True, **wt.echo()},
            [(n, np.asarray(a)) for n, a in zip(names, arrays)],
        )

    def _op_solve_compact(self, sock, header: dict, t: Dict[str, np.ndarray],
                          wt: Optional[tracing.WireTrace] = None) -> None:
        """The wire-efficient solve: the decision returns as a
        CompactDecision (~50 KB) instead of the dense SolveOutputs
        (~1.5 MB) -- this boundary exists for the TPU-VM topology where the
        link is exactly the bandwidth-poor hop the compact layout is for."""
        import jax

        wt = wt or tracing.WireTrace(None)
        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        with wt.stage("device", op="solve_compact"):
            dec = ffd.ffd_solve_compact(
                inp, g_max=int(header["g_max"]), nnz_max=int(header["nnz_max"]),
                word_offsets=entry.offsets, words=entry.words,
                objective=str(header.get("objective", "price")),
            )
            if wt.ctx is not None:
                # see _op_solve: sync traced requests so XLA compute lands
                # in "device", not "fetch"
                jax.block_until_ready(dec)
        with wt.stage("fetch"):
            arrays = jax.device_get(tuple(dec))
        names = ffd.CompactDecision._fields
        _send_frame(
            sock, {"ok": True, **wt.echo()},
            [(n, np.atleast_1d(np.asarray(a))) for n, a in zip(names, arrays)],
        )


# -- client ------------------------------------------------------------------

class StaleSeqnumError(RuntimeError):
    """The sidecar does not know the staged-catalog seqnum an ASYNC solve
    named: it restarted or evicted the catalog while the request was in
    flight. The pipelined path surfaces this instead of silently
    re-staging (a restage cannot be spliced in front of a frame that has
    already streamed); the caller decides -- TPUSolver._finish_remote
    falls back to the synchronous op, which restages and retries."""


class StaleEpochError(StaleSeqnumError):
    """The class-epoch analogue of StaleSeqnumError: the sidecar no longer
    knows the base epoch a pipelined DELTA solve patched against (restart,
    or LRU eviction of the epoch). Subclasses StaleSeqnumError so every
    existing ladder that handles a mid-flight staging gap handles this one
    identically: the synchronous retry full-restages the class tensors
    (the client dropped its base on this error)."""


class _PendingReply:
    """One in-flight request's reply slot. `outcome` is filled by the FIFO
    drain: ("ok", header, tensors) or ("err", exception). `seqnum` names
    the staged catalog the request referenced -- the claim side drops the
    matching delta base on staging-gap errors."""

    __slots__ = ("outcome", "seqnum")

    def __init__(self, seqnum: str = ""):
        self.outcome = None
        self.seqnum = seqnum


class SolverClient:
    """Drop-in backend for TPUSolver-shaped solves over the wire. Maintains
    one persistent connection; `solve_classes` mirrors the tensor half of
    TPUSolver.solve (the caller does host-side encode/decode)."""

    def __init__(
        self, host: Optional[str] = None, port: Optional[int] = None,
        timeout: float = 30.0, *, path: Optional[str] = None,
        token: Optional[str] = None, ssl_context=None,
        server_hostname: Optional[str] = None,
        connect_timeout: float = DEFAULT_CONNECT_TIMEOUT,
        delta: Optional[bool] = None,
    ):
        self.addr = (host, port) if path is None else None
        self.path = path
        # timeout = the per-solve READ budget; connect_timeout bounds
        # connection establishment (connect + TLS + auth). They were one
        # knob before, which made a dead sidecar cost the full solve
        # budget per reconnect attempt instead of ~1s.
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        self.token = (token if token is not None else os.environ.get(TOKEN_ENV)) or None
        self._ssl_context = ssl_context
        self._server_hostname = server_hostname or (host if host else None)
        self._sock: Optional[socket.socket] = None
        self._staged_seqnums: set = set()
        self._features: Optional[frozenset] = None  # per-connection, lazy
        # delta class shipping (the incremental-tick wire layer): when the
        # server advertises solve_delta, compact solves stage the class
        # tensors under a class-epoch id and subsequent solves ship only
        # the dirty rows. Default on; delta=False or $KARPENTER_TPU_DELTA=0
        # forces the full ship (the two are bit-identical by construction
        # -- the server reassembles the same tensors either way).
        if delta is None:
            delta = os.environ.get(DELTA_ENV, "1") != "0"
        self.delta = bool(delta)
        # seqnum -> (epoch id, {name: array copy}): the last class tensor
        # state the server is known to hold for that catalog. Bounded LRU;
        # dropped eagerly on close() and on any staging-gap error.
        self._epoch_bases: Dict[str, tuple] = {}
        import uuid as _uuid

        self._epoch_prefix = _uuid.uuid4().hex[:12]
        self._epoch_counter = 0
        # shipping observability for the LAST solve dispatched (read by
        # the solver's metrics/span wiring and the bench's delta stage)
        self.last_delta = {"mode": "bypass", "rows": -1, "payload_bytes": 0, "full_bytes": 0}
        # one reentrant lock serializes the socket AND the staging set: the
        # protocol is strictly request/response on one connection, so a
        # whole roundtrip (and the stage-then-solve sequence inside
        # solve_classes) must be atomic across threads
        self._lock = threading.RLock()
        # request-pipelining FIFO (begin_solve_compact): replies come back
        # in request order on the one stream, so each dispatched frame's
        # reply slot queues here until a drain claims it
        from collections import deque

        self._pending: "deque[_PendingReply]" = deque()
        # one solve computing + one frame streaming behind it -- the depth
        # at which the RTT fully overlaps compute; anything deeper only
        # buffers latency (and decisions) without adding overlap
        self.MAX_INFLIGHT = 2

    def _conn(self) -> socket.socket:
        if self._sock is None:
            failpoints.eval("rpc.client.connect")
            # the WHOLE establishment sequence (connect, TLS handshake,
            # auth roundtrip) runs under connect_timeout; only then does
            # the socket get the long per-solve read budget
            if self.path is not None:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self.connect_timeout)
                sock.connect(self.path)
            else:
                sock = socket.create_connection(self.addr, timeout=self.connect_timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self._ssl_context is not None:
                    sock = self._ssl_context.wrap_socket(
                        sock, server_hostname=self._server_hostname
                    )
            self._sock = sock
            self._staged_seqnums.clear()
            try:
                if self.token:
                    # prove the shared token before any op (the server closes
                    # unauthenticated connections on the first non-auth frame)
                    _send_frame(sock, {"op": "auth", "token": self.token})
                    header, _ = _recv_frame(sock)
                    if not header.get("ok"):
                        raise ConnectionError("solver auth rejected")
            except (ConnectionError, OSError):
                sock.close()
                self._sock = None
                raise
            sock.settimeout(self.timeout)
        return self._sock

    def close(self) -> None:
        with self._lock:
            # replies can no longer arrive on this stream: fail their slots
            # so a later finish_solve_compact raises instead of hanging
            for h in self._pending:
                if h.outcome is None:
                    h.outcome = ("err", ConnectionError("connection closed with reply in flight"))
            self._pending.clear()
            if self._sock is not None:
                self._sock.close()
                self._sock = None
            self._features = None  # the replacement server may differ
            # eager, not on-reconnect: between close() and the next _conn()
            # a begin_solve_compact checks membership BEFORE connecting, and
            # a stale hit would skip the re-stage the replacement sidecar
            # needs (the breaker's promotion hook relies on this to gate
            # re-promotion on a catalog re-stage)
            self._staged_seqnums.clear()
            # delta bases die with the connection for the same reason: the
            # replacement sidecar holds no epochs, and a stale base would
            # cost one unknown-epoch roundtrip per seqnum before recovering
            self._epoch_bases.clear()

    # -- request pipelining (the async solve path) ---------------------------
    def _drain_pending(self, target: Optional[_PendingReply] = None) -> None:
        """Receive outstanding replies in FIFO order (all of them, or up to
        and including `target`). MUST run before any synchronous roundtrip
        so a pipelined reply is never misattributed to a later request.
        Caller holds the lock."""
        while self._pending:
            head = self._pending[0]
            if head.outcome is None:
                try:
                    header, tensors = _recv_frame(self._sock)
                    head.outcome = ("ok", header, tensors)
                except (ConnectionError, OSError) as e:
                    # the stream is unrecoverable mid-pipeline: every
                    # outstanding reply is lost with it
                    for h in self._pending:
                        if h.outcome is None:
                            h.outcome = ("err", e)
                    self._pending.clear()
                    self.close()
                    return
            done = self._pending.popleft()
            if target is not None and done is target:
                return

    def begin_solve_compact(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, nnz_max: int = 0, objective: str = "price",
    ) -> _PendingReply:
        """Dispatch a compact solve WITHOUT waiting for the reply: the
        request frame streams to the sidecar while it may still be
        computing a prior in-flight solve (request pipelining on the
        strict request/response framing -- replies return in request
        order). At most MAX_INFLIGHT (2: one computing, one streaming)
        may be outstanding; a deeper dispatch raises rather than silently
        buffering stale decisions. Claim the reply with
        finish_solve_compact. Unlike the synchronous op, an unknown
        seqnum surfaces as StaleSeqnumError -- no silent restage."""
        if not nnz_max:
            nnz_max = ffd.nnz_budget(class_set.c_pad, g_max)
        header = {
            "op": "solve_compact", "seqnum": seqnum, "g_max": g_max,
            "nnz_max": nnz_max, "objective": objective,
        }
        # trace-id propagation: the DISPATCHING tick's context rides the
        # request header; the server echoes it (plus its stage timings)
        # in the reply, so the claim side can graft the stages even when
        # the reply is drained a tick later under a different trace
        ctx = tracing.TRACER.inject()
        if ctx is not None:
            header["trace"] = ctx
        with self._lock:
            if len(self._pending) >= self.MAX_INFLIGHT:
                raise RuntimeError(
                    f"solve pipeline full: {len(self._pending)} requests already in flight"
                )
            if seqnum not in self._staged_seqnums:
                # staging is a synchronous roundtrip: the pipe must be
                # clear first or the stage reply would interleave
                self._drain_pending()
                self.stage_catalog(seqnum, catalog)
            # delta class shipping: may rewrite the header into a
            # solve_delta op and return only the dirty rows (feature-gated;
            # full ship otherwise -- the server reassembles identically)
            tensors = self._delta_request(seqnum, class_set, header)
            sock = self._conn()
            try:
                _send_frame(sock, header, tensors)
            except (ConnectionError, OSError):
                # a PARTIAL frame may be on the wire: the stream is
                # desynchronized, and a later synchronous fallback would
                # write its frame into the torn one's remainder -- close
                # so that fallback reconnects onto a clean stream
                self.close()
                raise
            handle = _PendingReply(seqnum)
            self._pending.append(handle)
            return handle

    def finish_solve_compact(self, handle: _PendingReply) -> ffd.CompactDecision:
        """Claim a begin_solve_compact reply (blocking until it arrives).
        Raises StaleSeqnumError on unknown-seqnum, ConnectionError when
        the stream died with the reply in flight."""
        with self._lock:
            if handle.outcome is None:
                self._drain_pending(target=handle)
            if handle.outcome is None:
                raise ConnectionError("reply lost: not in the pipeline FIFO")
        kind, *rest = handle.outcome
        if kind == "err":
            raise rest[0]
        header, out = rest
        if not header.get("ok"):
            err = str(header.get("error", ""))
            if err == "unknown-epoch":
                # the sidecar lost the base epoch mid-flight: drop the
                # client base so the synchronous retry ships full, and
                # surface the gap on the StaleSeqnumError contract
                self._drop_epoch(handle.seqnum)
                metrics.DELTA_EPOCH_RESTAGES.inc()
                raise StaleEpochError(err)
            if err == "unknown-seqnum":
                self._drop_epoch(handle.seqnum)
                raise StaleSeqnumError(err)
            raise RuntimeError(f"solve failed: {err}")
        # graft the echoed server-side stage spans under the span covering
        # this claim (the solver's "wire" span); the echo's trace context
        # links back to the dispatching tick when that differs
        tracing.TRACER.graft(header)
        fields = {n: out[n] for n in ffd.CompactDecision._fields}
        fields["nnz"] = fields["nnz"].reshape(())
        fields["n_open"] = fields["n_open"].reshape(())
        return ffd.CompactDecision(**fields)

    def features(self) -> frozenset:
        """Server feature set, probed once per connection via ping (an
        older server omits the field -> empty set). Callers that DEPEND on
        a semantic the server may lack check here and fall back -- e.g.
        taint-gated merged batches go to the oracle when 'join_allowed' is
        absent, because an old server would silently drop the mask and
        pack pods into pools whose taints they do not tolerate."""
        with self._lock:
            if self._features is None:
                header, _ = self._roundtrip({"op": "ping"})
                self._features = frozenset(header.get("features", ()))
            return self._features

    def _roundtrip(self, header, tensors=()):
        with self._lock:
            # pipelined replies still on the stream MUST drain first, or
            # this request would read an earlier solve's reply as its own
            self._drain_pending()
            sock = self._conn()
            try:
                _send_frame(sock, header, tensors)
                return _recv_frame(sock)
            except (ConnectionError, OSError):
                self.close()  # one reconnect attempt per call
                sock = self._conn()
                _send_frame(sock, header, tensors)
                return _recv_frame(sock)

    def ping(self) -> bool:
        header, _ = self._roundtrip({"op": "ping"})
        return bool(header.get("ok"))

    def stage_catalog(self, seqnum: str, catalog: encode.CatalogTensors) -> None:
        header = {
            "op": "stage", "seqnum": seqnum, "names": catalog.names,
            "k_real": catalog.k_real, "zones": catalog.zones, "words": catalog.words,
        }
        tensors = [
            ("cap", catalog.cap), ("tcode", catalog.tcode), ("tnum", catalog.tnum),
            ("tnum_present", catalog.tnum_present), ("tzone", catalog.tzone),
            ("tcap", catalog.tcap), ("price", catalog.price),
        ]
        resp, _ = self._roundtrip(header, tensors)
        if not resp.get("ok"):
            raise RuntimeError(f"stage failed: {resp.get('error')}")
        with self._lock:
            self._staged_seqnums.add(seqnum)

    @staticmethod
    def _class_tensors(class_set: encode.PodClassSet):
        """The pod-class tensor list both solve ops ship (ONE copy: a new
        class tensor must appear here or the dense and compact paths
        desynchronize)."""
        return [
            ("req", class_set.req), ("count", class_set.count),
            ("env_count", class_set.env_count),
            ("allowed", np.concatenate(class_set.allowed, axis=1)),
            ("num_lo", class_set.num_lo), ("num_hi", class_set.num_hi),
            ("azone", class_set.azone), ("acap", class_set.acap),
            ("schedulable", class_set.schedulable),
            ("node_overhead", class_set.node_overhead),
        ] + (
            [("open_allowed", class_set.open_allowed)]
            if getattr(class_set, "open_allowed", None) is not None else []
        ) + (
            [("join_allowed", class_set.join_allowed)]
            if getattr(class_set, "join_allowed", None) is not None else []
        )

    # -- delta class shipping (the incremental-tick wire layer) ---------------
    def _next_epoch(self) -> str:
        self._epoch_counter += 1
        return f"{self._epoch_prefix}-{self._epoch_counter}"

    def _drop_epoch(self, seqnum: str) -> None:
        with self._lock:
            self._epoch_bases.pop(seqnum, None)

    def _store_base(self, seqnum: str, epoch: str, named: Dict[str, np.ndarray]) -> None:
        """Record the class tensor state the server now holds for this
        seqnum (one copy per tensor: the caller's arrays belong to a live
        PodClassSet). Caller holds the lock."""
        self._epoch_bases.pop(seqnum, None)  # LRU refresh
        self._epoch_bases[seqnum] = (
            epoch, {n: np.array(a) for n, a in named.items()}
        )
        while len(self._epoch_bases) > 4:
            self._epoch_bases.pop(next(iter(self._epoch_bases)))

    def _patch_base(self, seqnum: str, epoch: str, b: Dict[str, np.ndarray],
                    rows: np.ndarray, named: Dict[str, np.ndarray]) -> None:
        """Advance a delta chain's stored base IN PLACE: O(dirty rows)
        host work per tick, like everything else in the engine -- a full
        re-copy here would spend memory bandwidth on exactly the bytes
        the delta ship avoids. Caller holds the lock; `b` is this
        client's private copy (never aliased into a frame)."""
        if rows.size:
            for name in PER_CLASS_TENSORS:
                b[name][rows] = named[name][rows]
        b["node_overhead"] = np.array(named["node_overhead"])
        self._epoch_bases.pop(seqnum, None)  # LRU refresh
        self._epoch_bases[seqnum] = (epoch, b)

    def _bypass_delta(self, full_bytes: int):
        self.last_delta = {
            "mode": "bypass", "rows": -1,
            "payload_bytes": full_bytes, "full_bytes": full_bytes,
        }
        metrics.DELTA_SOLVES.inc(mode="bypass")
        metrics.DELTA_PAYLOAD_BYTES.observe(full_bytes, mode="bypass")

    def _delta_request(self, seqnum: str, class_set: encode.PodClassSet, header: dict):
        """The tensors to ship for one compact solve, rewriting `header`
        into a solve_delta op when the delta path applies. Three modes
        (last_delta["mode"], mirrored into karpenter_scheduler_delta_*):

        - "delta": a base epoch for this seqnum exists with matching
          shapes and few rows changed -- ship only the dirty rows plus
          the epoch being patched;
        - "full": ship everything, establishing a new epoch server-side
          (the steady state's first tick, a shape change, or a high-churn
          tick past DELTA_MAX_DIRTY_FRACTION);
        - "bypass": delta not applicable (disabled, dense op, server
          without the feature, or merged-multipool masks present).

        The server reassembles the identical tensor set in every mode, so
        the decision is bit-identical by construction (tests/test_delta.py
        asserts it differentially). Caller holds the lock."""
        tensors = self._class_tensors(class_set)
        full_bytes = int(sum(a.nbytes for _, a in tensors))
        if not self.delta or header.get("op") != "solve_compact":
            self._bypass_delta(full_bytes)
            return tensors
        named = dict(tensors)
        if "open_allowed" in named or "join_allowed" in named:
            # merged multi-pool: the [C, K] masks dominate the payload and
            # are re-derived per tick -- the delta path stands down
            self._bypass_delta(full_bytes)
            return tensors
        try:
            if "solve_delta" not in self.features():
                self._bypass_delta(full_bytes)
                return tensors
        except (ConnectionError, OSError):
            # let the solve's own send surface the connection state
            self._bypass_delta(full_bytes)
            return tensors
        epoch = self._next_epoch()
        base = self._epoch_bases.get(seqnum)
        if base is not None:
            b = base[1]
            if set(b) == set(named) and all(
                b[n].shape == named[n].shape and b[n].dtype == named[n].dtype
                for n in named
            ):
                changed = np.zeros((named["req"].shape[0],), dtype=bool)
                for name in PER_CLASS_TENSORS:
                    diff = named[name] != b[name]
                    if diff.ndim > 1:
                        diff = diff.any(axis=tuple(range(1, diff.ndim)))
                    changed |= diff
                rows = np.nonzero(changed)[0]
                if rows.size <= int(changed.size * DELTA_MAX_DIRTY_FRACTION):
                    header["op"] = "solve_delta"
                    header["epoch"] = epoch
                    header["base"] = base[0]
                    header["rows"] = [int(r) for r in rows]
                    out = [
                        (name, np.ascontiguousarray(named[name][rows]))
                        for name in PER_CLASS_TENSORS
                    ]
                    # whole-set tensors always ship (tiny [R] vector)
                    out.append(("node_overhead", named["node_overhead"]))
                    self._patch_base(seqnum, epoch, b, rows, named)
                    payload = int(sum(a.nbytes for _, a in out))
                    self.last_delta = {
                        "mode": "delta", "rows": int(rows.size),
                        "payload_bytes": payload, "full_bytes": full_bytes,
                    }
                    metrics.DELTA_SOLVES.inc(mode="delta")
                    metrics.DELTA_ROWS_SHIPPED.inc(int(rows.size))
                    metrics.DELTA_PAYLOAD_BYTES.observe(payload, mode="delta")
                    return out
        # full ship, establishing the epoch the next tick patches
        header["op"] = "solve_delta"
        header["epoch"] = epoch
        header["base"] = None
        self._store_base(seqnum, epoch, named)
        self.last_delta = {
            "mode": "full", "rows": int(class_set.c_pad),
            "payload_bytes": full_bytes, "full_bytes": full_bytes,
        }
        metrics.DELTA_SOLVES.inc(mode="full")
        metrics.DELTA_PAYLOAD_BYTES.observe(full_bytes, mode="full")
        return tensors

    def debug_info(self) -> dict:
        """The server's staging debug document (the "debug" op: staged
        seqnums, class epochs, LRU eviction counts) -- the sidecar-topology
        source for /debug/solver."""
        header, _ = self._roundtrip({"op": "debug"})
        return header

    def _solve_op(self, op_header: dict, seqnum: str, catalog, class_set):
        """Shared stage-if-needed + solve + staging-gap retry ladder:
        unknown-epoch drops the delta base and re-ships full; unknown-
        seqnum re-stages the catalog and retries (the full reship also
        re-establishes the class epoch). Each rung fires at most once."""
        ctx = tracing.TRACER.inject()
        if ctx is not None:
            op_header = dict(op_header, trace=ctx)
        with self._lock:  # atomic stage-then-solve (reentrant)
            if seqnum not in self._staged_seqnums:
                self.stage_catalog(seqnum, catalog)
            header = dict(op_header)
            tensors = self._delta_request(seqnum, class_set, header)
            resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok") and resp.get("error") == "unknown-epoch":
                self._drop_epoch(seqnum)
                metrics.DELTA_EPOCH_RESTAGES.inc()
                header = dict(op_header)
                tensors = self._delta_request(seqnum, class_set, header)
                resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok") and resp.get("error") == "unknown-seqnum":
                # server restarted / evicted: re-stage once and retry with
                # a full class ship (the old epoch died with the staging)
                self._drop_epoch(seqnum)
                self.stage_catalog(seqnum, catalog)
                header = dict(op_header)
                tensors = self._delta_request(seqnum, class_set, header)
                resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok"):
                raise RuntimeError(f"solve failed: {resp.get('error')}")
            tracing.TRACER.graft(resp)
            return out

    def solve_classes(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 512, objective: str = "price",
    ) -> ffd.SolveOutputs:
        header = {"op": "solve", "seqnum": seqnum, "g_max": g_max, "objective": objective}
        out = self._solve_op(header, seqnum, catalog, class_set)
        return ffd.SolveOutputs(**{n: out[n] for n in ffd.SolveOutputs._fields})

    def solve_classes_compact(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, nnz_max: int = 0, objective: str = "price",
    ) -> ffd.CompactDecision:
        """The ~50 KB response variant of solve_classes (the deployed
        TPU-VM topology's hot path); the caller expands with
        ffd.expand_compact and falls back to solve_classes on overflow."""
        if not nnz_max:
            nnz_max = ffd.nnz_budget(class_set.c_pad, g_max)
        header = {
            "op": "solve_compact", "seqnum": seqnum, "g_max": g_max,
            "nnz_max": nnz_max, "objective": objective,
        }
        out = self._solve_op(header, seqnum, catalog, class_set)
        fields = {n: out[n] for n in ffd.CompactDecision._fields}
        # scalars travel as 1-element arrays
        fields["nnz"] = fields["nnz"].reshape(())
        fields["n_open"] = fields["n_open"].reshape(())
        return ffd.CompactDecision(**fields)


def serve_main(argv=None) -> int:
    """`python -m karpenter_tpu.solver.rpc` -- run the solver sidecar (the
    process that lives on the TPU VM). Default transport: a mode-0600 UNIX
    socket. TCP (--host/--port) requires --token-file / $KARPENTER_TPU_
    SOLVER_TOKEN, or the explicit --insecure flag; --tls-cert/--tls-key
    add TLS on top."""
    import argparse

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument(
        "--socket", default=None, metavar="PATH",
        help="UNIX socket path (default: $XDG_RUNTIME_DIR/karpenter-tpu-solver.sock, "
             "or a per-user /tmp dir; ignored when --host is given)",
    )
    parser.add_argument("--host", default=None, help="TCP bind address (requires a token)")
    parser.add_argument("--port", type=int, default=7077)
    parser.add_argument(
        "--token-file", default=None,
        help=f"file holding the shared token (or set ${TOKEN_ENV})",
    )
    parser.add_argument(
        "--insecure", action="store_true",
        help="allow a tokenless TCP listener (explicit operator decision)",
    )
    parser.add_argument("--tls-cert", default=None)
    parser.add_argument("--tls-key", default=None)
    parser.add_argument(
        "--handshake-timeout", type=float, default=30.0,
        help="TLS-handshake budget per connection (seconds)",
    )
    args = parser.parse_args(argv)

    token = None
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    ctx = None
    if args.tls_cert:
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(args.tls_cert, args.tls_key)
    if args.host is not None:
        server = SolverServer(
            args.host, args.port, token=token,
            insecure_tcp=args.insecure, ssl_context=ctx,
            handshake_timeout=args.handshake_timeout,
        ).start()
        print(
            f"solver service listening on {server.address[0]}:{server.address[1]}",
            flush=True,
        )
    else:
        if args.tls_cert or args.tls_key or args.insecure:
            # accepting-and-ignoring a security flag is how plaintext
            # traffic ships with an operator believing it is encrypted
            parser.error("--tls-cert/--tls-key/--insecure apply to TCP mode (--host)")
        path = args.socket or default_socket_path()
        if args.socket:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        else:
            ensure_socket_dir(path)  # squatting defense for the default dir
        server = SolverServer(path=path, token=token).start()
        print(f"solver service listening on {path}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())
