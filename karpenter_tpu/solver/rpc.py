"""Solver service boundary: the decision plane as a network sidecar.

SURVEY.md section 2.4/5 maps the reference's cloud-RPC seam (aws-sdk over
HTTPS with batching) to an RPC boundary between the host-side reconcilers
and the solver process on the TPU VM. This module implements that boundary
as a dependency-free length-prefixed binary protocol (the image ships no
grpc; the frame layout below is trivially portable to gRPC streaming
messages later):

    frame := u32 header_len | header_json | payload_bytes
    header := {"op"|"ok": ..., meta..., "tensors": [{name, dtype, shape}]}
    payload := the tensors' raw little-endian buffers, concatenated

Security posture (round 4, mirroring the reference's HTTPS+SigV4 seams,
`pkg/operator/operator.go:97-98`):

- the DEFAULT transport is a UNIX domain socket (mode 0600) -- filesystem
  permissions are the trust boundary, exactly right for the sidecar
  topology where reconcilers and solver share a pod;
- a TCP listener REQUIRES a shared token (constructor arg or
  KARPENTER_TPU_SOLVER_TOKEN) unless `insecure_tcp=True` is an explicit
  operator decision; the client proves it with an `auth` frame -- the
  FIRST frame on the connection, compared constant-time -- before any
  other op is dispatched;
- TCP can additionally be wrapped in TLS (`ssl_context` on both ends).

Design constraints carried over from the in-process solver (SURVEY.md
section 7 hard part #6 -- the 100 ms budget leaves no room for re-shipping
state): the catalog tensors are staged on the server ONCE per catalog
seqnum (`stage` op); each `solve` ships only the pod-class tensors
(~100 KB at 50k-pod scale) and returns the solve outputs; connections are
persistent (one socket, many solves).

Server-side compute = the same jitted kernels the in-process path uses
(solver/ffd.py), so differential guarantees carry over unchanged.
"""
from __future__ import annotations

import hmac
import json
import os
import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu import failpoints, metrics, overload, tracing
from karpenter_tpu.obs import hbm as obs_hbm
from karpenter_tpu.solver import encode, ffd, packing

TOKEN_ENV = "KARPENTER_TPU_SOLVER_TOKEN"
# kill switch for delta class shipping (solve_delta): the client defaults
# to delta-on whenever the server advertises the feature; "0" forces every
# solve back to the full class-tensor ship
DELTA_ENV = "KARPENTER_TPU_DELTA"
# shared-memory ring transport (solver/shm.py): "0" kills it on either
# side; "1" forces the client to ask even over TCP (colocated-by-config);
# unset, the client asks only on a UNIX-socket transport (the colocated
# sidecar topology the ring exists for)
SHM_ENV = "KARPENTER_TPU_SHM"
# trimmed compact replies (reply_v2): "0" forces the v1 dense reply shape
REPLY_V2_ENV = "KARPENTER_TPU_REPLY_V2"
# consecutive shm-mode stream failures after which a client stops
# re-negotiating the ring and stays on the socket transport (the
# corrupt-shm degrade path: crc failures close the stream; two strikes
# and the segment is considered bad, not the luck)
SHM_MAX_FAILURES = 2

# the per-class tensors delta shipping can patch row-wise. node_overhead
# ([R], whole-set) always ships in full; open_allowed/join_allowed ([C, K]
# merged-multipool masks) bypass the delta path entirely when they ship
# full-width -- bool rows dominate the payload and the merged shape
# re-derives them per tick. BIT-PACKED masks (solver/packing.py, the
# feature-negotiated "packed_masks" wire form) are [C, KW] uint32 rows an
# eighth the size, so they rejoin the row-patch machinery like any other
# per-class tensor (PACKED_MASK_TENSORS below).
PER_CLASS_TENSORS = (
    "req", "count", "env_count", "allowed", "num_lo", "num_hi",
    "azone", "acap", "schedulable",
)
# mask tensors that become row-patchable once packed: only clients that
# negotiated "packed_masks" ship them inside a delta request, so a server
# that advertises the feature is by construction the one patching them
PACKED_MASK_TENSORS = ("open_allowed", "join_allowed")
# kill switch for the packed-mask wire form: "0" ships full-width bool
# masks even to a packed_masks-advertising server
PACKED_MASKS_ENV = "KARPENTER_TPU_PACKED_MASKS"
# never ship a delta when more than this fraction of rows changed: the
# row-index header plus per-row framing overtakes the dense ship
DELTA_MAX_DIRTY_FRACTION = 0.5

# connection ESTABLISHMENT budget (TCP/UNIX connect + TLS handshake +
# auth), split from the solve/read budget: a dead sidecar must fail a
# degraded tick in ~1s, not eat the whole 30s solve budget per call
DEFAULT_CONNECT_TIMEOUT = 1.0


def default_socket_path() -> str:
    """Default sidecar socket location (PURE -- no filesystem side
    effects; callers that will bind/connect run ensure_socket_dir).
    Without XDG_RUNTIME_DIR the fallback is a PER-USER directory, never
    bare /tmp: a predictable world-writable path invites local socket
    squatting (an attacker pre-binds it and serves forged decisions)."""
    base = os.environ.get("XDG_RUNTIME_DIR") or f"/tmp/karpenter-tpu-{os.getuid()}"
    return os.path.join(base, "karpenter-tpu-solver.sock")


def ensure_socket_dir(path: str) -> None:
    """Create the socket's parent as mode 0700 and enforce ownership
    loudly: chmod on another user's squatted directory raises EPERM
    instead of silently trusting it."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, mode=0o700, exist_ok=True)
    if parent not in ("/tmp", "/run", "."):
        os.chmod(parent, 0o700)

_LEN = struct.Struct("<I")
MAX_FRAME = 256 * 1024 * 1024


# -- framing -----------------------------------------------------------------
#
# Round 8 (wire v2): the framing is ZERO-COPY end to end on the hot path.
# Encode ships C-contiguous tensor buffers as a scatter-gather send
# (socket.sendmsg / RingEndpoint.sendmsg over memoryviews -- no tobytes(),
# no join); decode receives straight INTO the final tensor buffers
# (recv_into over a numpy allocation) and hands out read-only views.
# Every residual copy is counted into karpenter_wire_payload_copies_total
# -- the warm delta path's counters read 0, test-asserted.


def _transport(sock) -> str:
    """Metric label for the wire a frame moved over: 'shm' for ring
    endpoints (solver/shm.py), 'tcp' for any socket (TCP or UNIX)."""
    return getattr(sock, "transport_label", "tcp")


def _payload_views(tensors: Sequence[Tuple[str, np.ndarray]]):
    """(byte views, copy count, total bytes) for a frame's payload.
    C-contiguous arrays (everything the production encode produces) view
    for free; a non-contiguous tensor pays one copy, counted."""
    views, copies, nbytes = [], 0, 0
    for _, a in tensors:
        c = np.ascontiguousarray(a)
        if c is not a:
            copies += 1
        if c.size == 0:
            continue  # nothing on the wire; the header still records the shape
        if c.ndim == 0:
            c = c.reshape(1)  # 0-d buffers cannot cast; the header keeps shape []
        views.append(memoryview(c).cast("B"))
        nbytes += c.nbytes
    return views, copies, nbytes


def _sendmsg_all(sock, bufs) -> None:
    """Drive a scatter-gather buffer list fully onto the wire (sendmsg
    may send fewer bytes than offered). Raises NotImplementedError
    untouched when the socket cannot scatter-gather (TLS) -- nothing has
    been sent at that point, so the caller's join fallback is safe."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b) for b in bufs]
    while bufs:
        sent = sock.sendmsg(bufs)
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if bufs and sent:
            bufs[0] = bufs[0][sent:]


def _send_frame(sock, header: dict, tensors: Sequence[Tuple[str, np.ndarray]] = ()) -> None:
    failpoints.eval("rpc.send")
    header = dict(header)
    header["tensors"] = [
        {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)} for name, a in tensors
    ]
    views, copies, payload_bytes = _payload_views(tensors)
    if views:
        # payload integrity: one crc32 STREAMED over the tensor views (no
        # intermediate concatenation). A flipped bit in a decision tensor
        # would otherwise decode into a silently WRONG placement; with the
        # checksum it surfaces as a ConnectionError and the caller degrades
        # through the ladder to a recomputed (correct) decision. Old peers
        # ignore the extra header field; frames from old peers skip the check.
        crc = 0
        for v in views:
            crc = zlib.crc32(v, crc)
        header["crc"] = crc
    hb = json.dumps(header).encode()
    prefix = _LEN.pack(len(hb)) + hb
    if copies:
        metrics.WIRE_PAYLOAD_COPIES.inc(copies, side="encode")
    metrics.WIRE_BYTES.inc(
        len(prefix) + payload_bytes, direction="sent", transport=_transport(sock)
    )
    if failpoints.live("rpc.frame.corrupt") is not None:
        # chaos path: the corrupt site needs the whole frame as one buffer
        # to flip a deterministic byte past the length prefix; the joining
        # copy is acceptable while THIS site can still fire (and counted)
        # -- a drill on an unrelated site, or one already spent, must not
        # cost the zero-copy path
        data = failpoints.corrupt("rpc.frame.corrupt", b"".join([prefix] + views))
        if views:
            metrics.WIRE_PAYLOAD_COPIES.inc(side="encode")
        sock.sendall(data)
        return
    try:
        _sendmsg_all(sock, [prefix] + views)
    except (NotImplementedError, AttributeError):
        # TLS sockets cannot scatter-gather (and encrypt-copy anyway):
        # join and send -- the one transport where the copy is inherent
        if views:
            metrics.WIRE_PAYLOAD_COPIES.inc(side="encode")
        sock.sendall(b"".join([prefix] + views))


def _recv_exact(sock, n: int) -> bytes:
    """Header reads share the recv_into discipline of the tensor path:
    one preallocated buffer filled in place (delta headers carry the
    dirty-row index list -- KBs at high churn, not worth re-buffering)."""
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def _recv_exact_into(sock, view: memoryview) -> None:
    """Fill `view` completely from the wire -- the zero-copy receive: the
    destination IS the final tensor buffer, there is no intermediate."""
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed mid-frame")
        got += r


def _recv_frame(sock, limit: int = MAX_FRAME) -> Tuple[dict, Dict[str, np.ndarray]]:
    failpoints.eval("rpc.recv")
    (hlen,) = _LEN.unpack(_recv_exact(sock, 4))
    if hlen > limit:
        raise ConnectionError(f"oversized header ({hlen} bytes)")
    # a corrupted frame must surface as a CONNECTION error, not a stray
    # JSONDecodeError/TypeError escaping into the solve: the stream is
    # desynchronized either way, and ConnectionError is what every caller
    # (reconnect ladders, the breaker) already handles
    try:
        header = json.loads(_recv_exact(sock, hlen))
        if not isinstance(header, dict):
            raise ValueError("frame header is not an object")
    except ValueError as e:
        raise ConnectionError(f"corrupt frame header: {e}") from None
    tensors: Dict[str, np.ndarray] = {}
    total = 0
    crc = 0
    try:
        for spec in header.get("tensors", ()):
            dtype = np.dtype(spec["dtype"])
            shape = [int(s) for s in spec["shape"]]
            if any(s < 0 for s in shape):
                raise ConnectionError(f"negative dimension in {spec}")
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            nbytes = count * dtype.itemsize
            total += nbytes
            # bound the payload BEFORE allocating: a hostile header must not be
            # able to make the sidecar allocate unbounded buffers
            if nbytes > limit or total > limit:
                raise ConnectionError(f"oversized tensor payload ({total} bytes)")
            # receive DIRECTLY into the tensor's own allocation -- the
            # decode-side zero copy -- then hand out a read-only view,
            # mirroring the frombuffer-over-bytes contract every consumer
            # (solve inputs, epoch store, reply decode) already tolerates
            raw = np.empty((nbytes,), dtype=np.uint8)
            mv = memoryview(raw)
            _recv_exact_into(sock, mv)
            crc = zlib.crc32(mv, crc)
            arr = raw.view(dtype).reshape(shape)
            arr.flags.writeable = False
            tensors[spec["name"]] = arr
    except (TypeError, ValueError, KeyError) as e:
        raise ConnectionError(f"corrupt tensor spec: {e}") from None
    want = header.get("crc")
    if want is not None and tensors and crc != int(want):
        raise ConnectionError("frame payload crc mismatch")
    metrics.WIRE_BYTES.inc(
        4 + hlen + total, direction="received", transport=_transport(sock)
    )
    return header, tensors


# -- reply trimming (reply_v2) ------------------------------------------------
#
# The v1 compact reply ships full g_max-row group tensors and the whole
# nnz_max sparse budget even though only n_open groups opened and nnz
# entries are real -- at the 50k tier that is ~120 KB of mostly padding
# and repetition per solve. reply_v2 (feature-negotiated like solve_delta)
# ships only the DECISION ROWS: idx/val truncated to the true nnz, and
# the per-group (survivor mask, zone/captype) rows deduplicated -- FFD
# opens groups in runs, so consecutive groups repeat the same row; the
# unique rows plus a per-group index reconstruct the dense form exactly.
# The client's vectorized reconstruction (expand_reply_v2) rebuilds a
# CompactDecision bit-identical in every decision-bearing lane, so
# expand_compact and the whole decode are unchanged downstream.

def _reply_v2_parts(d: Dict[str, np.ndarray]):
    """(extra header fields, tensor list) for a trimmed v2 reply, from
    the fetched CompactDecision arrays by field name."""
    idx = np.atleast_1d(np.asarray(d["idx"]))
    val = np.atleast_1d(np.asarray(d["val"]))
    unplaced = np.atleast_1d(np.asarray(d["unplaced"]))
    nnz = int(np.asarray(d["nnz"]).reshape(()))
    n_open = int(np.asarray(d["n_open"]).reshape(()))
    hdr = {"v": 2, "nnz": nnz, "n_open": n_open}
    if nnz > idx.shape[0]:
        # sparse-budget overflow: the compact decision is incomplete
        # either way; ship no tensors and let the client's dense-refetch
        # ladder take over (expand_compact returns None on nnz > len(idx))
        return hdr, []
    gmask_bits = np.asarray(d["gmask_bits"])[:n_open]
    gzc = np.asarray(d["gzc"])[:n_open]
    rows = np.concatenate([gmask_bits, gzc[:, None]], axis=1)
    uniq, gid = np.unique(rows, axis=0, return_inverse=True)
    tensors = [
        ("idx", idx[:nnz]), ("val", val[:nnz]), ("unplaced", unplaced),
        ("uniq", np.ascontiguousarray(uniq)),
        ("gid", np.ascontiguousarray(gid.reshape(-1).astype(np.int32))),
    ]
    return hdr, tensors


def expand_reply_v2(header: dict, t: Dict[str, np.ndarray], g_max: int):
    """Vectorized client-side reconstruction of a v2 reply into a
    CompactDecision (numpy leaves). Group rows rebuild as one fancy-index
    over the unique-row table plus zero padding to g_max (decode never
    reads past n_open). An overflow reply reconstructs with an empty idx,
    which expand_compact maps to None -- the existing dense-refetch
    ladder, unchanged."""
    from karpenter_tpu.solver import ffd

    nnz = int(header["nnz"])
    n_open = int(header["n_open"])
    if "idx" not in t:  # overflow: no tensors shipped
        return ffd.CompactDecision(
            idx=np.empty((0,), np.int32), val=np.empty((0,), np.int32),
            nnz=np.int32(max(nnz, 1)), unplaced=np.empty((0,), np.int32),
            n_open=np.int32(n_open), gmask_bits=np.empty((0, 0), np.uint32),
            gzc=np.empty((0,), np.uint32),
        )
    uniq = np.asarray(t["uniq"])
    gid = np.asarray(t["gid"]).reshape(-1)
    kw = max(uniq.shape[1] - 1, 0)
    gmask_bits = np.zeros((g_max, kw), dtype=np.uint32)
    gzc = np.zeros((g_max,), dtype=np.uint32)
    if n_open:
        rows = uniq[gid]
        gmask_bits[:n_open] = rows[:, :kw]
        gzc[:n_open] = rows[:, kw]
    return ffd.CompactDecision(
        idx=t["idx"], val=t["val"], nnz=np.int32(nnz),
        unplaced=t["unplaced"], n_open=np.int32(n_open),
        gmask_bits=gmask_bits, gzc=gzc,
    )


# -- server ------------------------------------------------------------------

class _ReplyBuffer:
    """Capture a coalesced op's reply frames in memory so the SHARED
    dispatcher thread never blocks on one tenant's socket: a stalled
    operator (full TCP window, SIGSTOP'd controller) must cost ITS
    handler thread at flush time, never head-of-line-block every other
    tenant's window. Quacks like the frame wire for _send_frame's
    purposes (sendmsg/sendall + the transport label); the one buffered
    copy per reply is the price of the isolation and replies are small
    (reply_v2 trims them to the decision rows)."""

    def __init__(self, sock):
        self.transport_label = _transport(sock)
        self._chunks: List[bytes] = []

    def sendmsg(self, bufs) -> int:
        n = 0
        for b in bufs:
            bb = bytes(b)
            self._chunks.append(bb)
            n += len(bb)
        return n

    def sendall(self, data) -> None:
        self._chunks.append(bytes(data))

    def flush_to(self, sock) -> None:
        """Write the buffered frames onto the real wire -- called from
        the submitting connection's own handler thread."""
        for chunk in self._chunks:
            sock.sendall(chunk)
        self._chunks.clear()


class _StagedEntry:
    def __init__(self, staged, offsets, words, tepoch=None, catalog=None):
        self.staged = staged
        self.offsets = offsets
        self.words = words
        # mesh fleet path only: the topology epoch the shards were staged
        # under, and the HOST catalog tensors so a topology change can be
        # healed server-side (one transparent restage at lookup -- the
        # client keeps its seqnum, no wire round-trip, no restage loop)
        self.tepoch = tepoch
        self.catalog = catalog


class SolverServer:
    """Serves auth/stage/solve/ping over persistent connections. One staged
    catalog per seqnum (bounded LRU of 4: catalogs change 12-hourly).

    Transports: `path` -> UNIX domain socket (mode 0600, the default
    deployment); `host`/`port` -> TCP, which REQUIRES a shared token
    unless `insecure_tcp=True`; `ssl_context` optionally wraps accepted
    TCP connections in TLS."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, *,
        path: Optional[str] = None, token: Optional[str] = None,
        insecure_tcp: bool = False, ssl_context=None,
        handshake_timeout: float = 30.0,
        shm: Optional[bool] = None, shm_size: Optional[int] = None,
        shm_dir: Optional[str] = None,
        mesh=None, coalescer=None,
    ):
        from karpenter_tpu.solver import shm as shm_mod

        # fleet subsystem (karpenter_tpu/fleet/): `mesh` is a
        # MeshSolveEngine (or a Mesh/layout spec) routing every device
        # dispatch through the sharded jit entries -- sharded==unsharded
        # bit-identity means the wire contract is byte-unchanged;
        # `coalescer` is a DispatchCoalescer batching concurrent
        # per-tenant solve ops into shared dispatch windows.
        if mesh is not None:
            from karpenter_tpu.fleet.shard import MeshSolveEngine

            if not isinstance(mesh, MeshSolveEngine):
                mesh = MeshSolveEngine(mesh)
        self._mesh = mesh
        self._coalescer = coalescer

        # shared-memory ring transport (solver/shm.py): advertised in ping
        # features and established per connection via the shm_open op.
        # Default on (the client only asks when IT decides the topology is
        # colocated); $KARPENTER_TPU_SHM=0 or shm=False kills the advert.
        if shm is None:
            shm = os.environ.get(SHM_ENV, "1") != "0"
        self._shm_enabled = bool(shm)
        self._shm_size = shm_size or shm_mod.ring_size()
        self._shm_dir = shm_dir
        # crash janitor: unlink ring segments whose creator pid is dead
        # (a SIGKILL'd sidecar cannot clean after itself) -- the
        # transport-level analogue of the restart recovery sweep. Runs
        # even with shm disabled: restarting with the kill switch set is
        # exactly the post-incident move that must not strand segments.
        shm_mod.cleanup_stale(self._shm_dir)
        # live per-connection ring segments: stop() flags them closed so a
        # handler blocked in a ring wait wakes and tears down (the listener
        # close alone cannot reach it)
        self._live_segs: set = set()
        self._staged: Dict[str, _StagedEntry] = {}
        # class-tensor epochs (solve_delta): epoch id -> {name: np array},
        # the full class tensor set as of that epoch, patched row-wise by
        # delta solves. Same bounded-LRU discipline as the catalog staging.
        self._epochs: Dict[str, Dict[str, np.ndarray]] = {}
        # disrupt leftover epochs (solve_disrupt): depoch id -> [S, C]
        # leftover tensor from a repack pass, referenced by the same
        # sweep's per-pool replacement passes so they ship only the class
        # masks. Same bounded-LRU + pressure-eviction discipline.
        self._disrupt: Dict[str, np.ndarray] = {}
        # eviction accounting (the LRUs used to evict silently): mirrored
        # into karpenter_solver_staged_evictions_total and served by the
        # "debug" op for the true sidecar topology
        self._evictions = {"catalog": 0, "class_epoch": 0, "disrupt": 0}
        self._lock = threading.Lock()
        # TLS-handshake budget (was a hardcoded 30s): a peer stalling the
        # handshake holds one daemon thread, never the accept loop, but the
        # bound should still be an operator decision
        self._handshake_timeout = handshake_timeout
        self._token = token if token is not None else os.environ.get(TOKEN_ENV)
        # an empty token is UNSET, not a guessable one-value secret: it
        # must neither satisfy the TCP guard nor be compared against
        if not self._token:
            self._token = None
        if path is None and self._token is None and not insecure_tcp:
            raise ValueError(
                "a TCP solver listener requires a shared token (token= or "
                f"${TOKEN_ENV}); pass insecure_tcp=True only as an explicit "
                "operator decision, or use a UNIX socket (path=)"
            )
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # per-connection auth state: with a token configured, the
                # FIRST frame must be a valid auth op; anything else closes
                # the connection (no op is dispatched unauthenticated).
                # Pre-auth frames are capped at 4 KB -- an unauthenticated
                # peer must not be able to force MAX_FRAME allocations.
                authed = outer._token is None
                # the frame wire for this connection: starts as the
                # socket; a successful shm_open handshake swaps in the
                # ring endpoint (the socket stays open as the liveness
                # anchor and the teardown signal)
                wire = self.request
                seg = None
                try:
                    if ssl_context is not None:
                        # handshake in THIS per-connection thread, never in
                        # the accept loop (a stalled handshake must not
                        # wedge the server), and bounded by a timeout
                        self.request.settimeout(outer._handshake_timeout)
                        self.request = ssl_context.wrap_socket(
                            self.request, server_side=True
                        )
                        self.request.settimeout(None)
                        wire = self.request
                    while True:
                        # chaos site: a connection-drop here closes the
                        # stream mid-conversation (the handler's except
                        # path), the wedge/kill shapes the chaos soak arms
                        failpoints.eval("rpc.server.conn")
                        header, tensors = _recv_frame(
                            wire,
                            limit=MAX_FRAME if authed else 4096,
                        )
                        op = header.get("op")
                        if op == "auth":
                            supplied = str(header.get("token", ""))
                            if outer._token is None or hmac.compare_digest(
                                supplied, outer._token
                            ):
                                authed = True
                                _send_frame(wire, {"ok": True})
                                continue
                            _send_frame(
                                wire, {"ok": False, "error": "unauthenticated"}
                            )
                            return
                        if not authed:
                            _send_frame(
                                wire, {"ok": False, "error": "unauthenticated"}
                            )
                            return
                        if op == "shm_open":
                            wire, seg = outer._op_shm_open(self.request, wire, seg)
                            continue
                        outer._dispatch(wire, header, tensors)
                except (ConnectionError, OSError, ValueError):
                    return
                finally:
                    if seg is not None:
                        # per-connection segment: unlink with the stream
                        # (a crashed server's leftovers are swept by the
                        # cleanup_stale janitor at the next start)
                        with outer._lock:
                            outer._live_segs.discard(seg)
                        seg.destroy()

        if path is not None:
            class Server(socketserver.ThreadingUnixStreamServer):
                daemon_threads = True

            try:
                os.unlink(path)
            except OSError:
                pass
            # bind under a restrictive umask: chmod-after-bind leaves a
            # window where any local user could connect and keep the
            # (tokenless) connection past the chmod
            old_umask = os.umask(0o177)
            try:
                self._server = Server(path, Handler)
            finally:
                os.umask(old_umask)
            os.chmod(path, 0o600)
            self.address = path
            self.path = path
        else:
            class Server(socketserver.ThreadingTCPServer):
                allow_reuse_address = True
                daemon_threads = True

            self._server = Server((host, port), Handler)
            self.address = self._server.server_address
            self.path = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SolverServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._coalescer is not None:
            # fail queued tenant submissions first so handler threads
            # blocked in submit() unwind before the listener dies
            self._coalescer.close()
        with self._lock:
            segs = list(self._live_segs)
        for seg in segs:
            # both closed flags: wake EITHER side's ring wait so the
            # handler unblocks, tears down, and unlinks the segment
            seg.set_closed_flags()
        self._server.shutdown()
        self._server.server_close()

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, sock, header: dict, tensors: Dict[str, np.ndarray]) -> None:
        op = header.get("op")
        # trace propagation (tracing.py): a request carrying a "trace"
        # context gets its server-side stages timed and ECHOED in the
        # reply header, so the client can graft them into the dispatching
        # tick's span tree; untraced requests pay nothing and the reply
        # is byte-identical to the pre-tracing protocol
        wt = tracing.WireTrace(header.get("trace"))
        try:
            # chaos site INSIDE the try: an injected error crosses the wire
            # as an error frame (an erroring solver); injected latency
            # models a wedged solver holding the reply
            failpoints.eval("rpc.server.dispatch")
            if op == "ping":
                # features lets a NEWER client decide whether semantics it
                # depends on exist server-side: an older server omits the
                # field (or errors on a future op), and the client falls
                # back -- e.g. taint-gated merged batches to the oracle
                # (service._try_solve_merged) rather than silently packing
                # without the join_allowed gate
                features = [
                    "join_allowed", "trace_echo", "solve_delta", "reply_v2",
                    "solve_disrupt", "packed_masks", "topology_epoch",
                    "convex",
                ]
                if self._shm_enabled:
                    features.append("shm")
                if self._coalescer is not None:
                    features.append("coalesce")
                _send_frame(sock, {"ok": True, "features": features})
            elif op == "stage":
                self._op_stage(sock, header, tensors)
            elif op in ("solve", "solve_compact", "solve_delta", "solve_disrupt",
                        "solve_convex"):
                if self._coalescer is not None:
                    # fleet topology: device dispatches from N tenants
                    # batch into shared windows with deterministic tenant
                    # ordering; a TenantRefusal (breaker open, deadline
                    # blown while queued) or a per-tenant dispatch error
                    # re-raises HERE -- in this tenant's handler thread --
                    # and crosses the wire as ITS error reply below,
                    # never another tenant's. The reply itself buffers
                    # inside the window and flushes from THIS thread, so
                    # a stalled tenant socket can never head-of-line-
                    # block the shared dispatcher.
                    reply = _ReplyBuffer(sock)
                    self._coalescer.submit(
                        str(header.get("tenant", "")),
                        lambda: self._dispatch_solve(reply, op, header, tensors, wt),
                    )
                    reply.flush_to(sock)
                else:
                    self._dispatch_solve(sock, op, header, tensors, wt)
            elif op == "debug":
                self._op_debug(sock)
            else:
                _send_frame(sock, {"ok": False, "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 -- errors cross the wire
            _send_frame(sock, {"ok": False, "error": f"{type(e).__name__}: {e}"})

    def _dispatch_solve(self, sock, op: str, header: dict,
                        tensors: Dict[str, np.ndarray], wt) -> None:
        """The device-dispatching ops (everything the fleet coalescer
        batches); replies stream on the submitting connection's wire."""
        if op == "solve":
            self._op_solve(sock, header, tensors, wt)
        elif op == "solve_compact":
            self._op_solve_compact(sock, header, tensors, wt)
        elif op == "solve_delta":
            self._op_solve_delta(sock, header, tensors, wt)
        elif op == "solve_convex":
            self._op_solve_convex(sock, header, tensors, wt)
        else:
            self._op_solve_disrupt(sock, header, tensors, wt)

    def _op_shm_open(self, sock, wire, seg):
        """Transport-level handshake for the shared-memory ring (handled
        in the connection loop, not _dispatch: it rebinds the wire). The
        server creates a per-connection segment, names it over the
        SOCKET, and switches to the ring only after the client confirms
        its attach with shm_ready -- an attach failure (missing /dev/shm,
        permissions, injected rpc.shm.attach fault) leaves both peers on
        the socket with the stream intact. Returns (wire, seg)."""
        from karpenter_tpu.solver import shm as shm_mod

        if seg is not None or wire is not sock or not self._shm_enabled:
            _send_frame(wire, {"ok": False, "error": "shm-unavailable"})
            return wire, seg
        try:
            new_seg = shm_mod.ShmSegment.create(self._shm_size, self._shm_dir)
        except OSError as e:
            _send_frame(sock, {"ok": False, "error": f"shm-create: {e}"})
            return wire, seg
        try:
            _send_frame(sock, {"ok": True, "path": new_seg.path, "size": new_seg.size})
            # shm_ready rides the socket, BOUNDED: a client that dies (or
            # hangs) mid-handshake must neither pin this thread nor leak
            # the segment -- cleanup_stale cannot reclaim it while this
            # server's pid is alive
            prev_timeout = sock.gettimeout()
            sock.settimeout(self._handshake_timeout)
            try:
                header, _ = _recv_frame(sock)
            finally:
                sock.settimeout(prev_timeout)
        except BaseException:
            new_seg.destroy()
            raise
        if header.get("op") == "shm_ready" and header.get("ok"):
            with self._lock:
                self._live_segs.add(new_seg)
            # the server endpoint reads with timeout=None (parked between
            # operator ticks is healthy) but its reply SENDS are bounded:
            # a client reader that wedges with the ring full must cost
            # this handler the handshake budget, not its lifetime
            return new_seg.endpoint(
                "server", liveness=sock, send_timeout=self._handshake_timeout
            ), new_seg
        new_seg.destroy()
        return sock, None

    def _op_stage(self, sock, header: dict, t: Dict[str, np.ndarray]) -> None:
        seqnum = str(header["seqnum"])
        words = tuple(int(w) for w in header["words"])
        catalog = encode.CatalogTensors(
            names=list(header["names"]), k_real=int(header["k_real"]),
            k_pad=int(t["cap"].shape[0]), cap=t["cap"], tcode=t["tcode"],
            tnum=t["tnum"], tnum_present=t["tnum_present"], tzone=t["tzone"],
            tcap=t["tcap"], price=t["price"], vocabs=[], zones=list(header["zones"]),
            words=list(words),
        )
        tepoch = None
        if self._mesh is not None:
            # fleet: catalog tensors stage K-sharded across the mesh once
            # per seqnum; every tenant's later solves reuse the shards.
            # The entry keeps the HOST tensors so a topology-epoch change
            # restages transparently at the next lookup.
            staged, offsets, words, tepoch = (
                self._mesh.stage_catalog_versioned(catalog)
            )
        else:
            staged, offsets, words = ffd.stage_catalog(catalog)
        with self._lock:
            if len(self._staged) >= 4 and seqnum not in self._staged:
                self._staged.pop(next(iter(self._staged)))
                self._evictions["catalog"] += 1
                metrics.SOLVER_STAGED_EVICTIONS.inc(kind="catalog")
            self._staged[seqnum] = _StagedEntry(
                staged, offsets, words, tepoch=tepoch,
                catalog=catalog if self._mesh is not None else None,
            )
            self._evict_for_pressure_locked()
            self._staged_bytes_locked()
        reply = {"ok": True, "seqnum": seqnum}
        if tepoch is not None:
            reply["tepoch"] = int(tepoch)
        _send_frame(sock, reply)

    def _staged_bytes_locked(self) -> Dict[str, int]:
        """Staged bytes by owner (HBM attribution, obs/hbm.py): sums
        nbytes over the catalog staging and the class-epoch store --
        metadata reads, never a transfer -- and mirrors the split into
        karpenter_solver_staged_bytes{kind} so scrape and debug doc
        agree. Caller holds the lock."""
        catalog = sum(obs_hbm.sum_nbytes(e) for e in self._staged.values())
        epochs = sum(obs_hbm.sum_nbytes(e) for e in self._epochs.values())
        disrupt = sum(obs_hbm.sum_nbytes(e) for e in self._disrupt.values())
        metrics.SOLVER_STAGED_BYTES.set(float(catalog), kind="catalog")
        metrics.SOLVER_STAGED_BYTES.set(float(epochs), kind="class_epoch")
        metrics.SOLVER_STAGED_BYTES.set(float(disrupt), kind="disrupt")
        return {
            "catalog": int(catalog), "class_epoch": int(epochs),
            "disrupt": int(disrupt),
        }

    def _evict_for_pressure_locked(self) -> None:
        """Memory-pressure eviction (obs/hbm.py): headroom below the
        evict threshold shrinks BOTH staging LRUs to a floor of one
        (the most recently used entry) instead of waiting for the fixed
        capacity of 4 -- dropping the references releases the device
        buffers. No allocator ledger (CPU) = capacity-only, as before.
        Caller holds the lock; under_pressure's poll is rate-limited."""
        if len(self._staged) <= 1 and len(self._epochs) <= 1 and len(self._disrupt) <= 1:
            return
        if not obs_hbm.under_pressure():
            return
        while len(self._staged) > 1:
            self._staged.pop(next(iter(self._staged)))
            self._evictions["catalog"] += 1
            metrics.SOLVER_STAGED_EVICTIONS.inc(kind="catalog")
            metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.inc(kind="catalog")
        while len(self._epochs) > 1:
            self._epochs.pop(next(iter(self._epochs)))
            self._evictions["class_epoch"] += 1
            metrics.SOLVER_STAGED_EVICTIONS.inc(kind="class_epoch")
            metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.inc(kind="class_epoch")
        while len(self._disrupt) > 1:
            self._disrupt.pop(next(iter(self._disrupt)))
            self._evictions["disrupt"] += 1
            metrics.SOLVER_STAGED_EVICTIONS.inc(kind="disrupt")
            metrics.SOLVER_STAGED_PRESSURE_EVICTIONS.inc(kind="disrupt")

    def _op_debug(self, sock) -> None:
        """Staging observability: what the LRUs hold, their bytes by
        owner, and how often they evicted (the /debug/solver endpoint
        surfaces this in-process; this op serves the true sidecar
        topology where the server's counters live in another process)."""
        with self._lock:
            doc = {
                "ok": True,
                "staged_seqnums": list(self._staged),
                "class_epochs": list(self._epochs),
                "disrupt_epochs": list(self._disrupt),
                "evictions": dict(self._evictions),
                "staged_bytes": self._staged_bytes_locked(),
            }
        if self._mesh is not None:
            doc["mesh"] = self._mesh.describe()
        if self._coalescer is not None:
            doc["coalescer"] = self._coalescer.describe()
        _send_frame(sock, doc)

    def _op_solve_delta(self, sock, header: dict, t: Dict[str, np.ndarray],
                        wt: Optional[tracing.WireTrace] = None) -> None:
        """Compact solve whose class tensors are staged server-side under a
        class-EPOCH id, the per-tick analogue of the per-seqnum catalog
        staging. base=None ships the full tensor set and establishes the
        epoch; base=<epoch> ships only the dirty rows (header "rows") and
        patches a copy of the base epoch. An unknown base is an
        "unknown-epoch" error -- the client full-restages, mirroring the
        unknown-seqnum contract -- so sync, pipelined, and breaker-open
        paths all stay bit-identical to a full encode."""
        # catalog gap first: a restarted sidecar lost BOTH stagings, and
        # reporting the seqnum gap lets the client restage catalog + epoch
        # in one ladder pass instead of two error roundtrips
        with self._lock:
            known = str(header["seqnum"]) in self._staged
        if not known:
            _send_frame(sock, {"ok": False, "error": "unknown-seqnum"})
            return
        full = self._resolve_epoch(sock, header, t)
        if full is None:
            return
        self._op_solve_compact(sock, header, full, wt)

    def _resolve_epoch(self, sock, header: dict, t: Dict[str, np.ndarray]):
        """The full class tensor dict for this solve_delta request, staged
        under header["epoch"], or None after sending the unknown-epoch
        error.

        Round 8 (wire v2): epoch staging is COPY-FREE on the warm path.
        A full ship stores the received read-only frame views as-is (no
        defensive copy -- the old rpc.py:444 copy existed only so later
        deltas could patch, and patching now copies on FIRST write
        instead). A delta patch mutates its chain's base IN PLACE --
        O(dirty rows), counted zero payload copies -- which is sound
        because an epoch chain has exactly one writer: epoch ids are
        client-unique (uuid prefix) and one connection's requests are
        served strictly in order, so no concurrent reader of the base
        exists by construction. The one residual copy (per tensor, once,
        at the first patch after a full ship -- read-only view to
        writable array) is counted into
        karpenter_wire_payload_copies_total{side="decode"}; the warm
        steady state after it reads 0, test-asserted."""
        epoch = str(header["epoch"])
        base = header.get("base")
        ent = None
        if base is not None:
            with self._lock:
                ent = self._epochs.get(str(base))
                if ent is not None:
                    # LRU touch, same discipline as the catalog staging
                    self._epochs.pop(str(base))
                    self._epochs[str(base)] = ent
            if ent is None:
                _send_frame(sock, {"ok": False, "error": "unknown-epoch"})
                return None
            full = dict(ent)
            rows = np.asarray([int(r) for r in header.get("rows", ())], dtype=np.int64)
            for name, arr in t.items():
                # packed [C, KW] mask rows patch like any per-class tensor
                # (only packed_masks-negotiated clients ship them here;
                # full-width bool masks never enter a delta request)
                if name not in PER_CLASS_TENSORS and name not in PACKED_MASK_TENSORS:
                    full[name] = arr  # whole-set tensors replace wholesale
                elif rows.size:
                    cur = full[name]
                    if not cur.flags.writeable:
                        # copy-on-first-write: the base still holds the
                        # full ship's read-only frame views
                        cur = np.array(cur)
                        metrics.WIRE_PAYLOAD_COPIES.inc(side="decode")
                    cur[rows] = arr
                    full[name] = cur
        else:
            # the received tensors are read-only views over their own
            # receive buffers; store them directly -- later deltas
            # copy-on-first-write (above), so no defensive copy here
            full = dict(t)
        with self._lock:
            if base is not None:
                # the patched base is superseded: each client chain diffs
                # against its LAST acknowledged epoch, so the base can be
                # referenced at most by a rare error-recovery resend (which
                # the unknown-epoch ladder absorbs). Consuming it here
                # keeps the LRU at one epoch per live chain and makes the
                # eviction counter mean PRESSURE, not routine supersession.
                self._epochs.pop(str(base), None)
            self._epochs[epoch] = full
            while len(self._epochs) > 4:
                self._epochs.pop(next(iter(self._epochs)))
                self._evictions["class_epoch"] += 1
                metrics.SOLVER_STAGED_EVICTIONS.inc(kind="class_epoch")
            self._evict_for_pressure_locked()
            self._staged_bytes_locked()
        return full

    def _staged_entry(self, sock, header: dict) -> Optional[_StagedEntry]:
        """The staged catalog named by the header's seqnum (LRU-touched),
        or None after sending the unknown-seqnum error (the client
        re-stages on that contract)."""
        seqnum = str(header["seqnum"])
        with self._lock:
            entry = self._staged.get(seqnum)
            if entry is not None:
                # LRU touch: re-insert so eviction pops the least recently
                # USED catalog, not the oldest staged
                self._staged.pop(seqnum)
                self._staged[seqnum] = entry
            if (
                entry is not None
                and self._mesh is not None
                and entry.tepoch is not None
                and entry.tepoch != self._mesh.epoch
                and entry.catalog is not None
            ):
                # topology changed since this seqnum staged: the shards
                # live on a mesh that no longer exists. The server holds
                # the host tensors, so heal HERE -- one transparent
                # restage onto the current mesh, in place, under the
                # lock (exactly once per epoch change; the client keeps
                # its seqnum and never sees a staging gap). A device
                # loss DURING the solve itself still surfaces as
                # StaleTopologyError through the dispatch guard.
                metrics.MESH_STALE_SOLVES.inc(site="server-restage")
                staged, offsets, words, tepoch = (
                    self._mesh.stage_catalog_versioned(entry.catalog)
                )
                entry.staged, entry.offsets, entry.words = staged, offsets, words
                entry.tepoch = tepoch
        if entry is None:
            _send_frame(sock, {"ok": False, "error": "unknown-seqnum"})
        return entry

    def _staged_inputs(self, sock, header: dict, t: Dict[str, np.ndarray]):
        """(entry, SolveInputs) for the staged catalog named by the header's
        seqnum, or None after sending the unknown-seqnum error."""
        entry = self._staged_entry(sock, header)
        if entry is None:
            return None
        inp = ffd.SolveInputs(
            cap=entry.staged.cap, tcode=entry.staged.tcode, tnum=entry.staged.tnum,
            tnum_present=entry.staged.tnum_present, tzone=entry.staged.tzone,
            tcap=entry.staged.tcap, price=entry.staged.price,
            req=t["req"], count=t["count"], env_count=t["env_count"],
            allowed=t["allowed"], num_lo=t["num_lo"], num_hi=t["num_hi"],
            azone=t["azone"], acap=t["acap"], schedulable=t["schedulable"],
            # older clients do not send the per-node daemonset reserve;
            # zeros preserves their semantics exactly
            node_overhead=t.get(
                "node_overhead", np.zeros((t["req"].shape[1],), dtype=np.float32)
            ),
            # ones preserves pre-multipool clients: open anywhere compat allows
            open_allowed=t.get(
                "open_allowed",
                np.ones((t["req"].shape[0], entry.staged.cap.shape[0]), dtype=bool),
            ),
            # ones preserves clients without per-pool-taints gating
            join_allowed=t.get(
                "join_allowed",
                np.ones((t["req"].shape[0], entry.staged.cap.shape[0]), dtype=bool),
            ),
        )
        return entry, inp

    def _op_solve(self, sock, header: dict, t: Dict[str, np.ndarray],
                  wt: Optional[tracing.WireTrace] = None) -> None:
        import jax

        wt = wt or tracing.WireTrace(None)
        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        with wt.stage("device", op="solve"):
            if self._mesh is not None:
                out = self._mesh.solve_dense(
                    inp, g_max=int(header["g_max"]),
                    word_offsets=entry.offsets, words=entry.words,
                    objective=str(header.get("objective", "price")),
                    epoch=entry.tepoch,
                )
            else:
                out = ffd.ffd_solve(
                    inp, g_max=int(header["g_max"]),
                    word_offsets=entry.offsets, words=entry.words,
                    objective=str(header.get("objective", "price")),
                )
            if wt.ctx is not None:
                # jit dispatch is ASYNC: without a barrier the XLA compute
                # would block inside device_get and the echo would claim
                # device~=0, fetch=everything. Traced requests sync here so
                # the stages attribute honestly; untraced requests keep
                # the overlapped dispatch->fetch path untouched.
                jax.block_until_ready(out)
        with wt.stage("fetch"):
            # SANCTIONED_FETCH (jax_discipline): the dense op's host barrier
            arrays = jax.device_get(tuple(out))
        names = ffd.SolveOutputs._fields
        _send_frame(
            sock, {"ok": True, **wt.echo()},
            [(n, np.asarray(a)) for n, a in zip(names, arrays)],
        )

    def _op_solve_compact(self, sock, header: dict, t: Dict[str, np.ndarray],
                          wt: Optional[tracing.WireTrace] = None) -> None:
        """The wire-efficient solve: the decision returns as a
        CompactDecision (~50 KB) instead of the dense SolveOutputs
        (~1.5 MB) -- this boundary exists for the TPU-VM topology where the
        link is exactly the bandwidth-poor hop the compact layout is for."""
        import jax

        wt = wt or tracing.WireTrace(None)
        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        with wt.stage("device", op="solve_compact"):
            if self._mesh is not None:
                dec = self._mesh.solve_compact(
                    inp, g_max=int(header["g_max"]), nnz_max=int(header["nnz_max"]),
                    word_offsets=entry.offsets, words=entry.words,
                    objective=str(header.get("objective", "price")),
                    epoch=entry.tepoch,
                )
            else:
                dec = ffd.ffd_solve_compact(
                    inp, g_max=int(header["g_max"]), nnz_max=int(header["nnz_max"]),
                    word_offsets=entry.offsets, words=entry.words,
                    objective=str(header.get("objective", "price")),
                )
            if wt.ctx is not None:
                # see _op_solve: sync traced requests so XLA compute lands
                # in "device", not "fetch"
                jax.block_until_ready(dec)
        with wt.stage("fetch"):
            # SANCTIONED_FETCH (jax_discipline): the compact op's host barrier
            arrays = jax.device_get(tuple(dec))
        names = ffd.CompactDecision._fields
        if int(header.get("reply", 1)) >= 2:
            # reply trimming (reply_v2): only the decision rows ship --
            # idx/val cut to the true nnz, group rows deduplicated; the
            # client reconstructs the dense form bit-identically
            hdr2, tensors2 = _reply_v2_parts(dict(zip(names, arrays)))
            _send_frame(sock, {"ok": True, **hdr2, **wt.echo()}, tensors2)
            return
        _send_frame(
            sock, {"ok": True, **wt.echo()},
            [(n, np.atleast_1d(np.asarray(a))) for n, a in zip(names, arrays)],
        )

    def _op_solve_convex(self, sock, header: dict, t: Dict[str, np.ndarray],
                         wt: Optional[tracing.WireTrace] = None) -> None:
        """The convex global-solve op: the sidecar owns the staged tensors
        both tiers need, so ONE roundtrip runs the dense FFD solve, the
        LP relaxation (dispatched behind it -- the device overlaps both),
        the deterministic rounding, and the never-worse differential, and
        replies with the CHOSEN dense decision plus the certificate
        (winner, lower bound, iterations) in the header. A rounding
        failure server-side is the same FFD rung as in-process: the reply
        is exactly what the solve op would have returned, flagged with
        fallback=True so the client counts it."""
        import jax

        from karpenter_tpu.solver.convex import relax as convex_relax
        from karpenter_tpu.solver.convex import rounding as convex_rounding
        from karpenter_tpu.solver.convex import tier as convex_tier

        wt = wt or tracing.WireTrace(None)
        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        g_max = int(header["g_max"])
        iters = int(header.get("iters", convex_relax.DEFAULT_ITERS))
        objective = str(header.get("objective", "price"))
        with wt.stage("device", op="solve_convex"):
            if self._mesh is not None:
                out = self._mesh.solve_dense(
                    inp, g_max=g_max,
                    word_offsets=entry.offsets, words=entry.words,
                    objective=objective, epoch=entry.tepoch,
                )
            else:
                out = ffd.ffd_solve(
                    inp, g_max=g_max,
                    word_offsets=entry.offsets, words=entry.words,
                    objective=objective,
                )
            cx = convex_relax.convex_relax(
                inp, iters=iters,
                word_offsets=entry.offsets, words=entry.words,
            )
            if wt.ctx is not None:
                # see _op_solve: sync traced requests so XLA compute lands
                # in "device", not "fetch"
                jax.block_until_ready((tuple(out), tuple(cx)))
        with wt.stage("fetch"):
            # SANCTIONED_FETCH (jax_discipline): the convex op's host
            # barrier -- the FFD decision, the relaxation, and the small
            # catalog tensors rounding needs (the server keeps no host
            # catalog outside mesh mode)
            arrays = jax.device_get(tuple(out))
            x, lower, trace = convex_relax.fetch_relax(cx)
            feas = np.asarray(cx.feas)
            cap = np.asarray(inp.cap)
            price = np.asarray(inp.price)
            tzone = np.asarray(inp.tzone)
            tcap = np.asarray(inp.tcap)
            overhead = np.asarray(inp.node_overhead)
        names = ffd.SolveOutputs._fields
        ffd_out = dict(zip(names, (np.asarray(a) for a in arrays)))
        dense_ffd = (
            ffd_out["take"], ffd_out["unplaced"], int(ffd_out["n_open"]),
            ffd_out["gmask"], ffd_out["gzone"], ffd_out["gcap"],
        )
        cap_eff = np.maximum(
            cap.astype(np.float64) - overhead[None, :], 0.0)
        fallback = False
        try:
            dense_cx = convex_rounding.round_arrays(
                x, feas=feas, cap_eff=cap_eff, price=price,
                req=t["req"], count=t["count"],
                azone=t["azone"], acap=t["acap"],
                tzone=tzone, tcap=tcap, g_max=g_max,
            )
        except Exception:  # noqa: BLE001 -- the FFD rung owns the reply;
            # the error-frame path would cost the client a whole re-solve
            # for a candidate it is allowed to simply not have
            # (OperatorCrashed is BaseException and still flies)
            dense_cx = None
        fallback = dense_cx is None
        winner, dense, p_ffd, p_cx = convex_tier.choose(
            dense_ffd, dense_cx, price)
        take, unplaced, n_open, gmask, gzone, gcap = dense
        _send_frame(
            sock,
            {
                "ok": True, "winner": winner, "n_open": int(n_open),
                "lower": float(lower),
                "iterations": int(convex_relax.iterations_to_convergence(trace)),
                "fallback": bool(fallback),
                "price_ffd": float(p_ffd),
                "price_convex": (None if not np.isfinite(p_cx) else float(p_cx)),
                **wt.echo(),
            },
            [
                ("take", np.asarray(take, dtype=np.int32)),
                ("unplaced", np.asarray(unplaced, dtype=np.int32)),
                ("gmask", np.asarray(gmask)),
                ("gzone", np.asarray(gzone)),
                ("gcap", np.asarray(gcap)),
            ],
        )

    def _op_solve_disrupt(self, sock, header: dict, t: Dict[str, np.ndarray],
                          wt: Optional[tracing.WireTrace] = None) -> None:
        """Batched consolidation solve (solver/disrupt): one repack of
        every candidate set against the surviving headroom, plus an
        optional replacement search against the catalog ALREADY STAGED
        under the header's seqnum -- the capacity/price tensors never
        re-ship. The repacked leftover stages under the header's
        ``depoch`` so the same sweep's later per-pool replacement passes
        ship only the [C, K] class masks (a shipped ``leftover`` tensor
        is the fallback when the depoch was pressure-evicted mid-sweep,
        keeping the op stateless-correct). Kernels are the same jit
        entries the in-process fallback runs, so host == wire verdicts
        hold by construction."""
        import jax

        from karpenter_tpu.apis import labels as wk
        from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

        wt = wt or tracing.WireTrace(None)
        depoch = header.get("depoch")
        reply: List[Tuple[str, np.ndarray]] = []
        if "member" in t:  # the repack half
            with wt.stage("device", op="solve_disrupt"):
                if self._mesh is not None:
                    lo, _ = self._mesh.repack(
                        t["headroom"], t["feas"], t["req"], t["member"], t["excl"]
                    )
                else:
                    lo, _ = disrupt_kernel.disrupt_repack(
                        t["headroom"], t["feas"], t["req"], t["member"], t["excl"]
                    )
                if wt.ctx is not None:
                    # see _op_solve: sync traced requests so XLA compute
                    # lands in "device", not "fetch"
                    jax.block_until_ready(lo)
            with wt.stage("fetch"):
                # SANCTIONED_FETCH (jax_discipline): the disrupt op's host barrier
                leftover = np.asarray(jax.device_get(lo))
            if depoch is not None:
                with self._lock:
                    self._disrupt[str(depoch)] = leftover
                    while len(self._disrupt) > 4:
                        self._disrupt.pop(next(iter(self._disrupt)))
                        self._evictions["disrupt"] += 1
                        metrics.SOLVER_STAGED_EVICTIONS.inc(kind="disrupt")
                    self._evict_for_pressure_locked()
                    self._staged_bytes_locked()
            reply.append(("leftover", leftover))
        else:  # replacement-only pass of an in-flight sweep
            leftover = None
            if depoch is not None:
                with self._lock:
                    leftover = self._disrupt.get(str(depoch))
                    if leftover is not None:  # LRU touch
                        self._disrupt.pop(str(depoch))
                        self._disrupt[str(depoch)] = leftover
            if leftover is None:
                leftover = t.get("leftover")
            if leftover is None:
                _send_frame(sock, {"ok": False, "error": "unknown-depoch"})
                return
        if "compat" in t:  # the replacement half, against the staged catalog
            entry = self._staged_entry(sock, header)
            if entry is None:
                return
            od_col = int(encode.CAPTYPE_INDEX[wk.CAPACITY_TYPE_ON_DEMAND])
            with wt.stage("device", op="disrupt_replace"):
                if self._mesh is not None:
                    out = self._mesh.replace(
                        leftover, t["creq"], t["compat"], t["azone"], t["acap"],
                        entry.staged.cap, t["ovh"], entry.staged.price,
                        od_col=od_col, epoch=entry.tepoch,
                    )
                else:
                    out = disrupt_kernel.disrupt_replace(
                        leftover, t["creq"], t["compat"], t["azone"], t["acap"],
                        entry.staged.cap, t["ovh"], entry.staged.price,
                        od_col=od_col,
                    )
                if wt.ctx is not None:
                    jax.block_until_ready(out)
            with wt.stage("fetch"):
                # SANCTIONED_FETCH (jax_discipline): the replace half's barrier
                arrays = jax.device_get(tuple(out))
            reply.extend(
                (n, np.atleast_1d(np.asarray(a)))
                for n, a in zip(("best", "best_od", "best_k"), arrays)
            )
        _send_frame(sock, {"ok": True, **wt.echo()}, reply)


# -- client ------------------------------------------------------------------

class StaleSeqnumError(RuntimeError):
    """The sidecar does not know the staged-catalog seqnum an ASYNC solve
    named: it restarted or evicted the catalog while the request was in
    flight. The pipelined path surfaces this instead of silently
    re-staging (a restage cannot be spliced in front of a frame that has
    already streamed); the caller decides -- TPUSolver._finish_remote
    falls back to the synchronous op, which restages and retries."""


class StaleEpochError(StaleSeqnumError):
    """The class-epoch analogue of StaleSeqnumError: the sidecar no longer
    knows the base epoch a pipelined DELTA solve patched against (restart,
    or LRU eviction of the epoch). Subclasses StaleSeqnumError so every
    existing ladder that handles a mid-flight staging gap handles this one
    identically: the synchronous retry full-restages the class tensors
    (the client dropped its base on this error)."""


class StaleTopologyError(StaleSeqnumError):
    """The MESH-topology analogue of StaleSeqnumError: the device mesh a
    sharded solve was staged under changed mid-flight (a device was lost,
    quarantined, or returned -- fleet/topology.py bumps the topology
    epoch on any membership change). Staged shards from the old epoch
    live on a mesh that no longer exists, so the solve cannot be
    completed as issued. Subclasses StaleSeqnumError so every existing
    recovery rung -- the synchronous restage-and-retry ladder, the
    pipelined barrier fallback, the breaker, the delta-epoch drop --
    handles a topology change exactly like any other staging gap: the
    retry restages onto the CURRENT mesh (fleet/shard.py reshards
    lazily at the next dispatch) and re-solves bit-identically."""


class _PendingReply:
    """One in-flight request's reply slot. `outcome` is filled by the FIFO
    drain: ("ok", header, tensors) or ("err", exception). `seqnum` names
    the staged catalog the request referenced -- the claim side drops the
    matching delta base on staging-gap errors."""

    __slots__ = ("outcome", "seqnum", "g_max")

    def __init__(self, seqnum: str = "", g_max: int = 0):
        self.outcome = None
        self.seqnum = seqnum
        # the request's group budget: a reply_v2 reconstruction needs it
        # to rebuild the dense g_max-row group tensors client-side
        self.g_max = g_max


class SolverClient:
    """Drop-in backend for TPUSolver-shaped solves over the wire. Maintains
    one persistent connection; `solve_classes` mirrors the tensor half of
    TPUSolver.solve (the caller does host-side encode/decode)."""

    def __init__(
        self, host: Optional[str] = None, port: Optional[int] = None,
        timeout: float = 30.0, *, path: Optional[str] = None,
        token: Optional[str] = None, ssl_context=None,
        server_hostname: Optional[str] = None,
        connect_timeout: float = DEFAULT_CONNECT_TIMEOUT,
        delta: Optional[bool] = None,
        shm: Optional[bool] = None, reply_v2: Optional[bool] = None,
        track_transport: bool = True, tenant: Optional[str] = None,
        packed_masks: Optional[bool] = None,
    ):
        self.addr = (host, port) if path is None else None
        self.path = path
        # fleet topology (karpenter_tpu/fleet/): the tenant id this
        # replica's solve ops carry -- the shared sidecar's coalescer keys
        # its deterministic ordering, deadline budget, and per-tenant
        # breaker on it. None (the single-cluster default) omits the
        # field; the server then treats the connection as the anonymous
        # tenant, which is exactly the pre-fleet behavior.
        self.tenant = str(tenant) if tenant else None
        # karpenter_wire_transport_in_use is process-global: only the
        # PRIMARY client (the solver's real wire) reports to it. Throwaway
        # connections -- the breaker's half-open probe, ad-hoc tooling --
        # pass False so they never clobber the operator's degrade signal.
        self._track_transport = bool(track_transport)
        # shared-memory ring transport (solver/shm.py): negotiated per
        # connection when the server advertises it. Default: ask only on
        # a UNIX-socket transport (the colocated-sidecar topology -- a
        # remote TCP sidecar cannot share memory); $KARPENTER_TPU_SHM=1
        # forces the ask over TCP (colocated-by-config), =0 kills it.
        # The socket stays the portable fallback: attach failures keep
        # the connection on it, and SHM_MAX_FAILURES consecutive shm
        # stream failures (e.g. crc mismatches from a corrupt segment)
        # stop the client re-negotiating -- the automatic degrade to TCP.
        if shm is None:
            env = os.environ.get(SHM_ENV)
            shm = (path is not None) if env is None else env != "0"
        self.shm = bool(shm) and ssl_context is None
        self._shm_failures = 0
        self._ring = None          # live RingEndpoint (shm mode)
        self._ring_seg = None      # its segment mapping
        self._wire = None          # the frame wire: ring or socket
        # trimmed compact replies (reply_v2): on when the server
        # advertises the feature; $KARPENTER_TPU_REPLY_V2=0 kills
        if reply_v2 is None:
            reply_v2 = os.environ.get(REPLY_V2_ENV, "1") != "0"
        self.reply_v2 = bool(reply_v2)
        # reply observability for the LAST decision decoded (bench reads
        # it): payload bytes on the wire and the reply shape version
        self.last_reply = {"bytes": 0, "v": 0}
        # timeout = the per-solve READ budget; connect_timeout bounds
        # connection establishment (connect + TLS + auth). They were one
        # knob before, which made a dead sidecar cost the full solve
        # budget per reconnect attempt instead of ~1s.
        self.timeout = timeout
        self.connect_timeout = connect_timeout
        # True while the LAST _apply_budget_timeout clamped the read
        # budget below `timeout` (an active tick-deadline budget): a
        # timeout in that state is deliberate shedding, and _wire_failed
        # exempts it from the shm degrade ladder
        self._budget_clamped = False
        self.token = (token if token is not None else os.environ.get(TOKEN_ENV)) or None
        self._ssl_context = ssl_context
        self._server_hostname = server_hostname or (host if host else None)
        self._sock: Optional[socket.socket] = None
        self._staged_seqnums: set = set()
        # mesh topology epoch each seqnum was staged under, as reported in
        # the stage reply (feature-negotiated "topology_epoch"; an older or
        # unsharded server omits the field). Informational: the SERVER
        # owns restaging across topology changes -- this is the observable
        # half, so operators and tests can see which device set a staged
        # catalog targeted.
        self._staged_tepochs: Dict[str, int] = {}
        self._features: Optional[frozenset] = None  # per-connection, lazy
        # delta class shipping (the incremental-tick wire layer): when the
        # server advertises solve_delta, compact solves stage the class
        # tensors under a class-epoch id and subsequent solves ship only
        # the dirty rows. Default on; delta=False or $KARPENTER_TPU_DELTA=0
        # forces the full ship (the two are bit-identical by construction
        # -- the server reassembles the same tensors either way).
        if delta is None:
            delta = os.environ.get(DELTA_ENV, "1") != "0"
        self.delta = bool(delta)
        # bit-packed mask wire form (solver/packing.py): when the server
        # advertises "packed_masks", the [C, K] open/join masks ship as
        # [C, KW] uint32 words -- 8x less payload AND row-patchable by
        # the delta path (full-width bool masks bypass it). Bit-identical
        # by construction: the kernel unpacks in-jit. Default on;
        # packed_masks=False or $KARPENTER_TPU_PACKED_MASKS=0 forces the
        # full-width ship (and an older server simply never negotiates).
        if packed_masks is None:
            packed_masks = os.environ.get(PACKED_MASKS_ENV, "1") != "0"
        self.packed_masks = bool(packed_masks)
        # seqnum -> (epoch id, {name: array copy}): the last class tensor
        # state the server is known to hold for that catalog. Bounded LRU;
        # dropped eagerly on close() and on any staging-gap error.
        self._epoch_bases: Dict[str, tuple] = {}
        import uuid as _uuid

        self._epoch_prefix = _uuid.uuid4().hex[:12]
        self._epoch_counter = 0
        # shipping observability for the LAST solve dispatched (read by
        # the solver's metrics/span wiring and the bench's delta stage)
        self.last_delta = {"mode": "bypass", "rows": -1, "payload_bytes": 0, "full_bytes": 0}
        # one reentrant lock serializes the socket AND the staging set: the
        # protocol is strictly request/response on one connection, so a
        # whole roundtrip (and the stage-then-solve sequence inside
        # solve_classes) must be atomic across threads
        self._lock = threading.RLock()
        # request-pipelining FIFO (begin_solve_compact): replies come back
        # in request order on the one stream, so each dispatched frame's
        # reply slot queues here until a drain claims it
        from collections import deque

        self._pending: "deque[_PendingReply]" = deque()
        # one solve computing + one frame streaming behind it -- the depth
        # at which the RTT fully overlaps compute; anything deeper only
        # buffers latency (and decisions) without adding overlap
        self.MAX_INFLIGHT = 2

    def _conn(self):
        """The frame wire for this connection: the shared-memory ring
        endpoint when negotiation succeeded, the socket otherwise."""
        if self._sock is None:
            failpoints.eval("rpc.client.connect")
            # the WHOLE establishment sequence (connect, TLS handshake,
            # auth roundtrip, shm negotiation) runs under connect_timeout;
            # only then does the wire get the long per-solve read budget
            if self.path is not None:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    sock.settimeout(self.connect_timeout)
                    sock.connect(self.path)
                except OSError:
                    # close on the error edge too: a reconnect storm
                    # against a dead sidecar must not dangle one fd per
                    # attempt until GC (reslife/leak-on-error)
                    sock.close()
                    raise
            else:
                sock = socket.create_connection(self.addr, timeout=self.connect_timeout)
                try:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    if self._ssl_context is not None:
                        sock = self._ssl_context.wrap_socket(
                            sock, server_hostname=self._server_hostname
                        )
                except OSError:
                    sock.close()
                    raise
            self._sock = sock
            self._wire = sock
            self._staged_seqnums.clear()
            try:
                if self.token:
                    # prove the shared token before any op (the server closes
                    # unauthenticated connections on the first non-auth frame)
                    _send_frame(sock, {"op": "auth", "token": self.token})
                    header, _ = _recv_frame(sock)
                    if not header.get("ok"):
                        raise ConnectionError("solver auth rejected")
                if self.shm and self._shm_failures < SHM_MAX_FAILURES:
                    self._try_shm(sock)
            except (ConnectionError, OSError):
                sock.close()
                self._sock = None
                self._wire = None
                raise
            sock.settimeout(self.timeout)
            if self._ring is not None:
                self._ring.settimeout(self.timeout)
            if self._track_transport:
                metrics.WIRE_TRANSPORT.set(
                    1.0 if self._ring is not None else 0.0, transport="shm"
                )
                metrics.WIRE_TRANSPORT.set(
                    0.0 if self._ring is not None else 1.0, transport="tcp"
                )
        return self._wire

    def _try_shm(self, sock) -> None:
        """Negotiate the shared-memory ring on a fresh connection. Every
        failure mode leaves the SOCKET stream intact and usable:
        - an injected rpc.shm.attach fault or a local attach failure fires
          BEFORE/AFTER complete roundtrips, and shm_ready(ok=False) tells
          the server to unlink the segment and stay on the socket;
        - a server without the op answers with an error frame ("unknown
          op"), which reads as a refusal."""
        from karpenter_tpu.solver import shm as shm_mod

        try:
            failpoints.eval("rpc.shm.attach")
        except (ConnectionError, OSError, RuntimeError):
            return  # injected attach failure: stay on the socket
        _send_frame(sock, {"op": "shm_open"})
        header, _ = _recv_frame(sock)
        if not header.get("ok") or "path" not in header:
            return  # refused / old server: the socket is the transport
        try:
            seg = shm_mod.ShmSegment.attach(str(header["path"]), int(header["size"]))
        except (shm_mod.ShmAttachError, ValueError, KeyError,
                ConnectionError, OSError, RuntimeError):
            # the wide net matters: attach re-evals the rpc.shm.attach
            # failpoint, and an injected ConnectionError must degrade to
            # the socket here, not tear down the whole connection
            _send_frame(sock, {"op": "shm_ready", "ok": False})
            return
        try:
            _send_frame(sock, {"op": "shm_ready", "ok": True})
        except BaseException:
            # the socket died between attach and ready: the segment was
            # never adopted (self._ring_seg unset), so close the mapping
            # here or its fd leaks for the life of the process under a
            # reconnect storm against a crashing sidecar
            seg.close()
            raise
        self._ring_seg = seg
        self._ring = seg.endpoint("client", liveness=sock, timeout=self.connect_timeout)
        self._wire = self._ring

    def _apply_budget_timeout(self) -> None:
        """Per-tick deadline budgets (karpenter_tpu/overload.py): clamp
        this roundtrip's READ budget to the active tick budget's
        remaining time, so a tick that is going to blow its deadline
        fails the wire EARLY -- the expiring timeout surfaces as the same
        OSError every degrade ladder (reconnect, breaker, CPU fallback)
        already handles -- instead of timing out late. No active budget
        (the default, and every deterministic test) leaves the configured
        solve timeout untouched. Caller holds the lock."""
        wire = self._wire
        if wire is None:
            return
        t = overload.clamp_timeout(self.timeout)
        # remembered for _wire_failed: a timeout under a clamped budget is
        # OUR impatience, not transport evidence
        self._budget_clamped = t < self.timeout
        if wire.gettimeout() != t:
            wire.settimeout(t)

    def _wire_failed(self, exc: Optional[BaseException] = None) -> None:
        """Stream-failure accounting for the shm degrade ladder: failures
        WHILE the ring was the wire count toward SHM_MAX_FAILURES (after
        which reconnects stay on the socket); socket failures do not.
        Neither does a peer found ALREADY dead before the frame went onto
        the ring (ShmPeerGoneError) -- every reconnect gets a fresh
        segment, so a crash-looping sidecar must not permanently cost the
        ring. Failures once bytes are in flight DO count: a server hangs
        up on a corrupt stream, so a reply-wait EOF is ambiguous with
        corruption, and crc/decode failures and wedged-peer timeouts are
        direct evidence.

        A TIMEOUT while the tick-deadline budget had CLAMPED the read
        below the configured solve timeout is OUR deliberate impatience
        (overload early-shed), not transport evidence -- counting it
        would let one slow storm permanently degrade the ring to tcp for
        the client's lifetime (there is no shm re-promotion probe)."""
        from karpenter_tpu.solver import shm as shm_mod

        if self._ring is None or isinstance(exc, shm_mod.ShmPeerGoneError):
            return
        if isinstance(exc, TimeoutError) and getattr(self, "_budget_clamped", False):
            return
        self._shm_failures += 1

    def cancel_inflight(self) -> None:
        """Out-of-band cancellation for the stuck-tick watchdog
        (karpenter_tpu/overload.py): tear the TRANSPORT down WITHOUT
        taking the client lock -- the wedged thread holds it across its
        blocking read, so close() here would block the watchdog instead
        of unsticking the tick. Closing the ring endpoint flips its
        closed flag (the blocked ring wait's liveness check raises
        ShmError within milliseconds) and shutting the socket down makes
        a blocked recv return EOF; either way the wedged call surfaces a
        ConnectionError into the normal degrade ladder, which then
        closes the client PROPERLY under the lock."""
        ring, sock = self._ring, self._sock
        try:
            if ring is not None:
                ring.close()
        except Exception:  # noqa: BLE001 -- cancellation is best-effort
            metrics.HANDLED_ERRORS.inc(site="rpc.cancel_inflight")
        try:
            if sock is not None:
                sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            # replies can no longer arrive on this stream: fail their slots
            # so a later finish_solve_compact raises instead of hanging
            for h in self._pending:
                if h.outcome is None:
                    h.outcome = ("err", ConnectionError("connection closed with reply in flight"))
            self._pending.clear()
            if self._ring is not None:
                self._ring.close()      # sets the client-closed flag
                self._ring = None
            if self._ring_seg is not None:
                self._ring_seg.close()  # unmap only; the server unlinks
                self._ring_seg = None
            self._wire = None
            if self._track_transport:
                metrics.WIRE_TRANSPORT.set(0.0, transport="shm")
                metrics.WIRE_TRANSPORT.set(0.0, transport="tcp")
            if self._sock is not None:
                self._sock.close()
                self._sock = None
            self._features = None  # the replacement server may differ
            # eager, not on-reconnect: between close() and the next _conn()
            # a begin_solve_compact checks membership BEFORE connecting, and
            # a stale hit would skip the re-stage the replacement sidecar
            # needs (the breaker's promotion hook relies on this to gate
            # re-promotion on a catalog re-stage)
            self._staged_seqnums.clear()
            self._staged_tepochs.clear()
            # delta bases die with the connection for the same reason: the
            # replacement sidecar holds no epochs, and a stale base would
            # cost one unknown-epoch roundtrip per seqnum before recovering
            self._epoch_bases.clear()

    def _op_header(self, **fields) -> dict:
        """An op header carrying this replica's tenant id (fleet
        topology); single-cluster clients omit the field entirely so the
        frames are byte-identical to the pre-fleet protocol."""
        if self.tenant is not None:
            fields["tenant"] = self.tenant
        return fields

    # -- request pipelining (the async solve path) ---------------------------
    def _drain_pending(self, target: Optional[_PendingReply] = None) -> None:
        """Receive outstanding replies in FIFO order (all of them, or up to
        and including `target`). MUST run before any synchronous roundtrip
        so a pipelined reply is never misattributed to a later request.
        Caller holds the lock."""
        self._apply_budget_timeout()
        while self._pending:
            head = self._pending[0]
            if head.outcome is None:
                try:
                    header, tensors = _recv_frame(self._wire)
                    head.outcome = ("ok", header, tensors)
                    if self._ring is not None:
                        self._shm_failures = 0
                except (ConnectionError, OSError) as e:
                    # the stream is unrecoverable mid-pipeline: every
                    # outstanding reply is lost with it
                    self._wire_failed(e)
                    for h in self._pending:
                        if h.outcome is None:
                            h.outcome = ("err", e)
                    self._pending.clear()
                    self.close()
                    return
            done = self._pending.popleft()
            if target is not None and done is target:
                return

    def begin_solve_compact(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, nnz_max: int = 0, objective: str = "price",
    ) -> _PendingReply:
        """Dispatch a compact solve WITHOUT waiting for the reply: the
        request frame streams to the sidecar while it may still be
        computing a prior in-flight solve (request pipelining on the
        strict request/response framing -- replies return in request
        order). At most MAX_INFLIGHT (2: one computing, one streaming)
        may be outstanding; a deeper dispatch raises rather than silently
        buffering stale decisions. Claim the reply with
        finish_solve_compact. Unlike the synchronous op, an unknown
        seqnum surfaces as StaleSeqnumError -- no silent restage."""
        if not nnz_max:
            nnz_max = ffd.nnz_budget(class_set.c_pad, g_max)
        header = self._op_header(
            op="solve_compact", seqnum=seqnum, g_max=g_max,
            nnz_max=nnz_max, objective=objective,
        )
        # trace-id propagation: the DISPATCHING tick's context rides the
        # request header; the server echoes it (plus its stage timings)
        # in the reply, so the claim side can graft the stages even when
        # the reply is drained a tick later under a different trace
        ctx = tracing.TRACER.inject()
        if ctx is not None:
            header["trace"] = ctx
        with self._lock:
            if len(self._pending) >= self.MAX_INFLIGHT:
                raise RuntimeError(
                    f"solve pipeline full: {len(self._pending)} requests already in flight"
                )
            if seqnum not in self._staged_seqnums:
                # staging is a synchronous roundtrip: the pipe must be
                # clear first or the stage reply would interleave
                self._drain_pending()
                self.stage_catalog(seqnum, catalog)
            # delta class shipping: may rewrite the header into a
            # solve_delta op and return only the dirty rows (feature-gated;
            # full ship otherwise -- the server reassembles identically)
            tensors = self._delta_request(seqnum, class_set, header)
            self._maybe_reply_v2(header)
            sock = self._conn()
            try:
                _send_frame(sock, header, tensors)
            except (ConnectionError, OSError) as e:
                # a PARTIAL frame may be on the wire: the stream is
                # desynchronized, and a later synchronous fallback would
                # write its frame into the torn one's remainder -- close
                # so that fallback reconnects onto a clean stream
                self._wire_failed(e)
                self.close()
                raise
            handle = _PendingReply(seqnum, g_max=g_max)
            self._pending.append(handle)
            return handle

    def finish_solve_compact(self, handle: _PendingReply) -> ffd.CompactDecision:
        """Claim a begin_solve_compact reply (blocking until it arrives).
        Raises StaleSeqnumError on unknown-seqnum, ConnectionError when
        the stream died with the reply in flight."""
        with self._lock:
            if handle.outcome is None:
                self._drain_pending(target=handle)
            if handle.outcome is None:
                raise ConnectionError("reply lost: not in the pipeline FIFO")
        kind, *rest = handle.outcome
        if kind == "err":
            raise rest[0]
        header, out = rest
        if not header.get("ok"):
            err = str(header.get("error", ""))
            if err.startswith("StaleTopologyError"):
                # the sidecar's device mesh changed membership while this
                # solve was in flight (server errors cross the wire as
                # "ClassName: message"). The server transparently restages
                # the seqnum onto the surviving devices at its next touch,
                # so the typed re-raise rides the existing StaleSeqnumError
                # barrier-fallback rung -- one synchronous retry against
                # the SAME seqnum lands on the new topology epoch.
                metrics.MESH_STALE_SOLVES.inc(site="client-wire")
                raise StaleTopologyError(err)
            if err == "unknown-epoch":
                # the sidecar lost the base epoch mid-flight: drop the
                # client base so the synchronous retry ships full, and
                # surface the gap on the StaleSeqnumError contract
                self._drop_epoch(handle.seqnum)
                metrics.DELTA_EPOCH_RESTAGES.inc()
                raise StaleEpochError(err)
            if err == "unknown-seqnum":
                self._drop_epoch(handle.seqnum)
                raise StaleSeqnumError(err)
            raise RuntimeError(f"solve failed: {err}")
        # graft the echoed server-side stage spans under the span covering
        # this claim (the solver's "wire" span); the echo's trace context
        # links back to the dispatching tick when that differs
        tracing.TRACER.graft(header)
        return self._compact_from_reply(header, out, handle.g_max)

    def _compact_from_reply(self, header: dict, out: Dict[str, np.ndarray],
                            g_max: int) -> "ffd.CompactDecision":
        """A CompactDecision from a solve reply of either shape (v1 dense
        or v2 trimmed), recording the reply's wire payload bytes."""
        self.last_reply = {
            "bytes": int(sum(a.nbytes for a in out.values())),
            "v": int(header.get("v", 1)),
        }
        if int(header.get("v", 1)) >= 2:
            return expand_reply_v2(header, out, g_max)
        fields = {n: out[n] for n in ffd.CompactDecision._fields}
        fields["nnz"] = fields["nnz"].reshape(())
        fields["n_open"] = fields["n_open"].reshape(())
        return ffd.CompactDecision(**fields)

    def _maybe_reply_v2(self, header: dict) -> None:
        """Request the trimmed reply shape when the op supports it and
        the server advertises the feature (cached per connection -- the
        probe rides the same ping `features()` already uses)."""
        if not self.reply_v2 or header.get("op") not in ("solve_compact", "solve_delta"):
            return
        try:
            if "reply_v2" in self.features():
                header["reply"] = 2
        except (ConnectionError, OSError):
            # let the solve's own send surface the connection state
            pass

    def features(self) -> frozenset:
        """Server feature set, probed once per connection via ping (an
        older server omits the field -> empty set). Callers that DEPEND on
        a semantic the server may lack check here and fall back -- e.g.
        taint-gated merged batches go to the oracle when 'join_allowed' is
        absent, because an old server would silently drop the mask and
        pack pods into pools whose taints they do not tolerate."""
        with self._lock:
            if self._features is None:
                header, _ = self._roundtrip({"op": "ping"})
                self._features = frozenset(header.get("features", ()))
            return self._features

    def _packed_wire(self) -> bool:
        """True when class masks should ship bit-packed: enabled on this
        client AND negotiated with the server. A wire error here answers
        False (full-width is always understood) and lets the solve's own
        send surface the connection state -- same discipline as the
        solve_delta gate in _delta_request."""
        if not self.packed_masks:
            return False
        try:
            return "packed_masks" in self.features()
        except (ConnectionError, OSError):
            return False

    def _roundtrip(self, header, tensors=()):
        with self._lock:
            # pipelined replies still on the stream MUST drain first, or
            # this request would read an earlier solve's reply as its own
            self._drain_pending()
            sock = self._conn()
            self._apply_budget_timeout()
            try:
                _send_frame(sock, header, tensors)
                out = _recv_frame(sock)
                if self._ring is not None:
                    self._shm_failures = 0
                return out
            except (ConnectionError, OSError) as e:
                self._wire_failed(e)
                self.close()  # one reconnect attempt per call
                sock = self._conn()
                self._apply_budget_timeout()
                try:
                    _send_frame(sock, header, tensors)
                    out = _recv_frame(sock)
                except (ConnectionError, OSError) as e2:
                    # the retry leg's stream failures count toward the shm
                    # degrade ladder too, or a persistently corrupt ring
                    # takes twice the documented failures to stick to tcp
                    self._wire_failed(e2)
                    self.close()  # leave a clean slate for the next call
                    raise
                if self._ring is not None:
                    self._shm_failures = 0
                return out

    def ping(self) -> bool:
        header, _ = self._roundtrip({"op": "ping"})
        return bool(header.get("ok"))

    def stage_catalog(self, seqnum: str, catalog: encode.CatalogTensors) -> None:
        header = {
            "op": "stage", "seqnum": seqnum, "names": catalog.names,
            "k_real": catalog.k_real, "zones": catalog.zones, "words": catalog.words,
        }
        tensors = [
            ("cap", catalog.cap), ("tcode", catalog.tcode), ("tnum", catalog.tnum),
            ("tnum_present", catalog.tnum_present), ("tzone", catalog.tzone),
            ("tcap", catalog.tcap), ("price", catalog.price),
        ]
        resp, _ = self._roundtrip(header, tensors)
        if not resp.get("ok"):
            raise RuntimeError(f"stage failed: {resp.get('error')}")
        with self._lock:
            self._staged_seqnums.add(seqnum)
            if resp.get("tepoch") is not None:
                self._staged_tepochs[seqnum] = int(resp["tepoch"])

    @staticmethod
    def _class_tensors(class_set: encode.PodClassSet, packed: bool = False):
        """The pod-class tensor list both solve ops ship (ONE copy: a new
        class tensor must appear here or the dense and compact paths
        desynchronize). With `packed` (the negotiated "packed_masks" wire
        form) the [C, K] bool masks ship as [C, KW] uint32 words -- the
        server's kernels dispatch on dtype, so no header flag is needed
        and the decision is bit-identical either way."""

        def _mask(m):
            if packed and not packing.is_packed(m):
                return packing.pack_mask(m)
            if not packed and packing.is_packed(m):
                # a pre-packed class set meeting a server that never
                # negotiated the form: ship the full-width bool rows the
                # old server understands (KW*32 == k_pad exactly -- k_pad
                # is a multiple of 128)
                return packing.unpack_mask(m, m.shape[-1] * packing.WORD_BITS)
            return m

        return [
            ("req", class_set.req), ("count", class_set.count),
            ("env_count", class_set.env_count),
            ("allowed", np.concatenate(class_set.allowed, axis=1)),
            ("num_lo", class_set.num_lo), ("num_hi", class_set.num_hi),
            ("azone", class_set.azone), ("acap", class_set.acap),
            ("schedulable", class_set.schedulable),
            ("node_overhead", class_set.node_overhead),
        ] + (
            [("open_allowed", _mask(class_set.open_allowed))]
            if getattr(class_set, "open_allowed", None) is not None else []
        ) + (
            [("join_allowed", _mask(class_set.join_allowed))]
            if getattr(class_set, "join_allowed", None) is not None else []
        )

    # -- delta class shipping (the incremental-tick wire layer) ---------------
    def _next_epoch(self) -> str:
        self._epoch_counter += 1
        return f"{self._epoch_prefix}-{self._epoch_counter}"

    def _drop_epoch(self, seqnum: str) -> None:
        with self._lock:
            self._epoch_bases.pop(seqnum, None)

    def _store_base(self, seqnum: str, epoch: str, named: Dict[str, np.ndarray]) -> None:
        """Record the class tensor state the server now holds for this
        seqnum (one copy per tensor: the caller's arrays belong to a live
        PodClassSet). Caller holds the lock."""
        self._epoch_bases.pop(seqnum, None)  # LRU refresh
        self._epoch_bases[seqnum] = (
            epoch, {n: np.array(a) for n, a in named.items()}
        )
        while len(self._epoch_bases) > 4:
            self._epoch_bases.pop(next(iter(self._epoch_bases)))

    def _patch_base(self, seqnum: str, epoch: str, b: Dict[str, np.ndarray],
                    rows: np.ndarray, named: Dict[str, np.ndarray],
                    row_names=PER_CLASS_TENSORS) -> None:
        """Advance a delta chain's stored base IN PLACE: O(dirty rows)
        host work per tick, like everything else in the engine -- a full
        re-copy here would spend memory bandwidth on exactly the bytes
        the delta ship avoids. Caller holds the lock; `b` is this
        client's private copy (never aliased into a frame)."""
        if rows.size:
            for name in row_names:
                b[name][rows] = named[name][rows]
        b["node_overhead"] = np.array(named["node_overhead"])
        self._epoch_bases.pop(seqnum, None)  # LRU refresh
        self._epoch_bases[seqnum] = (epoch, b)

    def _bypass_delta(self, full_bytes: int):
        self.last_delta = {
            "mode": "bypass", "rows": -1,
            "payload_bytes": full_bytes, "full_bytes": full_bytes,
        }
        metrics.DELTA_SOLVES.inc(mode="bypass")
        metrics.DELTA_PAYLOAD_BYTES.observe(full_bytes, mode="bypass")

    def _delta_request(self, seqnum: str, class_set: encode.PodClassSet, header: dict):
        """The tensors to ship for one compact solve, rewriting `header`
        into a solve_delta op when the delta path applies. Three modes
        (last_delta["mode"], mirrored into karpenter_scheduler_delta_*):

        - "delta": a base epoch for this seqnum exists with matching
          shapes and few rows changed -- ship only the dirty rows plus
          the epoch being patched;
        - "full": ship everything, establishing a new epoch server-side
          (the steady state's first tick, a shape change, or a high-churn
          tick past DELTA_MAX_DIRTY_FRACTION);
        - "bypass": delta not applicable (disabled, dense op, server
          without the feature, or merged-multipool masks present).

        The server reassembles the identical tensor set in every mode, so
        the decision is bit-identical by construction (tests/test_delta.py
        asserts it differentially). Caller holds the lock."""
        tensors = self._class_tensors(class_set, packed=self._packed_wire())
        full_bytes = int(sum(a.nbytes for _, a in tensors))
        if not self.delta or header.get("op") != "solve_compact":
            self._bypass_delta(full_bytes)
            return tensors
        if overload.sheds_delta():
            # brownout ladder rung 3 (karpenter_tpu/overload.py): under
            # sustained deadline pressure the delta-epoch machinery stands
            # down -- no staging diffs, no epoch bookkeeping, and above
            # all no unknown-epoch restage retry roundtrips. The full ship
            # is bit-identical by construction; the ladder's hysteretic
            # recovery restores delta shipping (the first solve after
            # re-entry establishes a fresh epoch).
            self._bypass_delta(full_bytes)
            return tensors
        named = dict(tensors)
        if any(
            n in named and not packing.is_packed(named[n])
            for n in PACKED_MASK_TENSORS
        ):
            # merged multi-pool, FULL-WIDTH masks: the bool [C, K] rows
            # dominate the payload and are re-derived per tick -- the
            # delta path stands down. Packed [C, KW] uint32 masks are an
            # eighth the size and row-patch below like any class tensor.
            self._bypass_delta(full_bytes)
            return tensors
        row_names = list(PER_CLASS_TENSORS) + [
            n for n in PACKED_MASK_TENSORS if n in named
        ]
        try:
            if "solve_delta" not in self.features():
                self._bypass_delta(full_bytes)
                return tensors
        except (ConnectionError, OSError):
            # let the solve's own send surface the connection state
            self._bypass_delta(full_bytes)
            return tensors
        epoch = self._next_epoch()
        base = self._epoch_bases.get(seqnum)
        if base is not None:
            b = base[1]
            if set(b) == set(named) and all(
                b[n].shape == named[n].shape and b[n].dtype == named[n].dtype
                for n in named
            ):
                changed = np.zeros((named["req"].shape[0],), dtype=bool)
                for name in row_names:
                    diff = named[name] != b[name]
                    if diff.ndim > 1:
                        diff = diff.any(axis=tuple(range(1, diff.ndim)))
                    changed |= diff
                rows = np.nonzero(changed)[0]
                if rows.size <= int(changed.size * DELTA_MAX_DIRTY_FRACTION):
                    header["op"] = "solve_delta"
                    header["epoch"] = epoch
                    header["base"] = base[0]
                    header["rows"] = [int(r) for r in rows]
                    out = [
                        (name, np.ascontiguousarray(named[name][rows]))
                        for name in row_names
                    ]
                    # whole-set tensors always ship (tiny [R] vector)
                    out.append(("node_overhead", named["node_overhead"]))
                    self._patch_base(seqnum, epoch, b, rows, named, row_names)
                    payload = int(sum(a.nbytes for _, a in out))
                    self.last_delta = {
                        "mode": "delta", "rows": int(rows.size),
                        "payload_bytes": payload, "full_bytes": full_bytes,
                    }
                    metrics.DELTA_SOLVES.inc(mode="delta")
                    metrics.DELTA_ROWS_SHIPPED.inc(int(rows.size))
                    metrics.DELTA_PAYLOAD_BYTES.observe(payload, mode="delta")
                    return out
        # full ship, establishing the epoch the next tick patches
        header["op"] = "solve_delta"
        header["epoch"] = epoch
        header["base"] = None
        self._store_base(seqnum, epoch, named)
        self.last_delta = {
            "mode": "full", "rows": int(class_set.c_pad),
            "payload_bytes": full_bytes, "full_bytes": full_bytes,
        }
        metrics.DELTA_SOLVES.inc(mode="full")
        metrics.DELTA_PAYLOAD_BYTES.observe(full_bytes, mode="full")
        return tensors

    def debug_info(self) -> dict:
        """The server's staging debug document (the "debug" op: staged
        seqnums, class epochs, LRU eviction counts) -- the sidecar-topology
        source for /debug/solver."""
        header, _ = self._roundtrip({"op": "debug"})
        return header

    def _solve_op(self, op_header: dict, seqnum: str, catalog, class_set):
        """Shared stage-if-needed + solve + staging-gap retry ladder:
        unknown-epoch drops the delta base and re-ships full; unknown-
        seqnum re-stages the catalog and retries (the full reship also
        re-establishes the class epoch). Each rung fires at most once."""
        ctx = tracing.TRACER.inject()
        if ctx is not None:
            op_header = dict(op_header, trace=ctx)
        with self._lock:  # atomic stage-then-solve (reentrant)
            if seqnum not in self._staged_seqnums:
                self.stage_catalog(seqnum, catalog)
            header = dict(op_header)
            tensors = self._delta_request(seqnum, class_set, header)
            self._maybe_reply_v2(header)
            resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok") and resp.get("error") == "unknown-epoch":
                self._drop_epoch(seqnum)
                metrics.DELTA_EPOCH_RESTAGES.inc()
                header = dict(op_header)
                tensors = self._delta_request(seqnum, class_set, header)
                self._maybe_reply_v2(header)
                resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok") and resp.get("error") == "unknown-seqnum":
                # server restarted / evicted: re-stage once and retry with
                # a full class ship (the old epoch died with the staging)
                self._drop_epoch(seqnum)
                self.stage_catalog(seqnum, catalog)
                header = dict(op_header)
                tensors = self._delta_request(seqnum, class_set, header)
                self._maybe_reply_v2(header)
                resp, out = self._roundtrip(header, tensors)
            if (
                not resp.get("ok")
                and str(resp.get("error", "")).startswith("StaleTopologyError")
            ):
                # the sidecar's device mesh changed membership mid-solve
                # (device lost, quarantine, or return). Its staging layer
                # restages the seqnum onto the current device set on the
                # next touch, so one retry -- same seqnum, same tensors --
                # lands on the new topology epoch. At most once: a second
                # stale answer surfaces as the failure it is and rides the
                # breaker ladder like any other wire fault.
                metrics.MESH_STALE_SOLVES.inc(site="client-sync")
                header = dict(op_header)
                tensors = self._delta_request(seqnum, class_set, header)
                self._maybe_reply_v2(header)
                resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok"):
                raise RuntimeError(f"solve failed: {resp.get('error')}")
            tracing.TRACER.graft(resp)
            return resp, out

    def solve_classes(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 512, objective: str = "price",
    ) -> ffd.SolveOutputs:
        header = self._op_header(
            op="solve", seqnum=seqnum, g_max=g_max, objective=objective
        )
        _, out = self._solve_op(header, seqnum, catalog, class_set)
        return ffd.SolveOutputs(**{n: out[n] for n in ffd.SolveOutputs._fields})

    def solve_classes_compact(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, nnz_max: int = 0, objective: str = "price",
    ) -> ffd.CompactDecision:
        """The ~50 KB response variant of solve_classes (the deployed
        TPU-VM topology's hot path); the caller expands with
        ffd.expand_compact and falls back to solve_classes on overflow."""
        if not nnz_max:
            nnz_max = ffd.nnz_budget(class_set.c_pad, g_max)
        header = self._op_header(
            op="solve_compact", seqnum=seqnum, g_max=g_max,
            nnz_max=nnz_max, objective=objective,
        )
        resp, out = self._solve_op(header, seqnum, catalog, class_set)
        return self._compact_from_reply(resp, out, g_max)

    def solve_convex(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, iters: Optional[int] = None, objective: str = "price",
    ):
        """The convex tier's wire solve: one synchronous roundtrip through
        the same stage-if-needed + staging-gap retry ladder as every solve
        op. Returns (dense decode tuple, info dict) where the dense tuple
        is the differential WINNER the sidecar chose and info carries the
        certificate: winner, lower (the LP bound, $/h), iterations,
        fallback (rounding produced no candidate), price_ffd /
        price_convex. Callers gate on `\"convex\" in features()` first --
        an old sidecar answers unknown-op and this raises RuntimeError."""
        fields = dict(
            op="solve_convex", seqnum=seqnum, g_max=g_max, objective=objective,
        )
        if iters is not None:
            fields["iters"] = int(iters)
        header = self._op_header(**fields)
        resp, out = self._solve_op(header, seqnum, catalog, class_set)
        dense = (
            np.asarray(out["take"]), np.asarray(out["unplaced"]),
            int(resp["n_open"]), np.asarray(out["gmask"]),
            np.asarray(out["gzone"]), np.asarray(out["gcap"]),
        )
        info = {
            "winner": str(resp.get("winner", "ffd")),
            "lower": resp.get("lower"),
            "iterations": int(resp.get("iterations", 0)),
            "fallback": bool(resp.get("fallback", False)),
            "price_ffd": resp.get("price_ffd"),
            "price_convex": resp.get("price_convex"),
        }
        return dense, info

    # -- batched consolidation (solver/disrupt, the solve_disrupt op) ---------
    def _disrupt_roundtrip(self, header: dict, tensors, seqnum, catalog):
        """stage-if-needed + solve + one unknown-seqnum restage retry:
        the disrupt op's staging ladder, the same contract as _solve_op
        (the depoch fallback tensor makes a lost disrupt epoch a
        non-error, so only the catalog gap needs a rung)."""
        with self._lock:  # atomic stage-then-solve (reentrant)
            if seqnum is not None and seqnum not in self._staged_seqnums:
                self.stage_catalog(seqnum, catalog)
            resp, out = self._roundtrip(header, tensors)
            if (
                not resp.get("ok") and resp.get("error") == "unknown-seqnum"
                and seqnum is not None
            ):
                # sidecar restarted / evicted: re-stage once and retry
                self.stage_catalog(seqnum, catalog)
                resp, out = self._roundtrip(header, tensors)
            if (
                not resp.get("ok")
                and str(resp.get("error", "")).startswith("StaleTopologyError")
            ):
                # mesh membership changed mid-dispatch: server-side
                # restage is transparent on the next touch, retry once
                metrics.MESH_STALE_SOLVES.inc(site="client-disrupt")
                resp, out = self._roundtrip(header, tensors)
            if not resp.get("ok"):
                raise RuntimeError(f"solve_disrupt failed: {resp.get('error')}")
            tracing.TRACER.graft(resp)
            return out

    def solve_disrupt_repack(
        self, repack: Dict[str, np.ndarray], *,
        seqnum: Optional[str] = None, catalog=None,
        replace: Optional[Dict[str, np.ndarray]] = None,
    ):
        """Dispatch one batched consolidation repack (and, when `replace`
        names a staged catalog context, the first pool's replacement
        search in the same roundtrip). Returns (depoch, reply tensors):
        the depoch names the leftover tensor now staged sidecar-side for
        this sweep's later replacement passes."""
        failpoints.eval("rpc.disrupt.dispatch")
        with self._lock:
            depoch = self._next_epoch()
            header = self._op_header(op="solve_disrupt", depoch=depoch)
            tensors = list(repack.items())
            if replace is not None and seqnum is not None:
                header["seqnum"] = seqnum
                tensors += list(replace.items())
            out = self._disrupt_roundtrip(header, tensors, seqnum, catalog)
            return depoch, out

    def solve_disrupt_replace(
        self, depoch: str, *, seqnum: str, catalog,
        replace: Dict[str, np.ndarray],
        leftover: Optional[np.ndarray] = None,
    ) -> Dict[str, np.ndarray]:
        """One pool's replacement search against an in-flight sweep's
        staged leftover (`depoch`) and the catalog staged under `seqnum`.
        `leftover` rides along as the stateless fallback for a
        pressure-evicted depoch."""
        failpoints.eval("rpc.disrupt.dispatch")
        header = self._op_header(op="solve_disrupt", depoch=depoch, seqnum=seqnum)
        tensors = list(replace.items())
        if leftover is not None:
            tensors.append(("leftover", leftover))
        return self._disrupt_roundtrip(header, tensors, seqnum, catalog)


def serve_main(argv=None) -> int:
    """`python -m karpenter_tpu.solver.rpc` -- run the solver sidecar (the
    process that lives on the TPU VM). Default transport: a mode-0600 UNIX
    socket. TCP (--host/--port) requires --token-file / $KARPENTER_TPU_
    SOLVER_TOKEN, or the explicit --insecure flag; --tls-cert/--tls-key
    add TLS on top."""
    import argparse

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument(
        "--socket", default=None, metavar="PATH",
        help="UNIX socket path (default: $XDG_RUNTIME_DIR/karpenter-tpu-solver.sock, "
             "or a per-user /tmp dir; ignored when --host is given)",
    )
    parser.add_argument("--host", default=None, help="TCP bind address (requires a token)")
    parser.add_argument("--port", type=int, default=7077)
    parser.add_argument(
        "--token-file", default=None,
        help=f"file holding the shared token (or set ${TOKEN_ENV})",
    )
    parser.add_argument(
        "--insecure", action="store_true",
        help="allow a tokenless TCP listener (explicit operator decision)",
    )
    parser.add_argument("--tls-cert", default=None)
    parser.add_argument("--tls-key", default=None)
    parser.add_argument(
        "--handshake-timeout", type=float, default=30.0,
        help="TLS-handshake budget per connection (seconds)",
    )
    parser.add_argument(
        "--shm", action=argparse.BooleanOptionalAction, default=None,
        help="advertise the shared-memory ring transport for colocated "
        f"clients (default on; ${SHM_ENV}=0 also disables)",
    )
    parser.add_argument(
        "--shm-dir", default=None, metavar="DIR",
        help="ring-segment directory (default /dev/shm, else a per-user dir)",
    )
    parser.add_argument(
        "--shm-size", type=int, default=None, metavar="BYTES",
        help="ring size per direction (default 8 MiB or "
        f"${'KARPENTER_TPU_SHM_SIZE'}; see docs/operations.md for sizing)",
    )
    parser.add_argument(
        "--mesh", default=None, metavar="SPEC",
        help="shard the production solve across a device mesh: a count "
        "('8') or an NxM (hosts x devices) layout ('2x4'); default "
        "$KARPENTER_TPU_MESH, else single-device",
    )
    parser.add_argument(
        "--coalesce", action="store_true",
        help="fleet topology: batch concurrent solves from N operator "
        "replicas into shared dispatch windows (deterministic tenant "
        "ordering, per-tenant breaker; see docs/operations.md)",
    )
    parser.add_argument(
        "--tenant-budget", type=float, default=0.0, metavar="SECONDS",
        help="per-tenant dispatch deadline budget under --coalesce "
        "(0 = unbounded); a blown budget refuses THAT tenant's solve "
        "into its client's overload ladder",
    )
    args = parser.parse_args(argv)

    # persistent XLA compilation cache (solver/aot.py layout), enabled
    # BEFORE the first jit (mesh engine construction below may trace):
    # a sidecar restart then reuses every backend compile from the
    # previous incarnation, including the sharded mesh programs the
    # serialized-executable store cannot cover (device-assembly-pinned).
    # Failure returns None and the sidecar runs uncached -- a cache
    # optimization must never abort startup.
    from karpenter_tpu.utils import enable_jax_compilation_cache

    enable_jax_compilation_cache()

    token = None
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    ctx = None
    if args.tls_cert:
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(args.tls_cert, args.tls_key)
    shm_kw = dict(shm=args.shm, shm_dir=args.shm_dir, shm_size=args.shm_size)
    mesh = None
    mesh_spec = args.mesh if args.mesh is not None else os.environ.get("KARPENTER_TPU_MESH")
    if mesh_spec:
        from karpenter_tpu.fleet.shard import parse_mesh_spec

        mesh = parse_mesh_spec(mesh_spec)
    if args.coalesce:
        from karpenter_tpu.fleet.coalesce import DispatchCoalescer

        shm_kw["coalescer"] = DispatchCoalescer(budget_s=args.tenant_budget)
    if mesh is not None:
        shm_kw["mesh"] = mesh
    if args.host is not None:
        server = SolverServer(
            args.host, args.port, token=token,
            insecure_tcp=args.insecure, ssl_context=ctx,
            handshake_timeout=args.handshake_timeout, **shm_kw,
        ).start()
        print(
            f"solver service listening on {server.address[0]}:{server.address[1]}",
            flush=True,
        )
    else:
        if args.tls_cert or args.tls_key or args.insecure:
            # accepting-and-ignoring a security flag is how plaintext
            # traffic ships with an operator believing it is encrypted
            parser.error("--tls-cert/--tls-key/--insecure apply to TCP mode (--host)")
        path = args.socket or default_socket_path()
        if args.socket:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        else:
            ensure_socket_dir(path)  # squatting defense for the default dir
        server = SolverServer(path=path, token=token, **shm_kw).start()
        print(f"solver service listening on {path}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())
