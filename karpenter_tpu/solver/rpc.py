"""Solver service boundary: the decision plane as a network sidecar.

SURVEY.md section 2.4/5 maps the reference's cloud-RPC seam (aws-sdk over
HTTPS with batching) to an RPC boundary between the host-side reconcilers
and the solver process on the TPU VM. This module implements that boundary
as a dependency-free length-prefixed binary protocol over TCP (the image
ships no grpc; the frame layout below is trivially portable to gRPC
streaming messages later):

    frame := u32 header_len | header_json | payload_bytes
    header := {"op"|"ok": ..., meta..., "tensors": [{name, dtype, shape}]}
    payload := the tensors' raw little-endian buffers, concatenated

Design constraints carried over from the in-process solver (SURVEY.md
section 7 hard part #6 -- the 100 ms budget leaves no room for re-shipping
state): the catalog tensors are staged on the server ONCE per catalog
seqnum (`stage` op); each `solve` ships only the pod-class tensors
(~100 KB at 50k-pod scale) and returns the solve outputs; connections are
persistent (one socket, many solves).

Server-side compute = the same jitted kernels the in-process path uses
(solver/ffd.py), so differential guarantees carry over unchanged.
"""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.solver import encode, ffd

_LEN = struct.Struct("<I")
MAX_FRAME = 256 * 1024 * 1024


# -- framing -----------------------------------------------------------------

def _send_frame(sock: socket.socket, header: dict, tensors: Sequence[Tuple[str, np.ndarray]] = ()) -> None:
    header = dict(header)
    header["tensors"] = [
        {"name": name, "dtype": str(a.dtype), "shape": list(a.shape)} for name, a in tensors
    ]
    hb = json.dumps(header).encode()
    parts = [_LEN.pack(len(hb)), hb]
    for _, a in tensors:
        parts.append(np.ascontiguousarray(a).tobytes())
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[dict, Dict[str, np.ndarray]]:
    (hlen,) = _LEN.unpack(_recv_exact(sock, 4))
    if hlen > MAX_FRAME:
        raise ConnectionError(f"oversized header ({hlen} bytes)")
    header = json.loads(_recv_exact(sock, hlen))
    tensors: Dict[str, np.ndarray] = {}
    total = 0
    for spec in header.get("tensors", ()):
        dtype = np.dtype(spec["dtype"])
        shape = [int(s) for s in spec["shape"]]
        if any(s < 0 for s in shape):
            raise ConnectionError(f"negative dimension in {spec}")
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = count * dtype.itemsize
        total += nbytes
        # bound the payload BEFORE allocating: a hostile header must not be
        # able to make the sidecar allocate unbounded buffers
        if nbytes > MAX_FRAME or total > MAX_FRAME:
            raise ConnectionError(f"oversized tensor payload ({total} bytes)")
        raw = _recv_exact(sock, nbytes)
        tensors[spec["name"]] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return header, tensors


# -- server ------------------------------------------------------------------

class _StagedEntry:
    def __init__(self, staged, offsets, words):
        self.staged = staged
        self.offsets = offsets
        self.words = words


class SolverServer:
    """Serves stage/solve/ping over persistent TCP connections. One staged
    catalog per seqnum (bounded LRU of 4: catalogs change 12-hourly)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._staged: Dict[str, _StagedEntry] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        header, tensors = _recv_frame(self.request)
                        outer._dispatch(self.request, header, tensors)
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SolverServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, sock, header: dict, tensors: Dict[str, np.ndarray]) -> None:
        op = header.get("op")
        try:
            if op == "ping":
                _send_frame(sock, {"ok": True})
            elif op == "stage":
                self._op_stage(sock, header, tensors)
            elif op == "solve":
                self._op_solve(sock, header, tensors)
            elif op == "solve_compact":
                self._op_solve_compact(sock, header, tensors)
            else:
                _send_frame(sock, {"ok": False, "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 -- errors cross the wire
            _send_frame(sock, {"ok": False, "error": f"{type(e).__name__}: {e}"})

    def _op_stage(self, sock, header: dict, t: Dict[str, np.ndarray]) -> None:
        seqnum = str(header["seqnum"])
        words = tuple(int(w) for w in header["words"])
        catalog = encode.CatalogTensors(
            names=list(header["names"]), k_real=int(header["k_real"]),
            k_pad=int(t["cap"].shape[0]), cap=t["cap"], tcode=t["tcode"],
            tnum=t["tnum"], tnum_present=t["tnum_present"], tzone=t["tzone"],
            tcap=t["tcap"], price=t["price"], vocabs=[], zones=list(header["zones"]),
            words=list(words),
        )
        staged, offsets, words = ffd.stage_catalog(catalog)
        with self._lock:
            if len(self._staged) >= 4:
                self._staged.pop(next(iter(self._staged)))
            self._staged[seqnum] = _StagedEntry(staged, offsets, words)
        _send_frame(sock, {"ok": True, "seqnum": seqnum})

    def _staged_inputs(self, sock, header: dict, t: Dict[str, np.ndarray]):
        """(entry, SolveInputs) for the staged catalog named by the header's
        seqnum (LRU-touched), or None after sending the unknown-seqnum error
        (the client re-stages on that contract)."""
        seqnum = str(header["seqnum"])
        with self._lock:
            entry = self._staged.get(seqnum)
            if entry is not None:
                # LRU touch: re-insert so eviction pops the least recently
                # USED catalog, not the oldest staged
                self._staged.pop(seqnum)
                self._staged[seqnum] = entry
        if entry is None:
            _send_frame(sock, {"ok": False, "error": "unknown-seqnum"})
            return None
        inp = ffd.SolveInputs(
            cap=entry.staged.cap, tcode=entry.staged.tcode, tnum=entry.staged.tnum,
            tnum_present=entry.staged.tnum_present, tzone=entry.staged.tzone,
            tcap=entry.staged.tcap, price=entry.staged.price,
            req=t["req"], count=t["count"], env_count=t["env_count"],
            allowed=t["allowed"], num_lo=t["num_lo"], num_hi=t["num_hi"],
            azone=t["azone"], acap=t["acap"], schedulable=t["schedulable"],
            # older clients do not send the per-node daemonset reserve;
            # zeros preserves their semantics exactly
            node_overhead=t.get(
                "node_overhead", np.zeros((t["req"].shape[1],), dtype=np.float32)
            ),
        )
        return entry, inp

    def _op_solve(self, sock, header: dict, t: Dict[str, np.ndarray]) -> None:
        import jax

        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        out = ffd.ffd_solve(
            inp, g_max=int(header["g_max"]),
            word_offsets=entry.offsets, words=entry.words,
            objective=str(header.get("objective", "price")),
        )
        arrays = jax.device_get(tuple(out))
        names = ffd.SolveOutputs._fields
        _send_frame(
            sock, {"ok": True},
            [(n, np.asarray(a)) for n, a in zip(names, arrays)],
        )

    def _op_solve_compact(self, sock, header: dict, t: Dict[str, np.ndarray]) -> None:
        """The wire-efficient solve: the decision returns as a
        CompactDecision (~50 KB) instead of the dense SolveOutputs
        (~1.5 MB) -- this boundary exists for the TPU-VM topology where the
        link is exactly the bandwidth-poor hop the compact layout is for."""
        import jax

        hit = self._staged_inputs(sock, header, t)
        if hit is None:
            return
        entry, inp = hit
        dec = ffd.ffd_solve_compact(
            inp, g_max=int(header["g_max"]), nnz_max=int(header["nnz_max"]),
            word_offsets=entry.offsets, words=entry.words,
            objective=str(header.get("objective", "price")),
        )
        arrays = jax.device_get(tuple(dec))
        names = ffd.CompactDecision._fields
        _send_frame(
            sock, {"ok": True},
            [(n, np.atleast_1d(np.asarray(a))) for n, a in zip(names, arrays)],
        )


# -- client ------------------------------------------------------------------

class SolverClient:
    """Drop-in backend for TPUSolver-shaped solves over the wire. Maintains
    one persistent connection; `solve_classes` mirrors the tensor half of
    TPUSolver.solve (the caller does host-side encode/decode)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._staged_seqnums: set = set()
        # one reentrant lock serializes the socket AND the staging set: the
        # protocol is strictly request/response on one connection, so a
        # whole roundtrip (and the stage-then-solve sequence inside
        # solve_classes) must be atomic across threads
        self._lock = threading.RLock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=self.timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._staged_seqnums.clear()
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    def _roundtrip(self, header, tensors=()):
        with self._lock:
            sock = self._conn()
            try:
                _send_frame(sock, header, tensors)
                return _recv_frame(sock)
            except (ConnectionError, OSError):
                self.close()  # one reconnect attempt per call
                sock = self._conn()
                _send_frame(sock, header, tensors)
                return _recv_frame(sock)

    def ping(self) -> bool:
        header, _ = self._roundtrip({"op": "ping"})
        return bool(header.get("ok"))

    def stage_catalog(self, seqnum: str, catalog: encode.CatalogTensors) -> None:
        header = {
            "op": "stage", "seqnum": seqnum, "names": catalog.names,
            "k_real": catalog.k_real, "zones": catalog.zones, "words": catalog.words,
        }
        tensors = [
            ("cap", catalog.cap), ("tcode", catalog.tcode), ("tnum", catalog.tnum),
            ("tnum_present", catalog.tnum_present), ("tzone", catalog.tzone),
            ("tcap", catalog.tcap), ("price", catalog.price),
        ]
        resp, _ = self._roundtrip(header, tensors)
        if not resp.get("ok"):
            raise RuntimeError(f"stage failed: {resp.get('error')}")
        with self._lock:
            self._staged_seqnums.add(seqnum)

    @staticmethod
    def _class_tensors(class_set: encode.PodClassSet):
        """The pod-class tensor list both solve ops ship (ONE copy: a new
        class tensor must appear here or the dense and compact paths
        desynchronize)."""
        return [
            ("req", class_set.req), ("count", class_set.count),
            ("env_count", class_set.env_count),
            ("allowed", np.concatenate(class_set.allowed, axis=1)),
            ("num_lo", class_set.num_lo), ("num_hi", class_set.num_hi),
            ("azone", class_set.azone), ("acap", class_set.acap),
            ("schedulable", class_set.schedulable),
            ("node_overhead", class_set.node_overhead),
        ]

    def _solve_op(self, op_header: dict, seqnum: str, catalog, class_set):
        """Shared stage-if-needed + solve + unknown-seqnum retry."""
        with self._lock:  # atomic stage-then-solve (reentrant)
            if seqnum not in self._staged_seqnums:
                self.stage_catalog(seqnum, catalog)
            tensors = self._class_tensors(class_set)
            resp, out = self._roundtrip(op_header, tensors)
            if not resp.get("ok"):
                if resp.get("error") == "unknown-seqnum":
                    # server restarted / evicted: re-stage once and retry
                    self.stage_catalog(seqnum, catalog)
                    resp, out = self._roundtrip(op_header, tensors)
                if not resp.get("ok"):
                    raise RuntimeError(f"solve failed: {resp.get('error')}")
            return out

    def solve_classes(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 512, objective: str = "price",
    ) -> ffd.SolveOutputs:
        header = {"op": "solve", "seqnum": seqnum, "g_max": g_max, "objective": objective}
        out = self._solve_op(header, seqnum, catalog, class_set)
        return ffd.SolveOutputs(**{n: out[n] for n in ffd.SolveOutputs._fields})

    def solve_classes_compact(
        self, seqnum: str, catalog: encode.CatalogTensors, class_set: encode.PodClassSet,
        g_max: int = 1024, nnz_max: int = 0, objective: str = "price",
    ) -> ffd.CompactDecision:
        """The ~50 KB response variant of solve_classes (the deployed
        TPU-VM topology's hot path); the caller expands with
        ffd.expand_compact and falls back to solve_classes on overflow."""
        if not nnz_max:
            nnz_max = ffd.nnz_budget(class_set.c_pad, g_max)
        header = {
            "op": "solve_compact", "seqnum": seqnum, "g_max": g_max,
            "nnz_max": nnz_max, "objective": objective,
        }
        out = self._solve_op(header, seqnum, catalog, class_set)
        fields = {n: out[n] for n in ffd.CompactDecision._fields}
        # scalars travel as 1-element arrays
        fields["nnz"] = fields["nnz"].reshape(())
        fields["n_open"] = fields["n_open"].reshape(())
        return ffd.CompactDecision(**fields)


def serve_main(argv=None) -> int:
    """`python -m karpenter_tpu.solver.rpc --port 7077` -- run the solver
    sidecar (the process that lives on the TPU VM)."""
    import argparse

    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    # TRUST BOUNDARY: the sidecar speaks an unauthenticated length-prefixed
    # protocol and will stage multi-MB catalogs / run solves for any peer
    # that can connect. Default to loopback; binding a routable address is
    # an explicit operator decision (front it with mTLS or network policy,
    # the way the reference trusts only the in-cluster apiserver bus).
    parser.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address (default loopback; see trust-boundary note)",
    )
    parser.add_argument("--port", type=int, default=7077)
    args = parser.parse_args(argv)
    server = SolverServer(args.host, args.port).start()
    print(f"solver service listening on {server.address[0]}:{server.address[1]}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())
