"""Dense tensor encoding: catalog and pod classes -> solver inputs.

This is the bridge between the host-side constraint algebra and the TPU
decision plane (SURVEY.md section 2.3: "this label-set constraint algebra is
the boolean-mask layer of the future TPU solver").

Encoding scheme
===============
- Resources are scaled to *small exact integers* (cpu -> millicores,
  memory -> MiB, storage -> GiB, counts as-is) so every value is < 2^24 and
  float32 arithmetic (incl. floor division) is exact -- the differential
  guarantee vs the Python oracle depends on this.
- Label constraints lower to **bitset masks over per-dimension
  vocabularies**: the catalog contributes an int32 code per (type, dim);
  a pod class contributes packed uint32 allowed-bitmasks per dim. On device,
  compat[c, k] = AND_d bit(tcode[k, d]) in allowed[c, d]. Numeric
  requirements (Gt/Lt over cpu, memory...) lower to interval tests against
  numeric catalog columns.
- Zones and capacity types are small fixed axes (Z, CT) with explicit
  boolean masks, because they are offering properties (price/availability
  vary per (type, zone, captype)), not type properties.

Pods are grouped into equivalence classes by (requests, requirements,
tolerations) -- 50k pods typically collapse to a few hundred classes, which
turns the sequential FFD loop into a short scan with large per-step
vectorized work (the shape TPUs want).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.apis import Pod, labels as wk
from karpenter_tpu.providers.instancetype.types import InstanceType
from karpenter_tpu.scheduling import Requirements, Taint, tolerates_all
from karpenter_tpu.utils import gc_paused
from karpenter_tpu.scheduling import resources as res
from karpenter_tpu.native import grouping as _native_grouping

# -- static solver shape parameters (XLA wants fixed shapes) -----------------
R = res.NUM_RESOURCE_AXES          # resource axes
Z_PAD = 8                          # zone slots
CT = 3                             # capacity types: reserved, spot, on-demand
CAPTYPE_INDEX = {wk.CAPACITY_TYPE_RESERVED: 0, wk.CAPACITY_TYPE_SPOT: 1, wk.CAPACITY_TYPE_ON_DEMAND: 2}

# label dimensions lowered to bitset vocabularies, in fixed order
LABEL_DIMS: Tuple[str, ...] = (
    wk.INSTANCE_TYPE_LABEL,
    wk.ARCH_LABEL,
    wk.OS_LABEL,
    wk.LABEL_INSTANCE_CATEGORY,
    wk.LABEL_INSTANCE_FAMILY,
    wk.LABEL_INSTANCE_GENERATION,
    wk.LABEL_INSTANCE_SIZE,
    wk.LABEL_INSTANCE_CPU_MANUFACTURER,
    wk.LABEL_INSTANCE_HYPERVISOR,
    wk.LABEL_INSTANCE_GPU_NAME,
    wk.LABEL_INSTANCE_ACCELERATOR_NAME,
    wk.LABEL_INSTANCE_LOCAL_NVME,
    wk.LABEL_INSTANCE_ENCRYPTION_IN_TRANSIT,
    wk.NODEPOOL_LABEL,
    wk.REGION_LABEL,
)
D = len(LABEL_DIMS)

# numeric dims for Gt/Lt windows
NUMERIC_DIMS: Tuple[str, ...] = (
    wk.LABEL_INSTANCE_CPU,
    wk.LABEL_INSTANCE_MEMORY,
    wk.LABEL_INSTANCE_GENERATION,
    wk.LABEL_INSTANCE_NETWORK_BANDWIDTH,
    wk.LABEL_INSTANCE_EBS_BANDWIDTH,
    wk.LABEL_INSTANCE_GPU_COUNT,
    wk.LABEL_INSTANCE_ACCELERATOR_COUNT,
)
ND = len(NUMERIC_DIMS)

# requirement keys the tensor encoding can express; constraints on any
# OTHER key are invisible to the device compat (they ride into the decoded
# group requirements but cannot gate joins), so routing must keep classes
# with DIVERGENT un-encodable constraints off the device path
# (service.supports; the oracle's _try_group would refuse those joins)
ENCODABLE_KEYS = frozenset(LABEL_DIMS) | frozenset(NUMERIC_DIMS) | {
    wk.ZONE_LABEL,
    wk.CAPACITY_TYPE_LABEL,
}

# unit scaling per resource axis: raw base units -> small exact ints
_SCALE = np.ones((R,), dtype=np.float64)
_SCALE[res.AXIS_INDEX[res.MEMORY]] = 1.0 / 2**20          # bytes -> MiB
_SCALE[res.AXIS_INDEX[res.EPHEMERAL_STORAGE]] = 1.0 / 2**30  # bytes -> GiB


def scale_vector(v: Sequence[float]) -> np.ndarray:
    return np.asarray(v, dtype=np.float64) * _SCALE


def _pad_pow2_words(n: int) -> int:
    return (n + 31) // 32


def bucket(n: int, lo: int = 8) -> int:
    """Round up to a power of two (compile-cache-friendly static shapes)."""
    b = lo
    while b < n:
        b *= 2
    return b


@dataclass
class Vocab:
    """Per-dimension value vocabulary; index 0 is reserved for 'absent'."""

    values: List[str] = field(default_factory=lambda: ["<absent>"])
    index: Dict[str, int] = field(default_factory=lambda: {"<absent>": 0})

    def code(self, value: Optional[str]) -> int:
        if value is None:
            return 0
        i = self.index.get(value)
        if i is None:
            i = len(self.values)
            self.values.append(value)
            self.index[value] = i
        return i

    def __len__(self):
        return len(self.values)


@dataclass
class CatalogTensors:
    """Device-ready encoding of one resolved instance-type catalog."""

    names: List[str]                 # K_real entries
    k_real: int
    k_pad: int
    cap: np.ndarray                  # [K, R] float32, scaled allocatable; 0 rows for padding
    tcode: np.ndarray                # [K, D] int32 label codes
    tnum: np.ndarray                 # [K, ND] float32 numeric label values
    tnum_present: np.ndarray         # [K, ND] bool: label defined on the type
    tzone: np.ndarray                # [K, Z] bool: has any offering in zone
    tcap: np.ndarray                 # [K, CT] bool: has any offering of captype
    price: np.ndarray                # [K, Z, CT] float32; +inf when no available offering
    vocabs: List[Vocab]
    zones: List[str]                 # zone axis order
    words: List[int]                 # bitmask words per dim

    def zone_index(self, zone: str) -> int:
        return self.zones.index(zone)


def encode_catalog(instance_types: Sequence[InstanceType], k_pad: Optional[int] = None) -> CatalogTensors:
    k_real = len(instance_types)
    if k_pad is None:
        k_pad = max(128, ((k_real + 127) // 128) * 128)
    vocabs = [Vocab() for _ in LABEL_DIMS]
    zones: List[str] = []
    zone_idx: Dict[str, int] = {}
    for it in instance_types:
        for o in it.offerings:
            if o.zone not in zone_idx:
                if len(zones) >= Z_PAD:
                    raise ValueError(f"more than {Z_PAD} zones; raise Z_PAD")
                zone_idx[o.zone] = len(zones)
                zones.append(o.zone)

    cap = np.zeros((k_pad, R), dtype=np.float32)
    tcode = np.zeros((k_pad, D), dtype=np.int32)
    tnum = np.zeros((k_pad, ND), dtype=np.float32)
    tnum_present = np.zeros((k_pad, ND), dtype=bool)
    tzone = np.zeros((k_pad, Z_PAD), dtype=bool)
    tcap = np.zeros((k_pad, CT), dtype=bool)
    price = np.full((k_pad, Z_PAD, CT), np.inf, dtype=np.float32)
    names = []
    for k, it in enumerate(instance_types):
        names.append(it.name)
        cap[k] = scale_vector(it.allocatable().to_vector())
        labels = it.requirements.labels()
        for d, dim in enumerate(LABEL_DIMS):
            tcode[k, d] = vocabs[d].code(labels.get(dim))
        for nd_i, dim in enumerate(NUMERIC_DIMS):
            val = labels.get(dim)
            try:
                tnum[k, nd_i] = float(val) if val is not None else 0.0
                tnum_present[k, nd_i] = val is not None
            except ValueError:
                tnum[k, nd_i] = 0.0
                tnum_present[k, nd_i] = False
        for o in it.offerings:
            z = zone_idx[o.zone]
            c = CAPTYPE_INDEX[o.capacity_type]
            if o.available:
                tzone[k, z] = True
                tcap[k, c] = True
                price[k, z, c] = min(price[k, z, c], o.price)
    words = [_pad_pow2_words(len(v)) for v in vocabs]
    return CatalogTensors(
        names=names, k_real=k_real, k_pad=k_pad, cap=cap, tcode=tcode, tnum=tnum,
        tnum_present=tnum_present, tzone=tzone, tcap=tcap, price=price, vocabs=vocabs,
        zones=zones, words=words,
    )


@dataclass
class PodClass:
    """One equivalence class of identical-for-scheduling pods."""

    pods: List[Pod]
    requests: np.ndarray             # [R] scaled, includes pods=1
    requirements: Requirements
    key: tuple
    # price-envelope pod count for fresh-group sizing (solver/ffd.py price
    # objective): -1 = use the in-scan leftover; spread sub-classes pin 1
    env_count: int = -1
    # OR of routing-relevant constraint bits over EVERY signature that
    # merged into this class. The TERMS themselves are not in _class_key
    # (pods with different affinity targets but one shape still share a
    # class -- the oracle reads each pod's own terms at placement), but
    # oracle_suffix_rank IS: plain pods never merge behind a constrained
    # representative, so these bits answer "does anyone here carry
    # affinity?" exactly for the whole class (round 5)
    has_affinity: bool = False
    multi_node_affinity: bool = False
    has_preferences: bool = False


@dataclass
class PodClassSet:
    classes: List[PodClass]
    c_real: int
    c_pad: int
    req: np.ndarray                  # [C, R] float32
    count: np.ndarray                # [C] int32
    env_count: np.ndarray            # [C] i32 price-envelope pod count:
                                     # >0 pinned; <0 in-scan leftover plus
                                     # (-env-1) shared-envelope tail pods
                                     # (-1 = plain leftover; see
                                     # service._unify_envelopes / ffd.py)
    allowed: List[np.ndarray]        # per dim: [C, W_d] uint32 bitmasks
    num_lo: np.ndarray               # [C, ND] float32 exclusive lower bounds (-inf none)
    num_hi: np.ndarray               # [C, ND] float32 exclusive upper bounds (+inf none)
    azone: np.ndarray                # [C, Z] bool allowed zones
    acap: np.ndarray                 # [C, CT] bool allowed captypes
    schedulable: np.ndarray          # [C] bool (taints tolerated etc.)
    # [R] f32 per-fresh-node reserve (daemonset overhead for the solved
    # pool, apis/daemonset.pool_daemon_overhead); zeros = no reserve
    node_overhead: np.ndarray = None
    # [C, K] bool open-restriction mask (merged multi-pool solves only;
    # None = open anywhere compat allows). See ffd.SolveInputs.open_allowed.
    open_allowed: np.ndarray = None
    # [C, K] bool join-restriction mask ANDed into compat (merged
    # multi-pool solves with per-pool TAINTS only; None = no restriction).
    # Encodes the oracle's _try_group toleration gate: a class may join a
    # group only on columns of pools whose taints it tolerates.
    join_allowed: np.ndarray = None
    # [C, R] float64 EXACT base-unit per-pod request vectors (requests +
    # one pod axis), used by the vectorized decode: group totals become one
    # matmul instead of a per-class Python loop. Host-side only -- never
    # shipped over the wire.
    base_req: np.ndarray = None


def pack_class_masks(class_set: "PodClassSet") -> "PodClassSet":
    """Convert the set's [C, K] bool open/join masks to the bit-packed
    [C, KW] uint32 form IN PLACE (solver/packing.py; no-op for absent or
    already-packed masks) and return the set. The packed rows are what a
    packed_masks solver stages and what the wire's negotiated form ships
    -- every kernel dispatches on dtype, so downstream is agnostic.
    Exactly invertible, so decisions are bit-identical by construction."""
    from karpenter_tpu.solver import packing

    for name in ("open_allowed", "join_allowed"):
        m = getattr(class_set, name, None)
        if m is not None and not packing.is_packed(m):
            setattr(class_set, name, packing.pack_mask(m))
    return class_set


def soft_zone_tsc(pod: Pod):
    """The pod's single EFFECTIVE soft (ScheduleAnyway) zone-spread
    preference, or None. Applies only when the pod carries NO hard
    constraints (a hard constraint owns the pin -- one deterministic pin
    per pod is what keeps both paths equal) and the pod matches its own
    selector. With several soft zone constraints the first applies, the
    rest are scoring no-ops. Canonical definition (solver/spread.py
    re-exports; living here keeps the import graph acyclic since the
    class signature below needs it too)."""
    if any(t.hard() for t in pod.topology_spread):
        return None
    soft = [
        t for t in pod.topology_spread
        if not t.hard() and t.topology_key == wk.ZONE_LABEL
    ]
    if not soft:
        return None
    t = soft[0]
    if not all(pod.metadata.labels.get(k) == v for k, v in t.label_selector.items()):
        return None
    return t


def _spread_sig(pod: Pod) -> tuple:
    """Spread constraints that shape placement are part of scheduling
    identity: pods that spread differently (or match their own selector
    differently) must not collapse into one class (solver/spread.py
    distributes per class). That is every HARD constraint plus the
    single EFFECTIVE soft zone preference (soft_zone_tsc -- an INERT
    soft constraint must not fragment otherwise-identical classes);
    soft non-zone constraints stay scoring no-ops. when_unsatisfiable
    is in the tuple so a hard and a soft constraint of the same shape
    never share a class."""
    sig = tuple(
        (
            t.topology_key,
            t.max_skew,
            t.when_unsatisfiable,
            tuple(sorted(t.label_selector.items())),
            all(pod.metadata.labels.get(k) == v for k, v in t.label_selector.items()),
        )
        for t in pod.topology_spread
        if t.hard()
    )
    t = soft_zone_tsc(pod)
    if t is not None:
        sig += (
            (
                t.topology_key,
                t.max_skew,
                t.when_unsatisfiable,
                tuple(sorted(t.label_selector.items())),
                True,
            ),
        )
    return sig


def oracle_suffix_rank(pod: Pod) -> int:
    """1 for pods the device kernels cannot place -- pod (anti-)affinity,
    OR-of-node-affinity-terms, preferences -- the ORACLE-SUFFIX partition;
    0 for everything else. The rank LEADS the canonical sort, so every
    suffix pod schedules after every plain pod. That makes the class-level
    carve-out (device solves the plain prefix, the oracle continues with
    the suffix over the device's open state) order-equivalent to one full
    oracle pass over the whole batch (round 5): by the time a suffix pod
    places, the full pass and the split pass have built the same world.
    Scheduling constrained pods after their potential co-location targets
    also strictly helps required-affinity feasibility (the targets exist
    by then), replacing most uses of the self-match bootstrap rule."""
    return int(
        bool(pod.affinity_terms)
        or len(pod.node_affinity_terms) > 1
        or bool(pod.preferred_node_affinity_terms)
        or bool(pod.preferred_affinity_terms)
    )


def pod_sort_key(pod: Pod) -> tuple:
    """The canonical scheduling order: oracle-suffix pods last, then
    dominant resource descending, then a pool-independent class signature
    as the tie-break. BOTH the oracle's per-pod loop and group_pods' class
    order sort by this key, so pods of equal size but different classes
    are processed in the same relative order on both paths -- shared
    spread counts then evolve identically."""
    reqs = pod.scheduling_requirements()[0]
    return (
        oracle_suffix_rank(pod),
        -pod.requests.get(res.CPU),
        -pod.requests.get(res.MEMORY),
        # full request vector: classes may differ only in another axis
        # (gpu, storage); the tie-break must still order them identically
        tuple(-v for v in scale_vector((pod.requests + _one_pod()).to_vector())),
        reqs.stable_hash(),
        tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)),
        _spread_sig(pod),
    )


def _class_key(pod: Pod, reqs: Requirements) -> tuple:
    return (
        # suffix rank in the key: a class never mixes plain and
        # oracle-suffix pods, so the carve-out partitions EXACTLY along
        # class boundaries. Price envelopes deliberately IGNORE the rank
        # (oracle._env_key strips element 0) so a follower still shares
        # its anchor's envelope; the carve is blocked on such collisions
        # (service._aff_partition_blocked)
        oracle_suffix_rank(pod),
        tuple(np.asarray(scale_vector(
            (pod.requests + _one_pod()).to_vector()), dtype=np.float64)),
        reqs.stable_hash(),
        tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations)),
        _spread_sig(pod),
    )


def _one_pod():
    from karpenter_tpu.scheduling import Resources

    return Resources.from_base_units({res.PODS: 1})


# global signature intern table (utils.InternTable, same design as the
# pod spec-token table): structural signature -> small monotone int, so
# the per-call grouping loop hashes a machine int instead of re-hashing a
# deep nested tuple for every one of 50k pods. Monotone ids make a
# generation counter unnecessary: an id from before an overflow clear can
# never collide with one from after, and a stale memo merely re-interns
# (splitting, never merging, lookup groups -- classes still converge via
# _class_key).
from karpenter_tpu.utils import InternTable as _InternTable

_SIGS = _InternTable()
_intern_sig = _SIGS.intern


def group_pods(pods: Sequence[Pod], extra_requirements: Optional[Requirements] = None) -> List[PodClass]:
    """Collapse pods into equivalence classes. Pods with multiple affinity
    alternatives use their first term (the oracle handles full OR semantics;
    multi-term pods are rare and can be routed to the oracle).

    Four-level grouping keeps the 50k-pod hot path inside the latency
    budget. Fast path: pods carry a shared-spec identity token
    (Pod._spec_token -- ReplicaSet replicas constructed from the same
    interned spec objects share it), so the common case is ONE dict lookup
    per pod with the whole structural machinery running once per template.
    Slow path (spread pods, or pods built from per-pod spec copies): an
    interned small-int signature id (memoized across calls -- warm ticks
    hash machine ints, not tuples), distinct ids key by the structural
    signature (Pod.grouping_signature -- raw spec tuples), and ONE
    canonical key (Requirements construction + stable hash + scaled
    request vector) is computed per distinct signature. Signatures whose
    canonical keys coincide (e.g. the same constraint written as
    nodeSelector vs nodeAffinity) share a class, as do distinct tokens with
    equal signatures. The single ordered pass preserves input order within
    each class -- required for exact differential equivalence with the
    oracle's stable per-pod sort."""
    tok_to_class: Dict[tuple, PodClass] = {}
    id_to_class: Dict[tuple, PodClass] = {}
    groups: Dict[tuple, PodClass] = {}
    tok_get = tok_to_class.get
    id_get = id_to_class.get

    def classify(pod: Pod) -> PodClass:
        sid = pod._sig_id
        if sid is None:
            sid = pod._sig_id = _intern_sig(pod.grouping_signature())
        pc = id_get(sid)
        if pc is None:
            reqs = pod.scheduling_requirements()[0]
            if extra_requirements is not None:
                reqs = reqs.copy().add(*extra_requirements)
            key = _class_key(pod, reqs)
            pc = groups.get(key)
            if pc is None:
                requested = scale_vector((pod.requests + _one_pod()).to_vector()).astype(np.float32)
                pc = groups[key] = PodClass(pods=[], requests=requested, requirements=reqs, key=key)
            # routing bits OR over every signature the class absorbs.
            # oracle_suffix_rank in the class key means a constrained pod
            # can never merge behind a PLAIN representative; the bits are
            # uniform per class and the carve partitions along class
            # boundaries (TPUSolver._suffix_classes)
            if pod.affinity_terms:
                pc.has_affinity = True
            if len(pod.node_affinity_terms) > 1:
                pc.multi_node_affinity = True
            if pod.preferred_node_affinity_terms or pod.preferred_affinity_terms:
                pc.has_preferences = True
            id_to_class[sid] = pc
        return pc

    # gc paused: cold grouping of 50k fresh pods allocates ~400k young
    # containers; mid-loop generational collections multiply the cost ~6x
    with gc_paused():
        if _native_grouping is not None:
            # the C hot loop (native/_grouping.c): token attribute read +
            # dict probe + list append per pod, calling classify() back
            # only on per-template misses -- same semantics, ~5x less
            # per-pod cost and far less sensitivity to a churned heap
            _native_grouping.group_by_token(pods, classify)
        else:
            for pod in pods:
                tok = pod._spec_token
                if tok is not None:
                    pc = tok_get(tok)
                    if pc is None:
                        pc = tok_to_class[tok] = classify(pod)
                else:
                    pc = classify(pod)
                pc.pods.append(pod)
    # FFD order: dominant resource descending with the canonical tie-break
    # (pod_sort_key) -- must match the oracle's sort for differential
    # equivalence, including between equal-sized classes
    out = list(groups.values())
    out.sort(key=lambda pc: pod_sort_key(pc.pods[0]))
    return out


class IncrementalGrouper:
    """Dirty-tracking grouping across scheduling ticks (the delta-solve
    engine's host layer). group() is drop-in equivalent to group_pods(pods)
    -- same classes, same order, same pods lists, fresh PodClass objects
    per call (pipelined tickets own their class lists) -- but every
    per-signature canonical computation is memoized ACROSS ticks instead
    of per call: Requirements construction, the class key, the scaled
    request vector, the routing flags, and the FFD sort key (a pure
    function of class identity: every pod_sort_key component is determined
    by the _class_key components). A warm steady-state tick's grouping
    therefore costs one token/signature dict probe + list append per pod
    (the same native C loop group_pods runs) plus canonical work ONLY for
    signatures never seen before -- classification cost scales with churn,
    not cluster size.

    Routing flags are memoized PER SIGNATURE and OR'd over the signatures
    present THIS tick (exactly group_pods' fresh semantics -- a class whose
    affinity-carrying pods all left does not keep a stale flag).

    last_stats reports the tick-over-tick churn: classes whose pod count
    changed, appeared, or vanished since the previous call -- the
    dirty-fraction signal the delta wire metrics and span attrs quote.

    Not thread-safe; owned by the (single-threaded) scheduling tick."""

    def __init__(self):
        # sig id -> (class key, Requirements, requests f32, flags)
        self._sig_memo: Dict[int, tuple] = {}
        self._sort_memo: Dict[tuple, tuple] = {}   # class key -> pod_sort_key
        self._prev_counts: Dict[tuple, int] = {}
        self.last_stats = {
            "pods": 0, "classes": 0, "dirty_classes": 0, "new_classes": 0,
            "removed_classes": 0, "dirty_fraction": 1.0, "full_rebuild": True,
        }

    def reset(self) -> None:
        self.__init__()

    def group(self, pods: Sequence[Pod]) -> List[PodClass]:
        if len(self._sig_memo) > (1 << 16):
            # bound memo growth under signature churn: a clear only
            # re-derives canonical keys once (ids are monotone, so a stale
            # _sig_id can never alias -- see the _SIGS intern table)
            self._sig_memo.clear()
            self._sort_memo.clear()
        first = not self._prev_counts
        sig_memo = self._sig_memo
        tok_to_class: Dict[int, PodClass] = {}
        id_to_class: Dict[int, PodClass] = {}
        groups: Dict[tuple, PodClass] = {}
        tok_get = tok_to_class.get
        id_get = id_to_class.get

        def classify(pod: Pod) -> PodClass:
            sid = pod._sig_id
            if sid is None:
                sid = pod._sig_id = _intern_sig(pod.grouping_signature())
            pc = id_get(sid)
            if pc is not None:
                return pc
            ent = sig_memo.get(sid)
            if ent is None:
                reqs = pod.scheduling_requirements()[0]
                key = _class_key(pod, reqs)
                requested = scale_vector(
                    (pod.requests + _one_pod()).to_vector()
                ).astype(np.float32)
                flags = (
                    bool(pod.affinity_terms),
                    len(pod.node_affinity_terms) > 1,
                    bool(pod.preferred_node_affinity_terms or pod.preferred_affinity_terms),
                )
                ent = sig_memo[sid] = (key, reqs, requested, flags)
            key, reqs, requested, flags = ent
            pc = groups.get(key)
            if pc is None:
                pc = groups[key] = PodClass(
                    pods=[], requests=requested, requirements=reqs, key=key
                )
            if flags[0]:
                pc.has_affinity = True
            if flags[1]:
                pc.multi_node_affinity = True
            if flags[2]:
                pc.has_preferences = True
            id_to_class[sid] = pc
            return pc

        with gc_paused():
            if _native_grouping is not None:
                _native_grouping.group_by_token(pods, classify)
            else:
                for pod in pods:
                    tok = pod._spec_token
                    if tok is not None:
                        pc = tok_get(tok)
                        if pc is None:
                            pc = tok_to_class[tok] = classify(pod)
                    else:
                        pc = classify(pod)
                    pc.pods.append(pod)
        sort_memo = self._sort_memo

        def order_key(pc: PodClass) -> tuple:
            k = sort_memo.get(pc.key)
            if k is None:
                k = sort_memo[pc.key] = pod_sort_key(pc.pods[0])
            return k

        out = list(groups.values())
        out.sort(key=order_key)
        prev = self._prev_counts
        counts = {pc.key: len(pc.pods) for pc in out}
        new = sum(1 for k in counts if k not in prev)
        changed = sum(1 for k, n in counts.items() if k in prev and prev[k] != n)
        removed = sum(1 for k in prev if k not in counts)
        self._prev_counts = counts
        n_classes = len(counts)
        self.last_stats = {
            "pods": len(pods),
            "classes": n_classes,
            "dirty_classes": new + changed,
            "new_classes": new,
            "removed_classes": removed,
            # denominator = |prev UNION cur| (= cur + removed), so a full
            # turnover reads 1.0, never above -- the histogram buckets and
            # the span attr both promise a fraction
            "dirty_fraction": (
                1.0 if first
                else (new + changed + removed) / max(1, n_classes + removed)
            ),
            "full_rebuild": first,
        }
        return out


def with_extra_requirements(classes: Sequence[PodClass], extra: Requirements) -> List[PodClass]:
    """Re-base already-grouped classes onto a nodepool's requirements --
    the per-class equivalent of group_pods(pods, extra_requirements=...),
    letting one grouping pass serve routing plus every pool's solve.
    Classes that would have merged under the extra requirements stay
    separate, which the solver handles as independent rows."""
    return [
        PodClass(
            pods=pc.pods, requests=pc.requests,
            requirements=pc.requirements.copy().add(*extra),
            key=pc.key, env_count=pc.env_count,
            has_affinity=pc.has_affinity, multi_node_affinity=pc.multi_node_affinity,
            has_preferences=pc.has_preferences,
        )
        for pc in classes
    ]


def _allowed_bits_for(reqs: Requirements, vocab: Vocab, dim: str, words: int) -> np.ndarray:
    """Packed allowed-set bitmask for one dim. Unknown values in an In-set
    are ignored (they can't match any type); absent requirement = all ones.

    Semantics mirror Requirements.compatible on the *type* side: a type that
    does not define the label (code 0, 'absent') is PERMISSIVELY compatible
    with any requirement on that label (e.g. the karpenter.sh/nodepool
    requirement never appears on catalog types) -- except DoesNotExist,
    where absent is the only admissible state and defined values are not."""
    r = reqs.get(dim)
    out = np.zeros((words,), dtype=np.uint64)
    if r is None:
        out[:] = np.uint64(0xFFFFFFFF)
        return out.astype(np.uint32)
    if r.is_does_not_exist():
        out[0] = np.uint64(1)  # only 'absent' allowed
        return out.astype(np.uint32)
    if r.complement:
        out[:] = np.uint64(0xFFFFFFFF)
        for v in r.values:
            i = vocab.index.get(v)
            if i is not None:
                out[i // 32] &= ~np.uint64(1 << (i % 32))
    else:
        for v in r.values:
            i = vocab.index.get(v)
            if i is not None:
                out[i // 32] |= np.uint64(1 << (i % 32))
    out[0] |= np.uint64(1)  # absent label on the type side is permissive
    return out.astype(np.uint32)


def _row_key(pc: PodClass, taints_sig: tuple) -> tuple:
    """Cache key for one class's encoded tensor ROW (encode_classes
    row_cache): the full canonical requirement content -- NOT a hash, so
    two distinct requirement sets can never collide into one row -- plus
    the representative's tolerations (schedulable depends on them), the
    pool taints, and the FLOAT64-exact scaled request vector (the same
    precision _class_key distinguishes classes at: the cached row carries
    the exact base_req, so keying on the float32-rounded pc.requests
    could alias two classes whose requests differ below a float32 ulp)."""
    return (
        tuple(sorted(
            (r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than)
            for r in pc.requirements
        )),
        tuple(
            (t.key, t.operator, t.value, t.effect) for t in pc.pods[0].tolerations
        ),
        taints_sig,
        scale_vector((pc.pods[0].requests + _one_pod()).to_vector()).tobytes(),
    )


def encode_classes(
    classes: Sequence[PodClass],
    catalog: CatalogTensors,
    pool_taints: Sequence[Taint] = (),
    c_pad: Optional[int] = None,
    node_overhead: Optional[np.ndarray] = None,
    row_cache: Optional[Dict] = None,
) -> PodClassSet:
    """classes -> dense solver tensors. On the jax-discipline hot-path
    manifest (DEVICE_HOT_PATH): per-tick encode work stays host-side
    numpy; a device-value sync here is a lint violation.

    `row_cache` (optional, scoped to
    ONE catalog encoding -- the caller keys it per staged-catalog entry)
    memoizes the per-class row products that are pure functions of
    (requirements, tolerations, pool taints, requests): the packed allowed
    bitmasks, numeric windows, zone/captype masks, schedulability, and the
    float64 base request vector. On a warm steady-state tick only CHANGED
    classes pay the row construction; counts and env_counts are always
    written fresh (they change every tick and cost one store)."""
    c_real = len(classes)
    if c_pad is None:
        c_pad = max(8, ((c_real + 7) // 8) * 8)
    req = np.zeros((c_pad, R), dtype=np.float32)
    count = np.zeros((c_pad,), dtype=np.int32)
    env_count = np.zeros((c_pad,), dtype=np.int32)
    allowed = [np.zeros((c_pad, w), dtype=np.uint32) for w in catalog.words]
    num_lo = np.full((c_pad, ND), -np.inf, dtype=np.float32)
    num_hi = np.full((c_pad, ND), np.inf, dtype=np.float32)
    azone = np.zeros((c_pad, Z_PAD), dtype=bool)
    acap = np.zeros((c_pad, CT), dtype=bool)
    schedulable = np.zeros((c_pad,), dtype=bool)
    base_req = np.zeros((c_pad, R), dtype=np.float64)
    taints_sig = tuple((t.key, t.value, t.effect) for t in pool_taints)
    n_zones = len(catalog.zones)
    one = _one_pod()
    for c, pc in enumerate(classes):
        req[c] = pc.requests
        count[c] = len(pc.pods)
        env_count[c] = pc.env_count
        reqs = pc.requirements
        row = rkey = None
        if row_cache is not None:
            rkey = _row_key(pc, taints_sig)
            row = row_cache.get(rkey)
        if row is None:
            arow = [
                _allowed_bits_for(reqs, catalog.vocabs[d], dim, catalog.words[d])
                for d, dim in enumerate(LABEL_DIMS)
            ]
            nlo = np.full((ND,), -np.inf, dtype=np.float32)
            nhi = np.full((ND,), np.inf, dtype=np.float32)
            for nd_i, dim in enumerate(NUMERIC_DIMS):
                r = reqs.get(dim)
                if r is not None:
                    if r.greater_than is not None:
                        nlo[nd_i] = r.greater_than
                    if r.less_than is not None:
                        nhi[nd_i] = r.less_than
                    # In-sets over numeric dims are handled via the bitset
                    # path when the dim is also a LABEL_DIM
            zreq = reqs.get(wk.ZONE_LABEL)
            az = np.array(
                [zreq is None or zreq.matches(zone) for zone in catalog.zones],
                dtype=bool,
            )
            creq = reqs.get(wk.CAPACITY_TYPE_LABEL)
            ac = np.zeros((CT,), dtype=bool)
            for name, idx in CAPTYPE_INDEX.items():
                ac[idx] = creq is None or creq.matches(name)
            sched = tolerates_all(pc.pods[0].tolerations, pool_taints)
            brow = np.asarray(
                (pc.pods[0].requests + one).to_vector(), dtype=np.float64
            )
            row = (arow, nlo, nhi, az, ac, sched, brow)
            if row_cache is not None:
                if len(row_cache) > 8192:
                    row_cache.clear()  # bound growth across catalog lifetime
                row_cache[rkey] = row
        arow, nlo, nhi, az, ac, sched, brow = row
        for d in range(D):
            allowed[d][c] = arow[d]
        num_lo[c] = nlo
        num_hi[c] = nhi
        azone[c, :n_zones] = az
        acap[c] = ac
        schedulable[c] = sched
        base_req[c] = brow
    return PodClassSet(
        classes=list(classes), c_real=c_real, c_pad=c_pad, req=req, count=count,
        env_count=env_count, allowed=allowed, num_lo=num_lo, num_hi=num_hi,
        azone=azone, acap=acap, schedulable=schedulable,
        node_overhead=(
            node_overhead.astype(np.float32)
            if node_overhead is not None else np.zeros((R,), dtype=np.float32)
        ),
        base_req=base_req,
    )


def compat_matrix(catalog: CatalogTensors, classes: PodClassSet) -> np.ndarray:
    """[C, K] bool: class c may run on type k (labels + numeric windows).
    Host/numpy reference implementation -- the jitted solver computes the
    same thing on device (solver/ffd.py)."""
    C, K = classes.c_pad, catalog.k_pad
    ok = np.ones((C, K), dtype=bool)
    for d in range(D):
        codes = catalog.tcode[:, d]                       # [K]
        words = classes.allowed[d][:, codes // 32]        # [C, K]
        bits = (words >> (codes % 32).astype(np.uint32)) & 1
        ok &= bits.astype(bool)
    for nd_i in range(ND):
        v = catalog.tnum[:, nd_i][None, :]
        present = catalog.tnum_present[:, nd_i][None, :]
        in_window = (v > classes.num_lo[:, nd_i][:, None]) & (v < classes.num_hi[:, nd_i][:, None])
        # a type that does not define the numeric label is permissively
        # compatible (matches Requirements.compatible for missing keys)
        ok &= in_window | ~present
    # offering-level compat: some permitted zone AND captype must exist
    ok &= (classes.azone.astype(np.int8) @ catalog.tzone.T.astype(np.int8)) > 0
    ok &= (classes.acap.astype(np.int8) @ catalog.tcap.T.astype(np.int8)) > 0
    ok &= classes.schedulable[:, None]
    ok[:, catalog.k_real:] = False
    ok[classes.c_real:, :] = False
    return ok
