"""Device kernels for the batched consolidation solve.

The TPU reformulation of the disruption engine's candidate simulation
(HOT LOOP #3, SURVEY.md section 3.2: for each candidate node (set), "can
its pods reschedule onto the remaining nodes, plus at most one strictly
cheaper new node?"). The reference evaluates candidates one at a time
against a full scheduling simulation (designs/consolidation.md); here
every candidate set is evaluated simultaneously:

- ``disrupt_repack``: the repack simulation is a vmap over candidate
  sets of a lax.scan over FFD-ordered pod classes; the carry is the
  per-node remaining headroom [N, R], and first-fit spill across nodes
  uses the same exclusive-cumsum trick as the provisioning solver
  (solver/ffd.py);
- ``disrupt_replace``: the one-new-node replacement search reduces to:
  which instance types are compatible with EVERY leftover class and
  large enough for their aggregate -- a masked min over the staged
  (type, zone, captype) price tensor. The daemonset overhead vector is
  subtracted INSIDE the kernel so the host-fallback and the wire path
  compute cap_eff identically (bit-identity by construction).

Both kernels run identically on the sidecar (solver/rpc.py
``solve_disrupt``, against the catalog staged per seqnum) and in process
(the breaker-open / wire-dead fallback), so the differential contract --
host == wire == device verdicts -- holds the same way it does for the
provisioning solve.
"""
from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# XLA backend at import (see solver/ffd.py _INF)
_INF = np.float32(np.inf)


@functools.partial(jax.jit, static_argnames=())
def disrupt_repack(
    headroom0: jax.Array,   # [N, R] f32 remaining capacity of surviving nodes
    feas: jax.Array,        # [C, N] bool class-on-node feasibility
    req: jax.Array,         # [C, R] f32 per-pod request (includes pods=1)
    member: jax.Array,      # [S, C] i32 pods of class c in candidate set s
    excl: jax.Array,        # [S, N] bool node n is being deleted by set s
) -> Tuple[jax.Array, jax.Array]:
    """([S, C] i32 leftovers, [S, C, N] i32 per-node placements): pods of
    class c in set s packed first-fit-decreasing onto the surviving nodes
    (node order = oracle order); leftover did not fit anywhere."""

    def one_set(member_s: jax.Array, excl_s: jax.Array):
        hr0 = jnp.where(excl_s[:, None], 0.0, headroom0)          # [N, R]

        def step(hr, xs):
            req_c, feas_c, count_c = xs
            safe = jnp.where(req_c > 0, req_c, 1.0)               # [R]
            per_axis = jnp.where(
                req_c[None, :] > 0, jnp.floor(hr / safe[None, :]), _INF
            )                                                     # [N, R]
            fit = jnp.maximum(jnp.min(per_axis, axis=-1), 0.0)    # [N]
            fit = jnp.where(feas_c, fit, 0.0).astype(jnp.int32)
            cum_before = jnp.cumsum(fit) - fit
            take = jnp.clip(count_c - cum_before, 0, fit)         # [N]
            hr2 = hr - take[:, None].astype(jnp.float32) * req_c[None, :]
            return hr2, (count_c - jnp.sum(take), take)

        _, (leftover, takes) = jax.lax.scan(step, hr0, (req, feas, member_s))
        return leftover, takes                                    # [C], [C, N]

    return jax.vmap(one_set)(member, excl)


@functools.partial(jax.jit, static_argnames=("od_col",))
def disrupt_replace(
    leftover: jax.Array,    # [S, C] i32
    req: jax.Array,         # [C, R] f32
    compat: jax.Array,      # [C, K] bool class-type compat (pool ctx included)
    azone: jax.Array,       # [C, Z] bool
    acap: jax.Array,        # [C, CT] bool
    cap: jax.Array,         # [K, R] f32 raw type capacity (staged per seqnum)
    ovh: jax.Array,         # [R] f32 per-pool fresh-node daemonset reserve
    price: jax.Array,       # [K, Z, CT] f32 (+inf when unavailable)
    *,
    od_col: int,            # on-demand captype column (closed vocabulary)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cheapest single new node that absorbs every leftover pod of each set.
    Returns (best_price [S], best_od_price [S], best_type [S] i32, -1 none).
    A type qualifies iff it is compatible with every leftover class and its
    overhead-adjusted capacity covers the aggregate leftover request; the
    offering must sit in a zone/captype admitted by every leftover class."""
    cap_eff = jnp.maximum(cap - ovh[None, :], 0.0)                # [K, R]
    need = leftover > 0                                           # [S, C]
    agg = jnp.einsum("sc,cr->sr", leftover.astype(jnp.float32), req)
    ok_type = ~jnp.einsum("sc,ck->sk", need, ~compat)             # [S, K] no violator
    fits = jnp.all(cap_eff[None, :, :] >= agg[:, None, :], axis=-1)   # [S, K]
    ok_type = ok_type & fits & jnp.any(need, axis=-1)[:, None]
    zone_ok = ~jnp.einsum("sc,cz->sz", need, ~azone)              # [S, Z]
    cap_ok = ~jnp.einsum("sc,ct->st", need, ~acap)                # [S, CT]
    masked = jnp.where(
        ok_type[:, :, None, None]
        & zone_ok[:, None, :, None]
        & cap_ok[:, None, None, :],
        price[None, :, :, :],
        _INF,
    )                                                             # [S, K, Z, CT]
    S, K, Z, CTn = masked.shape
    flat = masked.reshape(S, -1)
    best_price = jnp.min(flat, axis=-1)
    best_type = jnp.where(
        jnp.isfinite(best_price), (jnp.argmin(flat, axis=-1) // (Z * CTn)).astype(jnp.int32), -1
    )
    best_od_price = jnp.min(masked[:, :, :, od_col].reshape(S, -1), axis=-1)
    return best_price, best_od_price, best_type
