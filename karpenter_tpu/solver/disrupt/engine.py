"""DisruptEngine: batched candidate-set consolidation in one dispatch.

Host side of the device-resident consolidation subsystem: encode the
candidate sets once ([S, C] membership, [S, N] exclusions, [C, N]
feasibility, [N, R] headroom), run the repack + replacement kernels
(solver/disrupt/kernel.py), and assemble per-set verdicts. Two dispatch
routes, bit-identical by construction (same kernels, same inputs):

- **wire**: the ``solve_disrupt`` op on the solver sidecar
  (solver/rpc.py), feature-negotiated like ``solve_delta``. The catalog
  price/capacity tensors are NOT re-shipped -- the op references the
  catalog already staged under its seqnum by the provisioning path
  (TPUSolver's catalog cache mints the seqnum; the client stages it on
  demand), and the repacked leftover tensor is staged server-side under
  a disrupt epoch so the per-pool replacement passes of one sweep ship
  only the [C, K]-shaped class masks.
- **local**: the same kernels in process -- the breaker-open and
  wire-dead fallback, and the only route when no sidecar is configured.

Any wire failure (connection, sidecar error, staging gap the retry
ladder cannot close) counts toward the shared circuit breaker and falls
back to the local route, so the disruption sweep degrades through
exactly the ladder the provisioning solve uses.

Scope: candidate sets whose pods carry stateful constraints (hard
topology spread, affinity terms, multi-term node affinity) are routed to
the Python oracle by the disruption controller; for everything else this
evaluator is differentially equivalent to oracle.Scheduler
(tests/test_consolidate.py). Verdicts are *decisions* for deletion
(equivalence is exact) and a *pre-filter plus price* for replacement:
the controller re-derives the replacement group through the oracle for
the one candidate set it acts on, so N-set scans cost one device call
instead of N full simulations.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu import metrics
from karpenter_tpu.apis import NodePool, Pod, labels as wk
from karpenter_tpu.scheduling import Resources, tolerates_all
from karpenter_tpu.solver import encode
from karpenter_tpu.solver.disrupt import kernel
from karpenter_tpu.solver.encode import CatalogTensors
from karpenter_tpu.solver.oracle import ExistingNode

_bucket = encode.bucket

# pair-enumeration window: underutilized pairs are drawn from the first
# WINDOW candidates of the disruption-cost order (bounded so the set axis
# stays O(N + WINDOW^2), not O(N^2))
PAIR_WINDOW = 6


@dataclass
class SetVerdict:
    """Device verdict for one candidate set."""

    can_delete: bool
    leftover: int                      # pods that did not fit existing nodes
    replace_price: float               # cheapest single-new-node price (inf none)
    replace_od_price: float            # cheapest on-demand-only price (inf none)
    replace_type: Optional[str]        # instance type name (None when inf)
    nodepool: Optional[str]            # pool the replacement came from

    def action(self, budget: float, od_only: bool = False) -> str:
        """The verdict as a decision against the candidate set's
        aggregate price: ``delete`` (pods fit the survivors),
        ``replace-cheaper`` (one new node absorbs the leftovers strictly
        under budget), or ``blocked``."""
        if self.can_delete:
            return "delete"
        price = self.replace_od_price if od_only else self.replace_price
        if math.isfinite(price) and price < budget:
            return "replace-cheaper"
        return "blocked"

    def savings(self, budget: float, od_only: bool = False) -> float:
        """Hourly savings of acting on this verdict (0 when blocked)."""
        if self.can_delete:
            return budget
        price = self.replace_od_price if od_only else self.replace_price
        if math.isfinite(price) and price < budget:
            return budget - price
        return 0.0


def enumerate_pairs(n: int, window: int = PAIR_WINDOW) -> List[Tuple[int, int]]:
    """Deterministic underutilized-pair enumeration over the first
    ``min(n, window)`` candidates of the disruption-cost order:
    lexicographic (i, j), i < j, excluding (0, 1) -- that set is already
    the k=2 prefix. Bounded so the batch's set axis stays small."""
    m = min(n, window)
    return [
        (i, j) for i in range(m) for j in range(i + 1, m) if (i, j) != (0, 1)
    ]


def device_eligible(pods: Sequence[Pod]) -> bool:
    """True when every pod is free of the stateful constraints the batch
    evaluator does not model (routing mirror of solver/service.py)."""
    for p in pods:
        if p.affinity_terms or p.preferred_node_affinity_terms or p.preferred_affinity_terms:
            return False
        if any(t.hard() for t in p.topology_spread):
            return False
        if len(p.scheduling_requirements()) != 1:
            return False
    return True


def _node_feasibility(
    classes: Sequence[encode.PodClass], nodes: Sequence[ExistingNode],
    class_zone_pins: bool = False,
) -> np.ndarray:
    """[C, N] bool: a pod of class c may land on node n (labels + taints).
    Mirrors oracle._try_existing's compatibility gate. With
    `class_zone_pins`, a SPREAD SUB-CLASS's pinned zone (the split pass
    marks these env_count == 0) additionally gates the node's zone -- the
    oracle's pinned-zone node-packing rule. Ordinary classes stay
    pool-agnostic: a pool-derived zone requirement must not block packing
    onto live capacity the oracle would use."""
    C, N = len(classes), len(nodes)
    out = np.zeros((C, N), dtype=bool)
    for ci, pc in enumerate(classes):
        pod = pc.pods[0]
        zreq = (
            pc.requirements.get(wk.ZONE_LABEL)
            if class_zone_pins and pc.env_count == 0
            else None
        )
        for ni, node in enumerate(nodes):
            if not tolerates_all(pod.tolerations, node.taints):
                continue
            if zreq is not None:
                node_zone = node.labels.get(wk.ZONE_LABEL)
                if node_zone is None or not zreq.matches(node_zone):
                    continue
            out[ci, ni] = any(
                alt.matches_labels(node.labels) for alt in pod.scheduling_requirements()
            )
    return out


def _with_pool_requirements(classes: Sequence[encode.PodClass], pool: NodePool) -> List[encode.PodClass]:
    """Re-derive each class's requirements merged with the pool's (the class
    set was grouped pool-agnostically; replacement compat is per-pool).
    One shared implementation with the provisioning path -- merge
    orientation is immaterial because Requirement.intersect is commutative
    in every branch (set ops + symmetric min/max windows)."""
    return encode.with_extra_requirements(classes, pool.requirements())


class _Encoded:
    """One sweep's host-encoded tensors (the repack problem)."""

    __slots__ = ("classes", "req", "feas", "headroom", "member", "excl",
                 "C", "N", "S", "n_sets")


class _PoolCtx:
    """One pool's replacement context: the catalog snapshot (and, in
    wire mode, its staged seqnum), the pool-merged class tensors, and
    the class-type compatibility masks."""

    __slots__ = ("pool", "catalog", "seqnum", "cs", "compat", "ovh")


class DisruptEngine:
    """Evaluates many consolidation candidate sets in one device dispatch.

    Replacement context comes from the nodepools in weight order: the first
    pool whose catalog admits a feasible replacement wins (the oracle's
    pool-iteration order in _open_group).

    ``solver`` (a TPUSolver) opts the engine into the wire route: its
    catalog cache mints the staged seqnums the ``solve_disrupt`` op
    references, its client carries the frames, and its breaker gates (and
    is fed by) the dispatch outcomes. ``mesh`` shards the local repack's
    candidate-set axis across devices (parallel/mesh.sharded_repack)."""

    def __init__(self, mesh=None, solver=None):
        self.mesh = mesh
        self.solver = solver
        # keyed by object identity; holds the items list so the id stays valid
        self._catalog_cache: Dict[int, Tuple[list, CatalogTensors]] = {}
        # dispatch observability for the LAST evaluate (flight recorder /
        # bench read it): route taken, set count, sweep wall time
        self.last_dispatch = {"path": "none", "sets": 0, "ms": 0.0}

    # -- catalog snapshots ----------------------------------------------------
    def _catalog_for(self, items: list) -> Tuple[CatalogTensors, Optional[str]]:
        """(catalog tensors, staged seqnum or None). With a solver, the
        PROVISIONING path's catalog cache supplies both -- the disrupt op
        reuses the exact snapshot (and sidecar staging) the scheduling
        solve runs against, so nothing re-encodes or re-ships per sweep."""
        if self.solver is not None:
            entry = self.solver._catalog(items)
            return entry.tensors, entry.seqnum
        key = id(items)
        hit = self._catalog_cache.get(key)
        if hit is None:
            if len(self._catalog_cache) > 8:  # bound it; evict oldest entry
                self._catalog_cache.pop(next(iter(self._catalog_cache)))
            hit = self._catalog_cache[key] = (items, encode.encode_catalog(items))
        return hit[1], None

    # -- encoding -------------------------------------------------------------
    def _encode_sets(
        self,
        nodes: Sequence[ExistingNode],
        sets: Sequence[Tuple[Sequence[Pod], Sequence[str]]],
    ) -> Optional[_Encoded]:
        all_pods = [p for pods, _ in sets for p in pods]
        if not all_pods:
            return None
        classes = encode.group_pods(all_pods)
        key_of = {pc.key: i for i, pc in enumerate(classes)}

        enc = _Encoded()
        enc.classes = classes
        enc.n_sets = len(sets)
        C = enc.C = _bucket(len(classes))
        N = enc.N = _bucket(max(1, len(nodes)), lo=16)
        S = _bucket(len(sets))
        if self.mesh is not None and S % self.mesh.size:
            # the sharded set axis must divide evenly across devices
            S = ((S + self.mesh.size - 1) // self.mesh.size) * self.mesh.size
        enc.S = S
        R = encode.R

        req = np.zeros((C, R), dtype=np.float32)
        for i, pc in enumerate(classes):
            req[i] = pc.requests
        enc.req = req
        feas = np.zeros((C, N), dtype=bool)
        feas[: len(classes), : len(nodes)] = _node_feasibility(classes, nodes)
        enc.feas = feas
        headroom = np.zeros((N, R), dtype=np.float32)
        for ni, node in enumerate(nodes):
            headroom[ni] = encode.scale_vector(node.remaining().to_vector())
        enc.headroom = headroom

        member = np.zeros((S, C), dtype=np.int32)
        excl = np.zeros((S, N), dtype=bool)
        name_to_idx = {n.name: i for i, n in enumerate(nodes)}
        for si, (pods, excluded) in enumerate(sets):
            for p in pods:
                pc_reqs = p.scheduling_requirements()[0]
                k = encode._class_key(p, pc_reqs)
                member[si, key_of[k]] += 1
            for name in excluded:
                ni = name_to_idx.get(name)
                if ni is not None:
                    excl[si, ni] = True
        enc.member = member
        enc.excl = excl
        return enc

    def _pool_contexts(
        self,
        enc: _Encoded,
        pools: Sequence[NodePool],
        catalogs: Dict[str, list],
        daemon_overhead: Optional[Dict[str, "Resources"]],
    ) -> List[_PoolCtx]:
        out = []
        for pool in sorted(pools, key=lambda p: -p.weight):
            items = catalogs.get(pool.name) or []
            if not items:
                continue
            ctx = _PoolCtx()
            ctx.pool = pool
            ctx.catalog, ctx.seqnum = self._catalog_for(items)
            ctx.cs = encode.encode_classes(
                _with_pool_requirements(enc.classes, pool), ctx.catalog,
                # template.taints ONLY: startup taints lift before pods land
                # (provisioner.py:68), and the oracle's _open_group gates on
                # exactly this set -- including startup taints here would
                # wrongly report inf replacement price for pods that do not
                # tolerate them (ADVICE round 1, medium)
                pool_taints=list(pool.template.taints),
                c_pad=enc.C,
            )
            ctx.compat = encode.compat_matrix(ctx.catalog, ctx.cs)
            ovh = (daemon_overhead or {}).get(pool.name)
            ctx.ovh = np.zeros((encode.R,), dtype=np.float32)
            if ovh is not None:
                ctx.ovh = encode.scale_vector(ovh.to_vector()).astype(np.float32)
            out.append(ctx)
        return out

    # -- evaluation -----------------------------------------------------------
    def evaluate(
        self,
        nodes: Sequence[ExistingNode],
        sets: Sequence[Tuple[Sequence[Pod], Sequence[str]]],
        pools: Sequence[NodePool] = (),
        catalogs: Optional[Dict[str, list]] = None,
        daemon_overhead: Optional[Dict[str, "Resources"]] = None,
    ) -> List[SetVerdict]:
        """nodes: surviving-capacity snapshot (oracle node order).
        sets: per candidate set, (pods to repack, names of excluded nodes).
        pools/catalogs: replacement context (optional; omit for delete-only).
        daemon_overhead: per-pool fresh-node reserve (apis/daemonset) --
        a replacement node must fit the leftovers PLUS its daemonsets.

        On the jax-discipline hot-path manifest (DEVICE_HOT_PATH); the
        fetches inside the dispatch helpers are this path's designed host
        barriers (async-prefetched, SANCTIONED_FETCH); any other sync
        added here is a lint violation.
        """
        if not sets:
            return []
        t0 = time.perf_counter()
        enc = self._encode_sets(nodes, sets)
        if enc is None:
            self.last_dispatch = {"path": "none", "sets": len(sets), "ms": 0.0}
            return [
                SetVerdict(True, 0, float("inf"), float("inf"), None, None) for _ in sets
            ]
        ctxs = (
            self._pool_contexts(enc, pools, catalogs, daemon_overhead)
            if pools and catalogs else []
        )
        path = "local"
        client = self.solver.client if self.solver is not None else None
        if client is not None:
            if self.solver.wire_healthy():
                try:
                    if "solve_disrupt" in client.features():
                        verdicts = self._evaluate_wire(enc, ctxs, client)
                        if self.solver.breaker is not None:
                            self.solver.breaker.record_success()
                        path = "wire"
                    else:
                        # older sidecar: the op does not exist; the local
                        # kernels are the same decision function
                        metrics.DISRUPTION_DEVICE_FALLBACKS.inc(
                            reason="feature-missing")
                        verdicts = self._evaluate_local(enc, ctxs)
                except (ConnectionError, OSError, RuntimeError) as e:
                    # the same ladder the provisioning solve degrades
                    # through: the failure counts toward opening the
                    # breaker, and the sweep re-runs on the in-process
                    # kernels -- bit-identical decisions either way
                    if self.solver.breaker is not None:
                        self.solver.breaker.record_failure()
                    metrics.DISRUPTION_DEVICE_FALLBACKS.inc(reason="rpc-down")
                    from karpenter_tpu import tracing

                    tracing.annotate(disrupt_fallback=f"{type(e).__name__}")
                    verdicts = self._evaluate_local(enc, ctxs)
            else:
                # breaker open (or half-open): instant fallback, counted
                metrics.DISRUPTION_DEVICE_FALLBACKS.inc(reason="breaker-open")
                verdicts = self._evaluate_local(enc, ctxs)
        else:
            verdicts = self._evaluate_local(enc, ctxs)
        metrics.DISRUPTION_DEVICE_DISPATCHES.inc(path=path)
        ms = (time.perf_counter() - t0) * 1e3
        metrics.DISRUPTION_DEVICE_SWEEP_SECONDS.observe(ms / 1e3)
        self.last_dispatch = {"path": path, "sets": len(sets), "ms": round(ms, 3)}
        return verdicts

    def _assemble(
        self, enc: _Encoded, ctxs: List[_PoolCtx], left_total: np.ndarray,
        replace,
    ) -> List[SetVerdict]:
        """Shared verdict assembly: per-pool replacement passes in weight
        order, first feasible pool wins per set; ``replace(ctx)`` returns
        (best, best_od, best_k) numpy arrays for the current leftover."""
        verdicts = [
            SetVerdict(
                can_delete=bool(left_total[si] == 0),
                leftover=int(left_total[si]),
                replace_price=float("inf"),
                replace_od_price=float("inf"),
                replace_type=None,
                nodepool=None,
            )
            for si in range(enc.n_sets)
        ]
        pending = [si for si in range(enc.n_sets) if left_total[si] > 0]
        for ctx in ctxs:
            if not pending:
                break
            best, best_od, best_k = replace(ctx)
            still = []
            for si in pending:
                if np.isfinite(best[si]):
                    verdicts[si] = SetVerdict(
                        can_delete=False,
                        leftover=int(left_total[si]),
                        replace_price=float(best[si]),
                        replace_od_price=float(best_od[si]),
                        replace_type=ctx.catalog.names[int(best_k[si])],
                        nodepool=ctx.pool.name,
                    )
                else:
                    still.append(si)
            pending = still
        return verdicts

    # -- local route ----------------------------------------------------------
    def _dispatch_local(self, enc: _Encoded) -> np.ndarray:
        """[n_sets] leftover totals from the in-process repack kernel.
        SANCTIONED_FETCH (jax_discipline): the np.asarray below is this
        route's designed host barrier, async-prefetched."""
        import jax.numpy as jnp  # noqa: F401  (backend init on first dispatch)

        if self.mesh is not None:
            from karpenter_tpu.parallel.mesh import sharded_repack

            leftover, _ = sharded_repack(
                self.mesh, enc.headroom, enc.feas, enc.req, enc.member, enc.excl
            )
        else:
            leftover, _ = kernel.disrupt_repack(
                enc.headroom, enc.feas, enc.req, enc.member, enc.excl
            )
        if hasattr(leftover, "copy_to_host_async"):
            # one async D2H issued at dispatch (a synchronous fetch over a
            # tunneled device costs a flat ~64 ms RTT; see service.solve)
            leftover.copy_to_host_async()
        self._leftover = np.asarray(leftover)
        return self._leftover.sum(axis=1)

    def _evaluate_local(self, enc: _Encoded, ctxs: List[_PoolCtx]) -> List[SetVerdict]:
        import jax.numpy as jnp

        left_total = self._dispatch_local(enc)
        od_col = int(encode.CAPTYPE_INDEX[wk.CAPACITY_TYPE_ON_DEMAND])

        def replace(ctx: _PoolCtx):
            out = kernel.disrupt_replace(
                jnp.asarray(self._leftover), jnp.asarray(ctx.cs.req),
                jnp.asarray(ctx.compat), jnp.asarray(ctx.cs.azone),
                jnp.asarray(ctx.cs.acap), jnp.asarray(ctx.catalog.cap),
                jnp.asarray(ctx.ovh), jnp.asarray(ctx.catalog.price),
                od_col=od_col,
            )
            for x in out:
                if hasattr(x, "copy_to_host_async"):
                    x.copy_to_host_async()  # overlap the three fetches
            return tuple(np.asarray(x) for x in out)

        return self._assemble(enc, ctxs, left_total, replace)

    # -- wire route -----------------------------------------------------------
    def _evaluate_wire(self, enc: _Encoded, ctxs: List[_PoolCtx], client) -> List[SetVerdict]:
        """One sweep over the sidecar: the repack ships once (the leftover
        stays staged under a disrupt epoch), each pool's replacement pass
        ships only the class-side masks, and the catalog tensors never
        ship at all -- the op references the seqnum staged by the
        provisioning path. Raises on any wire failure the client's retry
        ladder cannot absorb; the caller falls back to the local route."""
        def replace_tensors(ctx: _PoolCtx) -> Dict[str, np.ndarray]:
            return {
                "creq": ctx.cs.req, "compat": ctx.compat,
                "azone": ctx.cs.azone, "acap": ctx.cs.acap, "ovh": ctx.ovh,
            }

        first = ctxs[0] if ctxs else None
        depoch, out = client.solve_disrupt_repack(
            {
                "headroom": enc.headroom, "feas": enc.feas, "req": enc.req,
                "member": enc.member, "excl": enc.excl,
            },
            seqnum=first.seqnum if first is not None else None,
            catalog=first.catalog if first is not None else None,
            replace=replace_tensors(first) if first is not None else None,
        )
        leftover = np.asarray(out["leftover"])
        left_total = leftover.sum(axis=1)
        first_result = (
            (np.asarray(out["best"]), np.asarray(out["best_od"]), np.asarray(out["best_k"]))
            if "best" in out else None
        )

        def replace(ctx: _PoolCtx):
            if ctx is first and first_result is not None:
                return first_result
            r = client.solve_disrupt_replace(
                depoch, seqnum=ctx.seqnum, catalog=ctx.catalog,
                replace=replace_tensors(ctx), leftover=leftover,
            )
            return (
                np.asarray(r["best"]), np.asarray(r["best_od"]), np.asarray(r["best_k"])
            )

        return self._assemble(enc, ctxs, left_total, replace)
