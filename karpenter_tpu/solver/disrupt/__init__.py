"""Device-resident consolidation engine (the disruption solve).

The batched candidate-set evaluator the disruption controller drives:
enumerate candidate node sets (singletons, price-ranked multi-node
prefixes, underutilized pairs), fit-check every evicted pod against the
surviving capacity AND the replacement instance-type options in one
masked device pass, and return per-set verdicts (delete /
replace-cheaper / blocked, with replacement type and savings) from a
single dispatch.

Layout:

- ``kernel.py``  -- the jitted device kernels (``disrupt_repack``,
  ``disrupt_replace``), registered in the jax-discipline manifests;
- ``engine.py``  -- ``DisruptEngine``: host-side encoding, candidate-set
  enumeration helpers, the wire dispatch (``solve_disrupt`` on the
  sidecar, reusing staged catalog seqnums), and the in-process fallback
  that keeps decisions bit-identical through the breaker/degrade ladder.

``solver/consolidate.py`` remains as the back-compat shim re-exporting
this package's public names.
"""
from karpenter_tpu.solver.disrupt.engine import (  # noqa: F401
    DisruptEngine,
    SetVerdict,
    device_eligible,
    enumerate_pairs,
)
