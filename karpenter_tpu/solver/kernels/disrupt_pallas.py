"""Pallas disrupt-repack kernel ([S, C, N] candidate-set simulation).

The XLA twin (solver/disrupt/kernel.py disrupt_repack) vmaps a per-set
lax.scan over pod classes; each scan step's [N, R] headroom carry
materializes between fusions. Here the grid is (S, C) -- row-major, so
the class axis iterates innermost -- and the headroom carry for the
current candidate set lives in VMEM scratch across the C steps: the
whole per-set repack simulation runs without touching HBM.

Step math is the twin's, float32 ops in the same order (per-axis floor
of headroom over requests, first-fit exclusive cumsum, clip to the
class count), so takes and leftovers are bit-identical by construction.

Boolean feasibility/exclusion operands arrive as float32 at the
pallas_call boundary (TPU kernels avoid sub-byte bool blocks); the
wrapper converts, the comparison against zero inside the kernel
restores the predicate.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INF = np.float32(np.inf)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# same signature and (empty) static bucket as disrupt_kernel.disrupt_repack,
# the registered XLA twin (jaxjit/pallas-twin links the two)
@jax.jit
def disrupt_repack_pallas(headroom0, feas, req, member, excl):
    S, N = excl.shape
    C, R = req.shape

    feas_f = feas.astype(jnp.float32)                             # [C, N]
    excl_f = excl.astype(jnp.float32)                             # [S, N]
    member_i = member.astype(jnp.int32)                           # [S, C]

    def kernel(
        head_ref, req_ref, feas_ref, excl_ref, member_ref,
        left_ref, takes_ref, hr_s,
    ):
        c = pl.program_id(1)

        @pl.when(c == 0)
        def _init():
            excl_row = excl_ref[0, :]                             # [N]
            hr_s[...] = jnp.where(
                excl_row[:, None] > 0.0, 0.0, head_ref[...]
            )

        hr = hr_s[...]                                            # [N, R]
        req_c = req_ref[0, :]                                     # [R]
        feas_c = feas_ref[0, :]                                   # [N]
        count_c = member_ref[0, 0]

        safe = jnp.where(req_c > 0.0, req_c, 1.0)
        per_axis = jnp.where(
            req_c[None, :] > 0.0, jnp.floor(hr / safe[None, :]), _INF
        )                                                         # [N, R]
        fit = jnp.maximum(jnp.min(per_axis, axis=-1), 0.0)
        fit = jnp.where(feas_c > 0.0, fit, 0.0).astype(jnp.int32)

        cum_before = jnp.cumsum(fit) - fit
        take = jnp.clip(count_c - cum_before, 0, fit)             # [N]
        hr2 = hr - take[:, None].astype(jnp.float32) * req_c[None, :]

        takes_ref[0, 0, :] = take
        left_ref[0, 0] = count_c - jnp.sum(take)
        hr_s[...] = hr2

    fixed = lambda s, c: (0, 0)  # noqa: E731

    leftover, takes = pl.pallas_call(
        kernel,
        grid=(S, C),
        in_specs=[
            pl.BlockSpec((N, R), fixed),
            pl.BlockSpec((1, R), lambda s, c: (c, 0)),
            pl.BlockSpec((1, N), lambda s, c: (c, 0)),
            pl.BlockSpec((1, N), lambda s, c: (s, 0)),
            pl.BlockSpec((1, 1), lambda s, c: (s, c), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda s, c: (s, c)),
            pl.BlockSpec((1, 1, N), lambda s, c: (s, c, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S, C), jnp.int32),
            jax.ShapeDtypeStruct((S, C, N), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((N, R), jnp.float32)],
        interpret=_interpret(),
    )(headroom0, req, feas_f, excl_f, member_i)
    return leftover, takes
