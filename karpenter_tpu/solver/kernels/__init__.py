"""Hand-written Pallas kernels for the two hottest solve entries.

Round-16 per-jit-entry attribution showed the FFD scan-reduce and the
disrupt repack paying XLA materialization between every scan step: each
step's [G, K] temporaries round-trip HBM because XLA schedules the scan
body as separate fusions. These kernels run the WHOLE sequential pass
inside one Pallas program -- the carry (group accumulators, the packed
group-type masks, zone/captype bitsets, the open-slot counter) lives in
VMEM/SMEM scratch across grid steps, and the group open/close logic is
fused into the same kernel, so nothing materializes between steps.

Masks are consumed in the bit-packed uint32 form (solver/packing.py):
the group-survivor x class-compat intersection is a bitwise AND on
packed words, 32 type columns per lane.

Contract (identical to every existing entry family):

- bit-identical outputs to the XLA twins -- same float32 ops in the
  same order, same argmin tie-breaking, same fused buffer layout
  (tests/test_packing.py asserts differentially);
- same jit signatures and static argument buckets, registered in
  JIT_ENTRY_FUNCTIONS / STATIC_ARG_BUCKETS / DEVICE_HOT_PATH like the
  twins, and every kernel here MUST keep a registered XLA twin (the
  jaxjit pallas-twin lint rule) -- the fallback rung cannot be
  orphaned;
- selected via ``TPUSolver(kernels="pallas")``; any lowering or runtime
  failure (including VMEM overflow at extreme [G, K] tiers) is caught
  at dispatch and pins the process to the XLA twin
  (service._dispatch_fused) -- decisions never change, only who
  computes them;
- interpret mode on non-TPU backends (a trace-time backend read), so
  the differential suite runs the real kernel logic on CPU rigs.
"""
from karpenter_tpu.solver.kernels import disrupt_pallas, ffd_pallas  # noqa: F401
