"""Fused Pallas FFD scan-reduce (the provisioning solve's inner loop).

One Pallas program runs the whole class scan: grid = (C,), carry in
scratch (accum [G, R] f32 in VMEM, the group-survivor mask BIT-PACKED
[G, KW] u32 in VMEM, the packed zone/captype bitsets [G] u32, the
open-slot counter in SMEM), so the per-step [G, K] temporaries never
round-trip HBM and the group open/close arithmetic fuses with the fit
reduction. Per-class operands stream in as (1, ...) blocks -- exactly
the xs of the XLA twin's lax.scan (solver/ffd.py _ffd_body).

The survivor-mask x class-compat intersection is a bitwise AND on the
packed words (32 type columns per u32 lane); rows unpack in-register
only where the fit arithmetic needs the full width.

The XLA prologue (compat, fresh fits, price tables -- all batch [C, K]
work with no sequential dependence) and epilogue (sparse take, fused
u32 buffer concat) are shared with the twin BY CALLING ITS HELPERS, so
the only reimplemented math is the scan step itself -- float32 ops in
the twin's order, same argmin tie-breaking: bit-identical by
construction, asserted differentially in tests/test_packing.py.

Interpret mode on non-TPU backends (trace-time backend read) runs the
same kernel logic on CPU rigs; real-TPU lowering failures (e.g. VMEM
overflow at extreme [G, K] tiers) surface at dispatch and take the XLA
fallback rung (service._dispatch_fused).
"""
from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.solver import ffd, packing

_INF = np.float32(np.inf)


def _interpret() -> bool:
    """Trace-time backend read: the kernel interprets everywhere but on
    a real TPU (same program either way -- interpret mode executes the
    identical kernel logic through XLA on the host)."""
    return jax.default_backend() != "tpu"


def _pack_rows(mask: jax.Array) -> jax.Array:
    """[..., K] bool -> [..., K/32] u32, little-endian within the word
    (bit j of word w = column 32w + j; packing.py's convention and the
    CompactDecision.gmask_bits convention -- one bit layout everywhere)."""
    k = mask.shape[-1]
    kw = k // 32
    return jnp.sum(
        mask.reshape(mask.shape[:-1] + (kw, 32)).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32),
        axis=-1,
    )


def _unpack_rows(words: jax.Array, k: int) -> jax.Array:
    """[..., KW] u32 -> [..., k] bool (inverse of _pack_rows)."""
    bits = (words[..., :, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    return bits.reshape(words.shape[:-1] + (k,)).astype(bool)


def _fused_scan(
    inp: ffd.SolveInputs, g_max: int, word_offsets: Tuple[int, ...],
    words: Tuple[int, ...], objective: str,
):
    """(take [C, G] i32, unplaced [C] i32, n_open i32, gmask_bits
    [G, KW] u32, gzc [G] u32): the scan of _ffd_body as one Pallas
    program, outputs already in the compact decision's packed forms."""
    C, R = inp.req.shape
    K = int(inp.cap.shape[0])
    if K % 32:
        raise ValueError(f"pallas ffd kernel needs k_pad % 32 == 0, got {K}")
    KW = K // 32
    G = g_max

    # -- XLA prologue: the twin's hoisted batch work, via its helpers ----
    join_allowed = packing.as_bool_mask_jnp(inp.join_allowed, K)
    open_allowed = packing.as_bool_mask_jnp(inp.open_allowed, K)
    compat = ffd._device_compat(inp, word_offsets, words) & join_allowed
    cap_eff = jnp.maximum(inp.cap - inp.node_overhead[None, :], 0.0)
    tzc = ffd._pack_zc(inp.tzone, inp.tcap)                       # [K] u32
    azc = ffd._pack_zc(inp.azone, inp.acap)                       # [C] u32
    n_fresh_all = ffd._fresh_fit_counts(cap_eff, inp.req)         # [C, K]
    fresh_join = ffd._joint_ok(azc[:, None] & tzc[None, :])
    fresh_mask_all = compat & fresh_join & open_allowed
    if objective == "price":
        price_ck, has_res_ck = ffd._class_type_price(inp)
    else:
        price_ck = jnp.zeros_like(n_fresh_all)
        has_res_ck = jnp.zeros(n_fresh_all.shape, dtype=bool)

    # the kernel's streamed mask operands, bit-packed 32 columns per lane
    compat_w = _pack_rows(compat)                                 # [C, KW]
    fresh_w = _pack_rows(fresh_mask_all)                          # [C, KW]
    hasres_w = _pack_rows(has_res_ck)                             # [C, KW]
    count2 = inp.count.reshape(C, 1).astype(jnp.int32)
    env2 = inp.env_count.reshape(C, 1).astype(jnp.int32)
    azc2 = azc.reshape(C, 1)
    tzc2 = tzc.reshape(1, K)

    def kernel(
        req_ref, compat_ref, fresh_ref, nfresh_ref, price_ref, hasres_ref,
        count_ref, env_ref, azc_ref, cap_ref, tzc_ref,
        take_ref, unp_ref, gmasko_ref, gzco_ref, nopeno_ref,
        accum_s, gmask_s, gzc_s, nopen_s,
    ):
        c = pl.program_id(0)

        @pl.when(c == 0)
        def _init():
            accum_s[...] = jnp.zeros_like(accum_s)
            gmask_s[...] = jnp.zeros_like(gmask_s)
            gzc_s[...] = jnp.zeros_like(gzc_s)
            nopen_s[0] = jnp.int32(0)

        accum = accum_s[...]                                      # [G, R]
        gmask_w = gmask_s[...]                                    # [G, KW]
        gzc = gzc_s[...][:, 0]                                    # [G] u32
        n_open = nopen_s[0]

        req_c = req_ref[0, :]                                     # [R]
        count_c = count_ref[0, 0]
        env_c = env_ref[0, 0]
        azc_c = azc_ref[0, 0]
        tzc_k = tzc_ref[0, :]                                     # [K] u32
        cap_k = cap_ref[...]                                      # [K, R]
        compat_cw = compat_ref[0, :]                              # [KW] u32
        fresh_row = _unpack_rows(fresh_ref[0, :], K)              # [K] bool
        has_res_row = _unpack_rows(hasres_ref[0, :], K)
        n_fresh_row = nfresh_ref[0, :]                            # [K] f32
        price_row = price_ref[0, :]

        slot = jax.lax.broadcasted_iota(jnp.int32, (G, 1), 0)[:, 0]
        inf32 = jnp.float32(jnp.inf)

        # -- joint feasibility: bitwise AND on the PACKED words, then the
        #    zone/captype bitset intersection (twin: _ffd_body.step)
        gzc_new = gzc & azc_c                                     # [G] u32
        mw = gmask_w & compat_cw[None, :]                         # [G, KW]
        m = _unpack_rows(mw, K) & ffd._joint_ok(
            gzc_new[:, None] & tzc_k[None, :]
        )                                                         # [G, K]

        # -- in-scan fit counts, R-unrolled exactly like ffd._fit_counts
        n_fit = None
        for r in range(R):
            d = jnp.where(req_c[r] > 0.0, req_c[r], 1.0)
            axis_n = jnp.where(
                req_c[r] > 0.0,
                jnp.floor((cap_k[None, :, r] - accum[:, r, None]) / d),
                inf32,
            )                                                     # [G, K]
            n_fit = axis_n if n_fit is None else jnp.minimum(n_fit, axis_n)
        n_fit = jnp.maximum(n_fit, 0.0)

        n_grp = jnp.max(jnp.where(m, n_fit, 0.0), axis=-1)        # [G]
        n_grp = jnp.where(slot < n_open, n_grp, 0.0).astype(jnp.int32)

        cum_before = jnp.cumsum(n_grp) - n_grp
        take = jnp.clip(count_c - cum_before, 0, n_grp)           # [G]
        placed = jnp.sum(take)
        leftover = count_c - placed

        max_fit_f = jnp.max(jnp.where(fresh_row, n_fresh_row, 0.0))
        per_new_fit = max_fit_f.astype(jnp.int32)
        if objective == "price":
            env = jnp.where(
                env_c > 0, env_c, jnp.maximum(leftover + (-env_c - 1), 1)
            )
            ngroups = jnp.ceil(
                env.astype(jnp.float32) / jnp.maximum(n_fresh_row, 1.0)
            )
            envf = env.astype(jnp.float32)
            need = jnp.minimum(max_fit_f, envf)
            eligible = (
                fresh_row
                & (n_fresh_row >= 1.0)
                & ((2.0 * jnp.minimum(n_fresh_row, envf) >= need) | has_res_row)
            )
            total_cost = jnp.where(eligible, price_row * ngroups, inf32)
            kstar = jnp.argmin(total_cost)
            ok = jnp.isfinite(total_cost[kstar])
            per_new_price = jnp.where(ok, n_fresh_row[kstar], 0.0).astype(jnp.int32)
            p_star = price_row[kstar]
            price_mask = (
                fresh_row
                & (n_fresh_row >= per_new_price.astype(n_fresh_row.dtype))
                & (price_row <= p_star)
                & ok
            )
            use_fit = env_c == 0
            per_new = jnp.where(use_fit, per_new_fit, per_new_price)
            open_mask = jnp.where(use_fit, fresh_row, price_mask)
        else:
            per_new = per_new_fit
            open_mask = fresh_row

        can_open = (leftover > 0) & (per_new > 0)
        n_new = jnp.where(can_open, -(-leftover // jnp.maximum(per_new, 1)), 0)
        n_new = jnp.minimum(n_new, G - n_open)
        is_new = (slot >= n_open) & (slot < n_open + n_new)
        ordinal = slot - n_open
        take_new = jnp.where(
            is_new, jnp.clip(leftover - ordinal * per_new, 0, per_new), 0
        ).astype(jnp.int32)

        take_all = take + take_new
        still_unplaced = count_c - jnp.sum(take_all)

        accum2 = accum + take_all[:, None].astype(jnp.float32) * req_c[None, :]
        takef = take_all.astype(jnp.float32)
        touched_existing = take > 0
        gmask2 = jnp.where(
            touched_existing[:, None], m & (takef[:, None] <= n_fit),
            _unpack_rows(gmask_w, K),
        )
        gmask2 = jnp.where(
            is_new[:, None],
            open_mask[None, :] & (takef[:, None] <= n_fresh_row[None, :]),
            gmask2,
        )
        gmask2_w = _pack_rows(gmask2)                             # [G, KW]
        gzc2 = jnp.where(touched_existing, gzc_new, gzc)
        gzc2 = jnp.where(is_new, azc_c, gzc2)
        n_open2 = n_open + n_new

        take_ref[0, :] = take_all
        unp_ref[0, 0] = still_unplaced
        accum_s[...] = accum2
        gmask_s[...] = gmask2_w
        gzc_s[...] = gzc2[:, None]
        nopen_s[0] = n_open2

        @pl.when(c == pl.num_programs(0) - 1)
        def _final():
            gmasko_ref[...] = gmask2_w
            gzco_ref[...] = gzc2[:, None]
            nopeno_ref[0, 0] = n_open2

    fixed = lambda c: (0, 0)  # noqa: E731 -- whole-array block each step
    row = lambda c: (c, 0)    # noqa: E731 -- per-class streamed block

    take, unplaced, gmask_bits, gzc_out, n_open = pl.pallas_call(
        kernel,
        grid=(C,),
        in_specs=[
            pl.BlockSpec((1, R), row),
            pl.BlockSpec((1, KW), row),
            pl.BlockSpec((1, KW), row),
            pl.BlockSpec((1, K), row),
            pl.BlockSpec((1, K), row),
            pl.BlockSpec((1, KW), row),
            pl.BlockSpec((1, 1), row, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), row, memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), row, memory_space=pltpu.SMEM),
            pl.BlockSpec((K, R), fixed),
            pl.BlockSpec((1, K), fixed),
        ],
        out_specs=[
            pl.BlockSpec((1, G), row),
            pl.BlockSpec((1, 1), row),
            pl.BlockSpec((G, KW), fixed),
            pl.BlockSpec((G, 1), fixed),
            pl.BlockSpec((1, 1), fixed, memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, G), jnp.int32),
            jax.ShapeDtypeStruct((C, 1), jnp.int32),
            jax.ShapeDtypeStruct((G, KW), jnp.uint32),
            jax.ShapeDtypeStruct((G, 1), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, R), jnp.float32),
            pltpu.VMEM((G, KW), jnp.uint32),
            pltpu.VMEM((G, 1), jnp.uint32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=_interpret(),
    )(
        inp.req, compat_w, fresh_w, n_fresh_all, price_ck, hasres_w,
        count2, env2, azc2, cap_eff, tzc2,
    )
    return take, unplaced[:, 0], n_open[0, 0], gmask_bits, gzc_out[:, 0]


# same signature, statics, and fused buffer layout as ffd.ffd_solve_fused
# (the registered XLA twin -- jaxjit/pallas-twin links the two)
@functools.partial(jax.jit, static_argnames=("g_max", "nnz_max", "word_offsets", "words", "objective"))
def ffd_solve_fused_pallas(
    inp: ffd.SolveInputs,
    *,
    g_max: int,
    nnz_max: int,
    word_offsets: Tuple[int, ...],
    words: Tuple[int, ...],
    objective: str = "price",
) -> jax.Array:
    take, unplaced, n_open, gmask_bits, gzc = _fused_scan(
        inp, g_max, word_offsets, words, objective
    )
    idx, val, nnz_true = ffd._sparse_take(take, nnz_max)
    parts = [
        nnz_true.reshape(1).astype(jnp.uint32),
        n_open.reshape(1).astype(jnp.uint32),
        jax.lax.bitcast_convert_type(unplaced, jnp.uint32).ravel(),
        jax.lax.bitcast_convert_type(idx, jnp.uint32).ravel(),
        jax.lax.bitcast_convert_type(val, jnp.uint32).ravel(),
        gmask_bits.ravel(),
        gzc.ravel(),
    ]
    return jnp.concatenate(parts)
