"""Binary entry point: compose the operator and run the controller loop.

The analogue of cmd/controller/main.go:30-84 (operator construction, flag
parsing, controller registration, manager start) combined with kwok/main.go
(the in-memory cloud stands in for a real account, so the full stack --
providers, batchers, CloudProvider, all reconcilers, the TPU decision
plane -- runs self-contained). Flags mirror pkg/operator/options/options.go.

    python -m karpenter_tpu --help
    python -m karpenter_tpu --max-ticks 50 --tick-interval 0.1
    python -m karpenter_tpu --sim-record trace.jsonl --max-ticks 50
    python -m karpenter_tpu sim replay --differential trace.jsonl
"""
from __future__ import annotations

import argparse
import signal
import sys


def build_operator(args):
    from karpenter_tpu.operator import Operator, Options

    options = Options(
        cluster_name=args.cluster_name,
        interruption_queue=args.interruption_queue,
        vm_memory_overhead_percent=args.vm_memory_overhead_percent,
        reserved_nics=args.reserved_nics,
        isolated_network=args.isolated_network,
        pipelined_scheduling=getattr(args, "pipelined_scheduling", True),
        tick_deadline=getattr(args, "tick_deadline", 0.0),
        admission_max_pods=getattr(args, "admission_max_pods", 0),
        launch_max_groups=getattr(args, "launch_max_groups", 0),
        tracing=getattr(args, "tracing", True),
        tracing_sample=getattr(args, "trace_sample", 0.2),
        tracing_slow_ms=getattr(args, "trace_slow_ms", 1000.0),
        observatory=getattr(args, "observatory", True),
        seed=getattr(args, "seed", None),
    )
    # feature gates merge over the defaults (reference: the core's
    # --feature-gates flag, checked e.g. at cmd/controller/main.go:45-47)
    for pair in filter(None, (args.feature_gates or "").split(",")):
        name, sep, value = pair.partition("=")
        value = value.strip().lower()
        # malformed pairs fail startup loudly (the core's map-flag
        # semantics): a bare gate name or a typo'd boolean silently
        # becoming False would disable the feature the operator asked for
        if not sep or value not in ("true", "false", "1", "0", "yes", "no"):
            raise SystemExit(
                f"--feature-gates: malformed pair {pair!r} (want Name=true|false)"
            )
        options.feature_gates[name.strip()] = value in ("true", "1", "yes")
    solver = None
    evaluator = None
    if args.tpu_solver:
        from karpenter_tpu.logging import get_logger
        from karpenter_tpu.utils import enable_jax_compilation_cache, probe_jax_backend

        # probe the accelerator in a subprocess FIRST: a hung device tunnel
        # would otherwise block operator startup forever at jax backend
        # init; on failure the solver runs on the host CPU backend (same
        # code path, degraded speed) instead of taking the controller down
        # operator startup patience: one 60s attempt (the bench keeps its
        # longer 2x120s patience -- it must salvage a flaky tunnel; the
        # controller must come up and serve)
        backend, err = probe_jax_backend(timeout_s=60, attempts=1)
        if backend is None:
            # pin the platform via the ENV before the first jax import:
            # a sitecustomize hook may have re-pinned JAX_PLATFORMS to
            # the remote-accelerator plugin, whose INIT AT IMPORT TIME
            # hangs on a dead tunnel -- the exact wedge the probe just
            # detected (jax.config.update alone is too late to stop the
            # plugin's import-time work)
            import os as _os

            _os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
            get_logger("operator").warning(
                "accelerator probe failed; solver degrades to host cpu backend",
                error=(err or "")[:200],
            )
        from karpenter_tpu.solver.consolidate import ConsolidationEvaluator
        from karpenter_tpu.solver.service import TPUSolver

        cache_home = enable_jax_compilation_cache()
        # sidecar topology (deploy/controller.yaml): the solver process
        # owns the chip; this process ships tensors over its UNIX socket
        import os as _os

        sock = _os.environ.get("KARPENTER_TPU_SOLVER_SOCKET", "")
        addr = _os.environ.get("KARPENTER_TPU_SOLVER_ADDR", "")
        solver_timeouts = dict(
            timeout=getattr(args, "solver_timeout", 30.0),
            connect_timeout=getattr(args, "solver_connect_timeout", 1.0),
        )
        client = None
        if sock:
            from karpenter_tpu.solver.rpc import SolverClient

            client = SolverClient(path=sock, **solver_timeouts)
        elif addr:
            # TCP sidecar (deploy/values.yaml solver.tcp): the shared
            # token rides $KARPENTER_TPU_SOLVER_TOKEN on both ends; TLS
            # verifies the solver against $KARPENTER_TPU_SOLVER_TLS_CA
            # (the cert's SAN must cover
            # $KARPENTER_TPU_SOLVER_TLS_SERVERNAME, default the host)
            from karpenter_tpu.solver.rpc import SolverClient

            host, _, port = addr.rpartition(":")
            ctx = None
            ca = _os.environ.get("KARPENTER_TPU_SOLVER_TLS_CA", "")
            if ca:
                import ssl

                ctx = ssl.create_default_context(cafile=ca)
            client = SolverClient(
                host or "127.0.0.1", int(port), ssl_context=ctx,
                server_hostname=_os.environ.get("KARPENTER_TPU_SOLVER_TLS_SERVERNAME") or None,
                **solver_timeouts,
            )
        breaker = None
        if client is not None:
            # wire circuit breaker (solver/breaker.py): K consecutive RPC
            # failures open it and solves short-circuit to the in-process
            # CPU path; a background jittered-backoff probe re-tests the
            # sidecar and re-promotion restages the catalog
            from karpenter_tpu.solver.breaker import CircuitBreaker

            breaker_kw = {}
            if getattr(args, "seed", None) is not None:
                # seed discipline: the backoff jitter joins the Options.seed
                # derivation chain (the breaker takes an injected rng, so
                # the seed is applied where the breaker is built)
                from karpenter_tpu.seeding import seeded_rng

                breaker_kw["rng"] = seeded_rng("breaker", args.seed).random
            breaker = CircuitBreaker(
                failure_threshold=getattr(args, "breaker_failures", 3),
                backoff_base=getattr(args, "breaker_backoff", 0.5),
                backoff_max=getattr(args, "breaker_backoff_max", 30.0),
                auto_probe=True,
                **breaker_kw,
            )
        # mesh-sharded production solve (karpenter_tpu/fleet/): in-process
        # mode only -- a sidecar owns its own mesh via `python -m
        # karpenter_tpu.solver.rpc --mesh`
        mesh = None
        if client is None:
            from karpenter_tpu.fleet.shard import mesh_from_env, parse_mesh_spec

            spec = getattr(args, "mesh_devices", None)
            mesh = parse_mesh_spec(spec) if spec else mesh_from_env()
        solver = TPUSolver(
            auto_warm=client is None, client=client, breaker=breaker, mesh=mesh,
            tier=getattr(args, "solve_tier", "ffd"),
        )
        # AOT compile-cache subsystem (solver/aot.py): load serialized
        # executables now (the restart path's compile-free first tick)
        # and arm the background warmup ladder for every staged catalog.
        # In-process backends only (a sidecar owns its own AOT);
        # KARPENTER_TPU_AOT=0 opts out.
        if client is None and _os.environ.get("KARPENTER_TPU_AOT", "1") != "0":
            solver.enable_aot(
                _os.path.join(cache_home, "exec") if cache_home else None)
        # the consolidation engine rides the SAME wire as the scheduling
        # solve: with a sidecar configured, candidate-set sweeps dispatch
        # as the solve_disrupt op against the catalogs already staged per
        # seqnum, and the breaker's degrade ladder covers both paths
        evaluator = ConsolidationEvaluator(solver=solver, mesh=mesh)
    cluster = None
    if getattr(args, "kubeconfig", None) or getattr(args, "in_cluster", False):
        # real coordination bus (the reference's kwok deployment topology:
        # live apiserver, emulated cloud). Apply apis/crds/*.yaml first.
        from karpenter_tpu.kube import KubeClient, KubeConfig, KubeCluster

        cfg = (
            KubeConfig.in_cluster()
            if getattr(args, "in_cluster", False)
            else KubeConfig.from_kubeconfig(args.kubeconfig)
        )
        cluster = KubeCluster(KubeClient(cfg))
        # over a real bus, on_event handlers fire from watch threads (the
        # in-memory store dispatches synchronously from writes); without
        # this the pod-arrival wake-up and its batching window never engage
        from karpenter_tpu.apis import Pod

        cluster.watch_events([Pod])
    return Operator(
        options=options, solver=solver, consolidation_evaluator=evaluator,
        identity=getattr(args, "identity", ""),
        cluster=cluster,
    )


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "sim":
        # the simulation subsystem has its own verb-style CLI (generate /
        # replay / shrink / corpus) -- see karpenter_tpu/sim/cli.py
        from karpenter_tpu.sim.cli import main as sim_main

        return sim_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="karpenter-tpu", description="TPU-native node provisioning controller (kwok rig)"
    )
    parser.add_argument("--cluster-name", default="kwok-cluster")
    parser.add_argument(
        "--identity", default="",
        help="replica identity for leader election (empty = single replica, no election)",
    )
    parser.add_argument("--interruption-queue", default="interruption-queue")
    parser.add_argument("--vm-memory-overhead-percent", type=float, default=0.075)
    parser.add_argument("--reserved-nics", type=int, default=0)
    parser.add_argument("--isolated-network", action="store_true")
    parser.add_argument(
        "--feature-gates",
        default="",
        help="comma-separated Name=true|false (e.g. SpotToSpotConsolidation=true)",
    )
    parser.add_argument(
        "--tpu-solver", action=argparse.BooleanOptionalAction, default=True,
        help="route scheduling + consolidation decisions through the accelerator",
    )
    parser.add_argument(
        "--solve-tier", choices=("ffd", "convex"), default="ffd",
        help="solver decision tier: 'convex' runs the device-resident LP "
        "relaxation + deterministic rounding beside every FFD solve and "
        "ships whichever decision prices lower (never worse than FFD by "
        "construction), tightens the optimality-gap bound, and arms the "
        "global repack oracle in the disruption sweep; 'ffd' (default) "
        "is the plain fused first-fit-decreasing solve",
    )
    parser.add_argument(
        "--mesh-devices", default=None, metavar="SPEC",
        help="shard the in-process production solve across a device mesh: "
        "a count ('8') or NxM hosts-x-devices layout ('2x4'); default "
        "$KARPENTER_TPU_MESH, else single-device (ignored with a sidecar "
        "configured -- run the sidecar with --mesh instead)",
    )
    parser.add_argument(
        "--pipelined-scheduling", action=argparse.BooleanOptionalAction, default=True,
        help="double-buffer the provisioner tick under sustained load (the "
        "device solve overlaps the rest of the sweep; --no-pipelined-scheduling "
        "pins the synchronous dispatch+barrier path)",
    )
    parser.add_argument(
        "--solver-timeout", type=float, default=30.0,
        help="per-solve READ budget on the solver wire (seconds)",
    )
    parser.add_argument(
        "--solver-connect-timeout", type=float, default=1.0,
        help="solver-wire connection-establishment budget: connect + TLS + "
        "auth (seconds; split from --solver-timeout so a dead sidecar "
        "fails a degraded tick in ~1s, not the solve budget)",
    )
    parser.add_argument(
        "--breaker-failures", type=int, default=3,
        help="consecutive solver-wire failures that OPEN the circuit "
        "breaker (solves then fall back to the in-process CPU path "
        "instantly until a probe re-promotes)",
    )
    parser.add_argument(
        "--breaker-backoff", type=float, default=0.5,
        help="initial half-open probe backoff (seconds; doubles per failed "
        "probe with 0-50%% jitter)",
    )
    parser.add_argument(
        "--breaker-backoff-max", type=float, default=30.0,
        help="half-open probe backoff cap (seconds)",
    )
    parser.add_argument(
        "--tick-deadline", type=float, default=0.0,
        help="per-tick deadline budget in seconds (0 disables): arms the "
        "overload subsystem -- hierarchical stage budgets that clamp the "
        "solver wire's read timeout, deadline-sized admission shedding, "
        "the brownout ladder (disruption -> tracing -> delta staging), "
        "and the stuck-tick watchdog (cancel -> breaker-open -> crash)",
    )
    parser.add_argument(
        "--admission-max-pods", type=int, default=0,
        help="bounded admission: at most this many pending pods solved "
        "per tick; over the cap a deterministic priority/age-ordered "
        "prefix solves and the rest defer to later ticks (0 = unbounded)",
    )
    parser.add_argument(
        "--launch-max-groups", type=int, default=0,
        help="bounded launch fan-out: at most this many decision groups "
        "launch per tick; deferred groups' pods stay pending (0 = unbounded)",
    )
    parser.add_argument(
        "--failpoints", default="",
        help="arm fault-injection sites for game-day drills, e.g. "
        "'rpc.server.dispatch=latency(0.05):p=0.3;instance.launch="
        "error(InsufficientCapacityError):times=5' (also via "
        "$KARPENTER_TPU_FAILPOINTS; see karpenter_tpu/failpoints.py)",
    )
    parser.add_argument(
        "--kubeconfig", default="",
        help="run against a REAL apiserver via this kubeconfig (apply apis/crds/*.yaml first)",
    )
    parser.add_argument(
        "--in-cluster", action="store_true",
        help="use the pod serviceaccount to reach the apiserver",
    )
    parser.add_argument("--tick-interval", type=float, default=1.0, help="seconds between sweeps")
    parser.add_argument(
        "--health-port", type=int, default=8081,
        help="liveness/readiness/metrics HTTP port (0 disables)",
    )
    parser.add_argument("--max-ticks", type=int, default=0, help="stop after N sweeps (0 = run forever)")
    parser.add_argument("--metrics-dump", action="store_true", help="print Prometheus metrics on exit")
    parser.add_argument(
        "--tracing", action=argparse.BooleanOptionalAction, default=True,
        help="scheduling-tick span tracing + slow-tick flight recorder "
        "(/debug/traces); sampled -- see --trace-sample",
    )
    parser.add_argument(
        "--trace-sample", type=float, default=0.2,
        help="fraction of sweeps feeding the per-span stats/metrics volume "
        "(the flight recorder judges EVERY sweep regardless; default 0.2)",
    )
    parser.add_argument(
        "--trace-slow-ms", type=float, default=1000.0,
        help="flight-recorder threshold: retain span trees for sweeps slower than this",
    )
    parser.add_argument(
        "--trace-dump", action="store_true",
        help="print the slow-tick flight recorder (JSON span trees) on exit",
    )
    parser.add_argument(
        "--observatory", action=argparse.BooleanOptionalAction, default=True,
        help="device performance observatory (karpenter_tpu/obs/): per-tick "
        "HBM accounting, the always-on flight-data ring behind "
        "/debug/flightdata (crash-flushed to $KARPENTER_TPU_FLIGHTDATA), "
        "profiler tick bracketing, and the per-jit-entry cost table",
    )
    parser.add_argument(
        "--profile-ticks", type=int, default=0, metavar="N",
        help="arm an on-demand jax.profiler capture bracketing the first N "
        "production ticks (trace dir under $KARPENTER_TPU_PROFILE_DIR, "
        "default profiles/; same machinery as GET /debug/profile?ticks=N)",
    )
    parser.add_argument(
        "--seed", type=int, default=None,
        help="determinism root: every RNG on the replay path (object-name "
        "suffixes, failpoint schedules, trace sampling, breaker jitter) "
        "derives from this one seed (karpenter_tpu/sim/)",
    )
    parser.add_argument(
        "--sim-record", default="", metavar="PATH",
        help="capture this run as a replayable JSONL trace at the cluster/"
        "cloud seam (pod arrivals/deletes, kills, interruptions, ICE, "
        "pricing, clock advances); replay with `sim replay PATH`",
    )
    args = parser.parse_args(argv)

    if args.failpoints:
        # arm BEFORE the operator graph builds so cold-start paths
        # (catalog hydration, first connects) are injectable too
        from karpenter_tpu.failpoints import FAILPOINTS

        # seed FIRST: a Failpoint captures the registry seed at arm time,
        # so arming before the Operator's seed fan-out would build the
        # fault schedule from the default seed and break --seed replays
        if args.seed is not None:
            FAILPOINTS.seed = args.seed
        FAILPOINTS.arm_spec(args.failpoints)

    # health endpoints come up BEFORE the operator graph builds: a slow
    # or wedged cold start (catalog hydration, a hung cloud call) must
    # answer liveness 200 (readiness stays 503 until the first sweep) --
    # no listener at all reads as probe failure and restart-loops the pod
    health = None
    if args.health_port:
        from karpenter_tpu.operator.health import HealthServer

        # the stall window scales with the configured sweep cadence: a
        # long --tick-interval is a HEALTHY quiet loop, not a wedge
        health = HealthServer(
            port=args.health_port,
            stall_after=max(300.0, 5 * args.tick_interval),
        ).start()

    op = build_operator(args)
    if health is not None:
        breaker = getattr(op.solver, "breaker", None)
        if breaker is not None:
            # /healthz carries the breaker state; /debug/breaker serves the
            # full document (loopback-only)
            health.breaker_info = breaker.describe
        if hasattr(op.solver, "describe_wire"):
            # /debug/solver: incremental-tick engine + staging LRU state
            health.solver_info = op.solver.describe_wire
        if hasattr(op.solver, "describe_aot"):
            # /debug/aot: AOT armed-executable coverage + warmup ladder
            health.aot_info = op.solver.describe_aot
        # /debug/journal: the crash-consistency intent journal (open
        # write-ahead records + the recently-resolved ring)
        health.journal_info = op.journal.describe
        # /debug/overload: deadline/admission bounds + brownout/watchdog
        health.overload_info = op.describe_overload
        # /debug/profile only arms captures a tick will actually service
        health.profile_enabled = args.observatory
    if args.profile_ticks > 0 and args.observatory:
        # same machinery the /debug/profile endpoint arms -- here it
        # brackets the FIRST ticks, so warmup compiles land in the trace
        from karpenter_tpu.obs.profiler import PROFILER

        PROFILER.request(args.profile_ticks)
    if op.watchdog is not None:
        # the stuck-tick watchdog's background thread is a wall-clock
        # deployment concern -- deterministic rigs drive check_now().
        # Its crash escalation raises OperatorCrashed in the run loop
        # below; nothing here may catch it (the process dies, the
        # supervisor restarts it, and the recovery sweep takes over).
        op.watchdog.start()
    # latency GC policy: the provider graph and (if enabled) the jax
    # runtime are now the long-lived baseline; freeze it and stop gen2
    # collections from landing inside scheduling ticks
    from karpenter_tpu.utils import configure_gc_for_latency

    configure_gc_for_latency()
    # a default NodeClass + NodePool so the RIG provisions out of the box.
    # Never against a real apiserver: auto-writing a provisioning policy
    # into live infrastructure is an operator decision, not a default.
    from karpenter_tpu.apis import NodePool, TPUNodeClass

    kube_mode = bool(args.kubeconfig or args.in_cluster)
    if not kube_mode and not op.cluster.list(TPUNodeClass):
        op.cluster.create(TPUNodeClass("default"))
    if not kube_mode and not op.cluster.list(NodePool):
        op.cluster.create(NodePool("default"))

    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    recorder = None
    if args.sim_record:
        # capture hook at the cluster/cloud seam (sim subsystem): external
        # events become a replayable trace, dumped on exit
        from karpenter_tpu.sim.trace import TraceRecorder

        recorder = TraceRecorder(
            op.cluster, op.clock, scenario="recorded", seed=args.seed
        ).attach(op.cloud if not kube_mode else None)

    ticks = 0
    op.watch_pods()   # pod arrivals wake the loop through the batch window
    try:
        while not stop["flag"]:
            swept = op.tick()
            if recorder is not None and swept:
                recorder.record_tick()
            if health is not None:
                # the LOOP beat proves the process turns (leader or standby:
                # liveness); the SWEEP beat only on a real sweep (readiness)
                health.beat_loop()
                if swept:
                    health.beat_sweep()
            ticks += 1
            if args.max_ticks and ticks >= args.max_ticks:
                break
            op.wait_for_work(args.tick_interval)
    except BaseException:
        # OperatorCrashed (and any other death) still propagates -- the
        # process must die loudly for the supervisor -- but the black
        # box's location goes to stderr first so the postmortem knows
        # where to start (Operator.tick already flushed it)
        from karpenter_tpu.obs.flight import RECORDER as _flight

        if _flight.flushes:
            print(
                f"flight data: {_flight.dump()['last_flush_path']}",
                file=sys.stderr,
            )
        raise
    if op.watchdog is not None:
        op.watchdog.stop()
    if health is not None:
        health.stop()
    if recorder is not None:
        n = recorder.dump(args.sim_record)
        print(f"sim trace: {n} events -> {args.sim_record}", file=sys.stderr)

    if args.metrics_dump:
        from karpenter_tpu import metrics

        print(metrics.REGISTRY.expose())
    if args.trace_dump:
        from karpenter_tpu import tracing

        print(tracing.dump_json(indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
