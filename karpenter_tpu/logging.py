"""Structured JSON logging with change-deduplication.

The reference logs zap JSON through controller-runtime's log.FromContext and
suppresses repeat messages with a ChangeMonitor (e.g. the instance-type
provider logs catalog updates only when the hash changes,
pkg/providers/instancetype/instancetype.go:267-271). This module is that
pattern over the stdlib:

    log = get_logger("provisioner")
    log.info("launched node group", nodepool="default", pods=12)

emits one JSON object per line on stderr:

    {"ts": ..., "level": "INFO", "logger": "karpenter.provisioner",
     "msg": "launched node group", "nodepool": "default", "pods": 12}

and a ChangeMonitor keyed by any hashable value logs only on change:

    if MONITOR.has_changed("catalog", seqnum):
        log.info("instance types updated", count=n)
"""
from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any, Dict, Optional

ROOT = "karpenter"

_RESERVED = set(
    "name msg args asctime levelname levelno pathname filename module exc_info "
    "exc_text stack_info lineno funcName created msecs relativeCreated "
    "thread threadName processName process taskName message".split()
)


class JSONFormatter(logging.Formatter):
    """One JSON object per record; every non-reserved record attribute
    (the kwargs of StructuredAdapter) becomes a top-level field."""

    def format(self, record: logging.LogRecord) -> str:
        doc: Dict[str, Any] = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for key, value in record.__dict__.items():
            if key in _RESERVED or key.startswith("_"):
                continue
            try:
                json.dumps(value)
                doc[key] = value
            except (TypeError, ValueError):
                doc[key] = repr(value)
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=repr)


class StructuredAdapter(logging.LoggerAdapter):
    """kwargs become JSON fields: log.info("msg", nodepool="x", pods=3)."""

    def _log_kw(self, level: int, msg: str, fields: Dict[str, Any]) -> None:
        if self.logger.isEnabledFor(level):
            # LogRecord refuses extras that shadow its own attributes
            # (KeyError at the call site); prefix collisions instead
            safe = {
                (f"field_{k}" if k in _RESERVED else k): v for k, v in fields.items()
            }
            self.logger.log(level, msg, extra=safe)

    def debug(self, msg: str, **fields):
        self._log_kw(logging.DEBUG, msg, fields)

    def info(self, msg: str, **fields):
        self._log_kw(logging.INFO, msg, fields)

    def warning(self, msg: str, **fields):
        self._log_kw(logging.WARNING, msg, fields)

    def error(self, msg: str, **fields):
        self._log_kw(logging.ERROR, msg, fields)


_configured = False
_config_lock = threading.Lock()


def configure(stream=None, level: int = logging.INFO) -> None:
    """Install the JSON handler on the root framework logger (idempotent;
    re-running replaces the handler -- tests use this to capture output)."""
    global _configured
    with _config_lock:
        root = logging.getLogger(ROOT)
        for h in list(root.handlers):
            root.removeHandler(h)
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(JSONFormatter())
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True


def get_logger(name: str) -> StructuredAdapter:
    if not _configured:
        configure()
    return StructuredAdapter(logging.getLogger(f"{ROOT}.{name}"), {})


class ChangeMonitor:
    """Log-suppression by value change (reference: operatorpkg ChangeMonitor
    used throughout the providers): has_changed(key, value) is True only
    when `value` differs from the last one seen for `key`, or the entry
    is older than the TTL (so long-lived steady state still re-logs
    occasionally, as the reference's 24h default does)."""

    def __init__(self, ttl_seconds: float = 24 * 3600.0, clock=None):
        self.ttl = ttl_seconds
        self._clock = clock  # injectable for tests; None = wall time
        self._last: Dict[Any, tuple] = {}
        self._lock = threading.Lock()

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.time()

    def has_changed(self, key: Any, value: Any) -> bool:
        now = self._now()
        with self._lock:
            prev = self._last.get(key)
            if prev is not None and prev[0] == value and now - prev[1] < self.ttl:
                return False
            self._last[key] = (value, now)
            return True
