"""Cross-tenant dispatch coalescer: N clusters, one solver process.

The rpc sidecar already isolates tenants at the STAGING layer -- catalogs
stage under per-connection seqnums, class epochs under client-unique ids.
What it lacked was a dispatch policy: N operator replicas solving
concurrently each grabbed a handler thread and raced into the device,
so one storming cluster could queue everyone behind its solves and one
erroring cluster could burn every handler's retry budget.

This coalescer is that policy. Concurrent submissions batch into shared
dispatch WINDOWS drained by one dispatcher thread:

- **deterministic tenant ordering** -- a window's submissions dispatch
  sorted by (tenant id, per-tenant arrival seq), so device occupancy per
  window is a pure function of what was queued, never of thread timing;
  each tenant's solve is a pure function of its own tensors, which is
  why ``multi-tenant == isolated`` holds bit-exactly (differential sim
  replay, sim/fleet.py);
- **per-tenant deadline budgets** -- each tenant gets a wall budget per
  solve (`budget_s`); a submission still queued past its deadline is
  refused with a typed `TenantRefusal` instead of dispatched late. The
  refusal crosses the wire as an error reply, which the client's solve
  ladder surfaces as RuntimeError -- the same rung the existing overload
  ladder (breaker accounting + in-process host fallback,
  ``TPUSolver._finish_remote``) already terminates;
- **per-tenant breaker/degrade** -- `breaker_threshold` consecutive
  dispatch failures open that tenant's breaker for `breaker_cooldown_s`;
  its submissions then refuse FAST (no queue slot, no device time) while
  every other tenant dispatches normally. One sick cluster never poisons
  another: a tenant's failure is recorded on ITS submission and its
  breaker only (tests/test_tenant.py drills a mid-coalesce sidecar kill
  and a one-tenant corrupt frame).

The dispatcher swallows NOTHING silently: every per-submission exception
is captured into that submission's outcome and re-raised in the
submitting thread (the LADDER_SEAMS entry for `_run_one` pins the
contract; `OperatorCrashed` is a BaseException and still propagates).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

from karpenter_tpu import failpoints, metrics

# one dispatch window's coalescing wait: long enough that replicas whose
# ticks align land in one batch, short enough to be invisible against a
# multi-ms solve
DEFAULT_WINDOW_S = 0.0005
DEFAULT_BREAKER_THRESHOLD = 4
DEFAULT_BREAKER_COOLDOWN_S = 5.0


class TenantRefusal(RuntimeError):
    """A typed per-tenant refusal (deadline blown while queued, or the
    tenant's breaker is open). Crosses the wire as an error reply; the
    client's solve ladder raises it as RuntimeError into the caller's
    existing degrade rungs (breaker + in-process host fallback)."""

    def __init__(self, tenant: str, reason: str):
        super().__init__(f"tenant {tenant or '<default>'} refused: {reason}")
        self.tenant = tenant
        self.reason = reason


class _TenantState:
    __slots__ = ("tenant", "failures", "open_until", "seq")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.failures = 0
        self.open_until = 0.0
        self.seq = itertools.count()


class _Submission:
    __slots__ = ("tenant", "seq", "fn", "deadline", "done", "result", "error")

    def __init__(self, tenant: str, seq: int, fn: Callable, deadline: Optional[float]):
        self.tenant = tenant
        self.seq = seq
        self.fn = fn
        self.deadline = deadline
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class DispatchCoalescer:
    """Batch concurrent per-tenant solve closures into shared dispatch
    windows on one dispatcher thread. See the module docstring for the
    policy; `submit` is the only entry point handler threads use."""

    def __init__(
        self, *,
        window_s: float = DEFAULT_WINDOW_S,
        budget_s: float = 0.0,
        breaker_threshold: int = DEFAULT_BREAKER_THRESHOLD,
        breaker_cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = float(window_s)
        # 0 = unbounded (deterministic tests and the default sidecar; the
        # fleet deployment sizes it from the tick deadline, docs/operations.md)
        self.budget_s = float(budget_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._clock = clock
        self._cv = threading.Condition()
        self._queue: List[_Submission] = []
        self._states: Dict[str, _TenantState] = {}
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # observability for the last drained window (bench's fleet stage)
        self.last_window = {"batch": 0, "tenants": 0}

    # -- tenant state ---------------------------------------------------------
    def _state(self, tenant: str) -> _TenantState:
        st = self._states.get(tenant)
        if st is None:
            st = self._states[tenant] = _TenantState(tenant)
        return st

    def tenant_open(self, tenant: str) -> bool:
        """True while the tenant's breaker is open (its submissions refuse
        fast). Reads under the condition lock for a consistent snapshot."""
        with self._cv:
            return self._state(tenant).open_until > self._clock()

    def describe(self) -> dict:
        with self._cv:
            now = self._clock()
            return {
                "queued": len(self._queue),
                "tenants": {
                    t: {
                        "failures": st.failures,
                        "breaker_open": st.open_until > now,
                    }
                    for t, st in sorted(self._states.items())
                },
                "last_window": dict(self.last_window),
            }

    # -- submission -----------------------------------------------------------
    def submit(self, tenant: str, fn: Callable, *, budget_s: Optional[float] = None):
        """Run `fn` inside a coalesced dispatch window; blocks until its
        window drains and returns fn's result (or re-raises its error in
        THIS thread). Raises TenantRefusal without queueing when the
        tenant's breaker is open."""
        tenant = str(tenant or "")
        budget = self.budget_s if budget_s is None else float(budget_s)
        with self._cv:
            if self._closed:
                raise TenantRefusal(tenant, "coalescer closed")
            st = self._state(tenant)
            now = self._clock()
            if st.open_until:
                if st.open_until > now:
                    metrics.TENANT_REFUSALS.inc(tenant=tenant, reason="breaker-open")
                    raise TenantRefusal(tenant, "breaker open")
                # cooldown elapsed: the breaker is CLOSED again -- clear the
                # state and its gauge here, not only on the next success, so
                # an idle (or still-flaky) tenant never reads as open while
                # its solves actually dispatch
                st.open_until = 0.0
                metrics.TENANT_BREAKER_STATE.set(0.0, tenant=tenant)
            sub = _Submission(
                tenant, next(st.seq), fn,
                (now + budget) if budget > 0 else None,
            )
            self._queue.append(sub)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="fleet-coalescer",
                )
                self._thread.start()
            self._cv.notify_all()
        sub.done.wait()
        if sub.error is not None:
            raise sub.error
        return sub.result

    def close(self) -> None:
        """Stop accepting work and fail anything still queued (the
        sidecar's stop path): queued submitters must unblock, not hang
        on a window that will never drain."""
        with self._cv:
            self._closed = True
            queued, self._queue = self._queue, []
            self._cv.notify_all()
        for sub in queued:
            sub.error = TenantRefusal(sub.tenant, "coalescer closed")
            sub.done.set()

    # -- the dispatcher -------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
            # coalescing wait OUTSIDE the lock: submissions arriving in
            # this window join the batch
            if self.window_s > 0:
                time.sleep(self.window_s)
            with self._cv:
                batch, self._queue = self._queue, []
            if not batch:
                continue
            # deterministic tenant ordering: device occupancy per window
            # is a pure function of the queued set
            batch.sort(key=lambda s: (s.tenant, s.seq))
            self.last_window = {
                "batch": len(batch),
                "tenants": len({s.tenant for s in batch}),
            }
            metrics.TENANT_WINDOW_SIZE.observe(float(len(batch)))
            for i, sub in enumerate(batch):
                try:
                    self._run_one(sub)
                except BaseException as e:  # noqa: BLE001 -- sanctioned crash terminal
                    # SANCTIONED_CRASH_SWALLOWS site (checkers/errflow.py):
                    # a crash (OperatorCrashed and kin) TERMINATES the
                    # dispatcher here -- the sidecar's dispatcher has no
                    # run-loop driver above it to propagate to, and an
                    # unhandled daemon-thread death would silently wedge
                    # every queued and future submission instead. The
                    # propagation contract is behavioral: every remaining
                    # batch member fails with a typed refusal (its handler
                    # replies and that client degrades to its host
                    # fallback), the coalescer CLOSES so future submits
                    # refuse fast, the crash is logged + counted, and the
                    # thread exits.
                    from karpenter_tpu.logging import get_logger

                    for rest in batch[i + 1:]:
                        rest.error = TenantRefusal(
                            rest.tenant, "dispatcher crashed mid-window"
                        )
                        rest.done.set()
                    self.close()
                    metrics.HANDLED_ERRORS.inc(site="fleet.coalesce.dispatcher")
                    get_logger("fleet").error(
                        "tenant dispatcher crashed; coalescer closed "
                        "(tenants degrade to their host-fallback rungs)",
                        error=f"{type(e).__name__}: {e}"[:200],
                    )
                    return

    def _run_one(self, sub: _Submission) -> None:
        """One submission's dispatch, fault-isolated per tenant: every
        Exception becomes THIS submission's outcome (re-raised in its
        submitting thread) and this tenant's breaker accounting -- never
        an escape that kills the dispatcher or poisons the rest of the
        window. OperatorCrashed (BaseException) still propagates: a
        supervised crash must reach the run loop."""
        t0 = self._clock()
        try:
            # the tenant-dispatch chaos seam (LADDER_SEAMS): drills inject
            # dispatch-time faults here -- a mid-coalesce sidecar kill, a
            # wedged device -- and the soak asserts no cross-tenant drift
            failpoints.eval("fleet.dispatch")
            if sub.deadline is not None and self._clock() > sub.deadline:
                metrics.TENANT_REFUSALS.inc(tenant=sub.tenant, reason="deadline")
                raise TenantRefusal(sub.tenant, "deadline blown while queued")
            sub.result = sub.fn()
        except TenantRefusal as e:
            # deadline shedding is LOAD policy, not dispatch evidence: a
            # refusal caused by a congested neighbor must not trip the
            # victim's breaker (that would be exactly the cross-tenant
            # poisoning the breaker exists to prevent). The refusals
            # counter above already recorded it.
            sub.error = e
        except Exception as e:  # noqa: BLE001 -- captured into the outcome
            sub.error = e
            metrics.TENANT_DISPATCHES.inc(tenant=sub.tenant, outcome="error")
            self._record_failure(sub.tenant)
        except BaseException as e:
            # OperatorCrashed: the submitter gets a CONVERTED typed
            # refusal (its handler replies an error frame; its client
            # degrades to the host rung) while the original propagates to
            # _loop's sanctioned crash terminal, which closes the
            # coalescer
            sub.error = TenantRefusal(
                sub.tenant, f"dispatcher crashed: {type(e).__name__}"
            )
            metrics.TENANT_DISPATCHES.inc(tenant=sub.tenant, outcome="error")
            raise
        else:
            metrics.TENANT_DISPATCHES.inc(tenant=sub.tenant, outcome="ok")
            self._record_success(sub.tenant)
        finally:
            metrics.TENANT_DISPATCH_SECONDS.observe(
                max(self._clock() - t0, 0.0), tenant=sub.tenant
            )
            sub.done.set()

    def _record_failure(self, tenant: str) -> None:
        with self._cv:
            st = self._state(tenant)
            st.failures += 1
            if st.failures >= self.breaker_threshold:
                st.open_until = self._clock() + self.breaker_cooldown_s
                st.failures = 0
                metrics.TENANT_BREAKER_STATE.set(1.0, tenant=tenant)
                metrics.TENANT_BREAKER_TRIPS.inc(tenant=tenant)

    def _record_success(self, tenant: str) -> None:
        with self._cv:
            st = self._state(tenant)
            st.failures = 0
            if st.open_until:
                st.open_until = 0.0
            metrics.TENANT_BREAKER_STATE.set(0.0, tenant=tenant)
