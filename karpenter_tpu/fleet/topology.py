"""Topology epochs: the mesh's membership ledger and degrade ladder.

Every other seam in the stack has a typed failure ladder -- wire,
crash, overload, error paths -- but the mesh fleet path assumed the
device mesh was immortal. This module is the missing ledger: a
monotonic **topology epoch** that names one healthy-device set + mesh
layout, bumped on ANY membership change (device lost, quarantined by
the shard-straggler watchdog, or returned). Staged shards are stamped
with the epoch they were staged under; a solve dispatched against a
stale epoch surfaces as a typed ``StaleTopologyError`` (a
``StaleSeqnumError`` subclass, so every existing recovery rung --
synchronous restage-retry, pipelined barrier fallback, breaker, delta
epochs -- handles a topology change exactly like any other staging
gap).

The degrade ladder, every rung bit-identical on decisions (GSPMD only
changes placement, never semantics; the unsharded rung IS the proven
single-device entry set):

    full mesh -> shrunk mesh -> unsharded single-device
              -> wire breaker -> host CPU

``current_mesh`` computes the shrunk layout DETERMINISTICALLY from the
healthy set: a 2D ``(hosts, types)`` mesh collapses whole rows first
(a host with any lost chip leaves as a unit -- the DCN fabric's
failure domain), falling back to a flat mesh over the largest
power-of-two prefix of the surviving devices (pow2 counts are the only
ones every padded solver axis divides by), then to ``None`` (the
unsharded rung) when fewer than two remain. Shrunk ``Mesh`` objects are memoized per healthy-set
so a stable topology reuses jitted programs (``Mesh`` hashes by
devices+axes), and re-promotion to the full mesh returns the ORIGINAL
mesh object -- the warm jit cache from before the loss.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from karpenter_tpu import metrics
from karpenter_tpu.parallel import mesh as mesh_mod

# substrings (lowercased) that classify a RuntimeError out of a mesh
# dispatch as a DEVICE LOSS rather than a program bug: the XLA runtime's
# device-failure surfaces, plus the repo's own injected fault (the
# `mesh.device.lost` failpoint raises RuntimeError with the site name in
# the message -- the chaos soak exercises exactly this classifier).
# Anything else re-raises unchanged: misclassifying a compile error as a
# dead chip would shrink the mesh forever on every dispatch.
_DEVICE_LOSS_PATTERNS = (
    "mesh.device.lost",
    "device lost",
    "device failure",
    "device unavailable",
    "device halted",
    "chip halted",
    "data_loss",
    "hardware_error",
    "device or resource busy",
)

_DEVICE_INDEX_RE = re.compile(r"device[ #:]*(\d+)")


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (0 for n < 1): the legal shrunk-mesh
    device counts -- see _build_mesh_locked."""
    if n < 1:
        return 0
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def classify_device_error(exc: BaseException) -> Optional[str]:
    """The reason string when `exc` looks like a lost device, else None.

    Pattern-matched on the message because the XLA runtime surfaces
    device death as bare ``RuntimeError``/``XlaRuntimeError`` text --
    there is no typed exception to catch at this layer."""
    msg = str(exc).lower()
    for pat in _DEVICE_LOSS_PATTERNS:
        if pat in msg:
            return pat
    return None


def device_index_hint(exc: BaseException) -> Optional[int]:
    """A device index named in the error message, if any (the XLA
    runtime often includes one; the failpoint message does not)."""
    m = _DEVICE_INDEX_RE.search(str(exc).lower())
    return int(m.group(1)) if m else None


class TopologyTracker:
    """The healthy-device ledger behind one mesh engine.

    Thread-safe; the epoch is monotonic and bumps on every membership
    change in either direction, so ``epoch`` equality IS topology
    equality -- a solve staged at epoch N and dispatched at epoch M>N
    is provably against a different device set.
    """

    def __init__(self, devices: Tuple, shape: Tuple[int, ...],
                 axis_names: Tuple[str, ...], full_mesh: Optional[Mesh] = None):
        self._devices = tuple(devices)          # flat, host-major
        self._shape = tuple(shape)
        self._axis_names = tuple(axis_names)
        # the original mesh object: re-promotion hands this exact object
        # back so the module jit cache (keyed on the Mesh) stays warm
        self._full_mesh = full_mesh
        self._epoch = 1
        self._lost: Dict[int, str] = {}         # flat index -> reason
        self._mesh_cache: Dict[tuple, Mesh] = {}
        self._lock = threading.Lock()
        self._observe_locked()

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "TopologyTracker":
        return cls(
            tuple(mesh.devices.flat), tuple(mesh.devices.shape),
            tuple(mesh.axis_names), full_mesh=mesh,
        )

    # -- membership ----------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def size(self) -> int:
        return len(self._devices)

    def healthy_indices(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(
                i for i in range(len(self._devices)) if i not in self._lost
            )

    def quarantined(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._lost)

    def healthy_labels(self) -> frozenset:
        """The HBM-ledger labels (``platform:id``, obs/hbm.py) of the
        healthy devices -- tenant sizing filters the polled ledger to
        these so a quarantined chip's stale headroom never sizes
        capacity."""
        with self._lock:
            return frozenset(
                f"{d.platform}:{d.id}"
                for i, d in enumerate(self._devices) if i not in self._lost
            )

    def mark_lost(self, index: int, reason: str) -> bool:
        """Record device `index` as lost; bump the epoch iff this is a
        real membership change. Returns True on a bump."""
        index = int(index) % max(len(self._devices), 1)
        with self._lock:
            if index in self._lost:
                return False
            self._lost[index] = str(reason)
            self._epoch += 1
            self._observe_locked()
            metrics.MESH_TOPOLOGY_TRANSITIONS.inc(kind="device-lost")
            return True

    def mark_returned(self, index: int) -> bool:
        """Record device `index` as healthy again (the probe saw it come
        back, or the operator cleared a quarantine); bump the epoch iff
        it was actually out."""
        index = int(index) % max(len(self._devices), 1)
        with self._lock:
            if index not in self._lost:
                return False
            del self._lost[index]
            self._epoch += 1
            self._observe_locked()
            metrics.MESH_TOPOLOGY_TRANSITIONS.inc(kind="device-returned")
            return True

    def _observe_locked(self) -> None:
        metrics.MESH_TOPOLOGY_EPOCH.set(float(self._epoch))
        metrics.MESH_TOPOLOGY_HEALTHY.set(
            float(len(self._devices) - len(self._lost)))
        metrics.MESH_TOPOLOGY_QUARANTINED.set(float(len(self._lost)))

    # -- layout --------------------------------------------------------------
    def current_mesh(self) -> Optional[Mesh]:
        """The deterministic mesh for the CURRENT healthy set, or None
        for the unsharded single-device rung.

        All healthy -> the original full mesh object (warm jit cache).
        2D layouts collapse rows first: any row containing a lost
        device leaves whole (hosts are the DCN failure domain), and the
        largest power-of-two prefix of the surviving full rows keeps
        the 2D layout when >= 2 remain. Otherwise a flat mesh over the
        largest power-of-two prefix of the healthy devices, when >= 2
        remain; below that, sharding buys nothing -- descend to the
        unsharded rung. Power-of-two counts only: the padded axes the
        shardings split guarantee even division for them and nothing
        else (_build_mesh_locked)."""
        with self._lock:
            if not self._lost:
                return self._full_mesh
            healthy = tuple(
                i for i in range(len(self._devices)) if i not in self._lost
            )
            key = (self._shape, healthy)
            cached = self._mesh_cache.get(key)
            if cached is not None:
                return cached
            mesh = self._build_mesh_locked(healthy)
            if mesh is not None:
                self._mesh_cache[key] = mesh
            return mesh

    def _build_mesh_locked(self, healthy: Tuple[int, ...]) -> Optional[Mesh]:
        """Shrunk layouts use POWER-OF-TWO device counts only: every
        padded axis the shardings split (k_pad multiple of 128, c_pad
        multiple of 16, the disrupt pools' pow2 buckets) divides evenly
        by any power of two, while e.g. 7 survivors of 8 would fail
        GSPMD's even-split check at stage time. So 8 -> 4 -> 2 ->
        unsharded, always taking the LOWEST-indexed healthy devices
        (and earliest full rows) -- deterministic across processes."""
        if len(self._shape) == 2:
            n_hosts, per_host = self._shape
            full_rows = [
                r for r in range(n_hosts)
                if all(r * per_host + c in healthy for c in range(per_host))
            ]
            n_rows = _pow2_floor(len(full_rows))
            if n_rows >= 2:
                grid = np.array(
                    [
                        [self._devices[r * per_host + c] for c in range(per_host)]
                        for r in full_rows[:n_rows]
                    ]
                )
                return Mesh(grid, axis_names=self._axis_names)
        n_flat = _pow2_floor(len(healthy))
        if n_flat >= 2:
            return Mesh(
                np.array([self._devices[i] for i in healthy[:n_flat]]),
                axis_names=(mesh_mod.TYPES_AXIS,),
            )
        return None

    def shrunk_meshes(self) -> Tuple[Mesh, ...]:
        """Every DETERMINISTIC shrunk layout the degrade ladder can build
        (largest first), independent of which devices are currently
        healthy: pow2 row prefixes for a 2D mesh, then pow2 flat device
        prefixes down to 2. These are exactly the meshes
        _build_mesh_locked produces when the HIGHEST-indexed devices go
        (the quarantine rung removes highest-index first), so the AOT
        warmup ladder (solver/aot.py) can precompile their sharded
        programs BEFORE any device is lost -- a reshard then lands on a
        warm module jit cache (Mesh equality is by devices+axis names)."""
        with self._lock:
            devices, shape, names = self._devices, self._shape, self._axis_names
        out = []
        if len(shape) == 2:
            n_hosts, per_host = shape
            n_rows = _pow2_floor(n_hosts - 1) if n_hosts > 1 else 0
            while n_rows >= 2:
                grid = np.array(
                    [
                        [devices[r * per_host + c] for c in range(per_host)]
                        for r in range(n_rows)
                    ]
                )
                out.append(Mesh(grid, axis_names=names))
                n_rows //= 2
        n_flat = _pow2_floor(len(devices) - 1) if len(devices) > 1 else 0
        while n_flat >= 2:
            out.append(
                Mesh(
                    np.array(devices[:n_flat]),
                    axis_names=(mesh_mod.TYPES_AXIS,),
                )
            )
            n_flat //= 2
        return tuple(out)

    def mode(self) -> str:
        """Which ladder rung the current layout is: "full" | "shrunk" |
        "unsharded"."""
        with self._lock:
            if not self._lost:
                return "full"
        return "shrunk" if self.current_mesh() is not None else "unsharded"

    def describe(self) -> dict:
        with self._lock:
            lost = dict(self._lost)
            return {
                "epoch": self._epoch,
                "devices": len(self._devices),
                "healthy": len(self._devices) - len(lost),
                "quarantined": {str(k): v for k, v in sorted(lost.items())},
                "shape": list(self._shape),
            }
