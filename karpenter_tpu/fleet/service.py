"""Fleet service glue: the multi-cluster solver sidecar, assembled.

One solver process serves N operator replicas ("tenants" -- one per
cluster): the rpc server stages each tenant's catalogs/epochs under its
own ids, the DispatchCoalescer batches their concurrent solves into
shared device dispatch windows, and (when a mesh is configured) every
dispatch runs the mesh-sharded jit entries. Tenant sizing reads the
live HBM ledger when one exists (tenant_staged_bytes: the resident
packed-mask staging, not the round-16 full-width extrapolation). This
module is the small
assembly layer over `SolverServer(mesh=, coalescer=)` -- the same shape
the binary exposes as `python -m karpenter_tpu.solver.rpc --coalesce
--mesh ... --tenant-budget ...` -- shared by the sim fleet replay
(sim/fleet.py) and ad-hoc embedders.

Sizing (docs/operations.md "Multi-tenant runbook"): each tenant's staged
state is bounded by the server's LRUs (4 catalogs + 4 class epochs + 4
disrupt epochs per process-wide store, pressure-evicted below the HBM
headroom threshold), so tenant count is sized from measured headroom --
`max_tenants_for_headroom` is that arithmetic, fed by the round-16 HBM
ledger (obs/hbm.py).
"""
from __future__ import annotations

from typing import Optional

from karpenter_tpu.fleet.coalesce import DispatchCoalescer
from karpenter_tpu.fleet.shard import MeshSolveEngine, mesh_from_env
from karpenter_tpu.logging import get_logger
from karpenter_tpu.obs import hbm as obs_hbm

# fallback per-tenant footprint when no live ledger is available: the
# round-20 packed-mask staging profile (BENCH json staged_bytes_by_kind:
# catalog ~1.6 MB + class epoch ~0.4 MB with the open/join masks
# bit-packed at 8x below the round-16 bool rows + headroom for one
# in-flight solve's temporaries); deliberately rounded UP -- sizing must
# err toward fewer tenants
TENANT_STAGED_BYTES_FALLBACK = 6 * 1024 * 1024

# in-flight multiplier over the ledger's resident bytes: a tenant's
# steady-state staging plus one dispatch's transient copies (the staged
# epoch being replaced lingers until the LRU drops it)
_LIVE_SIZING_HEADROOM = 2


def tenant_staged_bytes(solver=None) -> int:
    """Per-tenant resident staging footprint for sizing. With a live
    solver, reads the HBM ledger (staged_bytes_by_kind: catalog +
    class_masks + solve_temporaries -- the PACKED mask bytes, i.e. what
    is actually resident, not the full-width equivalent) and doubles it
    for in-flight headroom; an empty ledger or no solver falls back to
    the round-20 static profile. Never returns below the fallback --
    a one-tenant measurement must not oversell capacity."""
    if solver is not None:
        try:
            kinds = solver.staged_bytes_by_kind()
        except Exception as e:  # noqa: BLE001 - sizing must never raise
            get_logger("fleet").warning(
                "tenant sizing: ledger read failed; using static fallback",
                error=f"{type(e).__name__}: {e}"[:200],
            )
            kinds = {}
        live = (
            int(kinds.get("catalog", 0))
            + int(kinds.get("class_masks", 0))
            + int(kinds.get("solve_temporaries", 0))
        )
        if live > 0:
            return max(_LIVE_SIZING_HEADROOM * live, TENANT_STAGED_BYTES_FALLBACK)
    return TENANT_STAGED_BYTES_FALLBACK


def max_tenants_for_headroom(
    headroom_bytes: Optional[int] = None,
    per_tenant_bytes: Optional[int] = None,
    reserve_fraction: float = 0.5,
    solver=None,
    engine=None,
) -> Optional[int]:
    """How many tenants the measured device headroom supports, keeping
    `reserve_fraction` of it free for solve temporaries and compile
    workspace. Per-tenant bytes come from the live HBM ledger when a
    `solver` is passed (tenant_staged_bytes), else the static fallback;
    an explicit `per_tenant_bytes` overrides both. None when no
    allocator ledger exists (CPU backend) -- capacity is then bounded by
    the LRUs alone, and the operator sizes from the runbook's table
    instead.

    TOPOLOGY-AWARE when `engine` (the MeshSolveEngine) is passed: sizing
    reads the engine's topology AT CALL TIME, so every call after an
    epoch bump recomputes against the surviving device set -- the
    pre-topology arithmetic froze the device count at sidecar start, and
    a shrunk mesh silently oversubscribed HBM headroom two ways: the
    quarantined chip's stale ledger entry still fed the min-headroom,
    and the K-sharded staging that concentrates onto fewer survivors
    still sized at the full-mesh per-device footprint."""
    if per_tenant_bytes is None:
        per_tenant_bytes = tenant_staged_bytes(solver)
        if engine is not None and getattr(engine, "topology", None) is not None:
            # shrunk mesh: the K-sharded catalog and packed masks
            # concentrate onto the survivors, so each healthy device
            # holds full/healthy times the per-device staging the
            # measurement (or fallback profile) was taken at
            topo = engine.topology
            healthy = len(topo.healthy_indices())
            if 0 < healthy < topo.size:
                per_tenant_bytes = int(per_tenant_bytes * topo.size / healthy)
    if headroom_bytes is None:
        devices = obs_hbm.poll().get("devices") or {}
        if engine is not None and getattr(engine, "topology", None) is not None:
            # a quarantined chip's ledger entry is stale (or the device
            # is gone outright): only healthy devices' headroom counts.
            # An empty intersection (label scheme drift, fake provider)
            # falls back to the unfiltered set -- sizing must degrade,
            # not vanish.
            labels = engine.topology.healthy_labels()
            filtered = {k: v for k, v in devices.items() if k in labels}
            devices = filtered or devices
        free = [
            int(d["bytes_limit"]) - int(d["bytes_in_use"])
            for d in devices.values()
            if int(d.get("bytes_limit", 0)) > 0
        ]
        if not free:
            return None
        headroom_bytes = min(free)
    usable = int(headroom_bytes * (1.0 - reserve_fraction))
    return max(usable // int(per_tenant_bytes), 0)


def build_fleet_server(
    *, path: Optional[str] = None, host: str = "127.0.0.1", port: int = 0,
    token: Optional[str] = None, insecure_tcp: bool = False,
    mesh=None, coalesce: bool = True,
    tenant_budget_s: float = 0.0, window_s: Optional[float] = None,
    **server_kw,
):
    """A started SolverServer wired for the fleet topology: the dispatch
    coalescer on (deterministic tenant ordering, per-tenant breaker and
    deadline budget) and, when `mesh` (or $KARPENTER_TPU_MESH) names a
    layout, the mesh-sharded solve engine. `mesh=None` consults the
    environment; any other falsy value (False, 0, "") pins the
    single-device path regardless of it -- deterministic gates must not
    take hidden configuration. Returns the running server; callers own
    stop()."""
    from karpenter_tpu.solver.rpc import SolverServer

    if mesh is None:
        mesh = mesh_from_env()
    engine = None
    if mesh:
        engine = mesh if isinstance(mesh, MeshSolveEngine) else MeshSolveEngine(mesh)
    coalescer = None
    if coalesce:
        kw = {"budget_s": tenant_budget_s}
        if window_s is not None:
            kw["window_s"] = window_s
        coalescer = DispatchCoalescer(**kw)
    server = SolverServer(
        host, port, path=path, token=token, insecure_tcp=insecure_tcp,
        mesh=engine, coalescer=coalescer, **server_kw,
    )
    return server.start()
