"""Mesh-sharded production solve: the multichip dry-run, promoted.

``parallel/mesh.py`` proved the sharded lowerings bit-identical on an
8-device mesh (MULTICHIP_r05) but nothing dispatched them on the real
tick. This engine is that promotion:

- the catalog axis K stays sharded over the mesh's ``types`` axis (the
  proven layout: per-scan-step fit max-reduces lower to ICI all-reduces);
- on a 2D ``(hosts, types)`` mesh the ``[C, K]`` pod-class masks
  additionally shard their CLASS axis over the ``hosts`` axis, so the
  compat precompute spreads over both fabrics;
- disrupt candidate pools (the ``[S, ...]`` repack/replace tensors)
  shard their set axis over EVERY mesh axis -- no in-solve communication,
  so DCN crossing costs nothing;
- every entry is jitted with REPLICATED ``out_shardings``: the per-shard
  winners all-gather INSIDE the jitted computation, so the fetch is a
  local read on every process -- one designed host barrier per tick
  (``fetch``), exactly like the single-device path.

Jitted wrappers cache per (mesh, entry, statics) -- the same discipline
as ``parallel/mesh.py``; the module is listed in ``DYNAMIC_JIT_MODULES``
so the jax witness polls these caches for retrace attribution.

The pipelined contract holds unchanged: ``solve_fused`` is an ASYNC
dispatch (the caller's ``copy_to_host_async`` + late ``np.asarray``
barrier work exactly as on one device), and the delta-epoch staging in
``solver/rpc.py`` is untouched -- epochs are host-side state patched
before dispatch, so per-shard epochs compose by construction and
pressure eviction/restage stays a non-error (tests/test_fleet.py drills
both).
"""
from __future__ import annotations

import functools
import os
import threading
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu import failpoints, metrics
from karpenter_tpu.fleet import topology as topo_mod
from karpenter_tpu.parallel import mesh as mesh_mod
from karpenter_tpu.solver import ffd, packing

# mesh layout for the production solve: "8" -> flat 8-device mesh,
# "2x4" -> (hosts, types); unset/empty/"0"/"1" -> single-device path
MESH_ENV = "KARPENTER_TPU_MESH"


def parse_mesh_spec(spec: Optional[str]) -> Optional[Mesh]:
    """A Mesh from an operator-facing layout spec, or None for the
    single-device path. "NxM" builds the (hosts, types) 2D layout;
    a bare count builds the flat catalog-parallel mesh. A spec asking
    for more devices than exist is a configuration error and raises --
    silently shrinking the mesh would change which programs compile
    without changing the operator's mental model."""
    if not spec:
        return None
    spec = spec.strip().lower()
    if not spec or spec in ("0", "1", "off", "none"):
        return None
    if "x" in spec:
        hosts_s, types_s = spec.split("x", 1)
        n_hosts, per_host = int(hosts_s), int(types_s)
        if n_hosts * per_host > len(jax.devices()):
            raise ValueError(
                f"mesh spec {spec!r} needs {n_hosts * per_host} devices; "
                f"{len(jax.devices())} available"
            )
        return mesh_mod.make_mesh_2d(n_hosts, per_host)
    n = int(spec)
    if n > len(jax.devices()):
        raise ValueError(
            f"mesh spec {spec!r} needs {n} devices; {len(jax.devices())} available"
        )
    return mesh_mod.make_mesh(n)


def mesh_from_env() -> Optional[Mesh]:
    return parse_mesh_spec(os.environ.get(MESH_ENV))


# jitted sharded wrappers keyed by (mesh, kind, statics) -- MODULE level
# so (a) two engines over one mesh share compiled programs and (b) the
# jax witness (DYNAMIC_JIT_MODULES in checkers/jax_discipline.py) polls
# these wrappers' compilation caches for per-entry retrace attribution,
# exactly like parallel/mesh.py's cache
_JIT_CACHE: Dict[tuple, object] = {}
_JIT_LOCK = threading.Lock()


class MeshSolveEngine:
    """Sharded dispatch for every production solve entry.

    One engine per mesh; TPUSolver (in-process) and SolverServer (the
    sidecar) both hold one and route their jitted dispatches through it.
    Decisions are bit-identical to the single-device entries (GSPMD only
    changes placement, never semantics) -- differential-asserted in
    tests/test_fleet.py and by the ``mesh`` sim backend's digests."""

    def __init__(self, mesh):
        if isinstance(mesh, int):
            mesh = mesh_mod.make_mesh(mesh)
        elif isinstance(mesh, str):
            parsed = parse_mesh_spec(mesh)
            if parsed is None:
                raise ValueError(f"mesh spec {mesh!r} parses to no mesh")
            mesh = parsed
        # the membership ledger: every dispatch syncs against it, every
        # staged catalog is stamped with the epoch it was staged under
        self.topology = topo_mod.TopologyTracker.from_mesh(mesh)
        # reshard is a swap of the engine's sharding tables: one writer
        # at a time, re-entrant because stage_catalog holds it across
        # _sync_topology
        self._topo_lock = threading.RLock()
        self._watchdog = None      # ShardStragglerWatchdog, attached by the owner
        self._apply_mesh(mesh)
        self._applied_epoch = self.topology.epoch

    def _apply_mesh(self, mesh: Optional[Mesh]) -> None:
        """Point every sharding table at `mesh`; ``None`` is the
        UNSHARDED rung of the degrade ladder -- dispatches fall through
        to the proven single-device jitted entries (bit-identical by
        the same differential that gates the sharded ones)."""
        self.mesh = mesh
        if mesh is None:
            self._rep = None
            self._in_shardings = None
            self._in_shardings_packed = None
            self._s_shard = None
            self._cat_k = None
            self._multiproc = False
            metrics.MESH_DEVICES.set(1.0)
            return
        self._rep = NamedSharding(mesh, P())
        shardings = mesh_mod.catalog_sharding(mesh)
        if len(mesh.axis_names) > 1:
            # 2D (hosts, types): the [C, K] class masks shard their class
            # axis over the host axis too -- pod classes spread across the
            # mesh while the scan's K-reduces stay on ICI. c_pad is always
            # a multiple of 16 (encode.bucket), so the row split is even
            # for any realistic host count.
            ck = P(mesh.axis_names[:-1], mesh_mod.TYPES_AXIS)
            shardings = shardings._replace(
                open_allowed=NamedSharding(mesh, ck),
                join_allowed=NamedSharding(mesh, ck),
            )
        self._in_shardings = shardings
        # bit-packed [C, KW] masks (solver/packing.py): KW = k_pad/32
        # need not divide the types axis, and the words are 8x smaller
        # than the bool rows they replace -- so the packed form drops
        # the K split: class rows shard over the HOSTS axis on a 2D mesh
        # (the DCN fabric the per-tick rows cross anyway), replicated on
        # a flat mesh. Selected per dispatch by mask dtype, same two-
        # bounded-programs discipline as the kernels.
        row = (
            P(mesh.axis_names[:-1], None)
            if len(mesh.axis_names) > 1 else P()
        )
        self._in_shardings_packed = shardings._replace(
            open_allowed=NamedSharding(mesh, row),
            join_allowed=NamedSharding(mesh, row),
        )
        # candidate-pool axis: data-parallel over every mesh axis
        self._s_shard = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        self._cat_k = NamedSharding(mesh, P(mesh_mod.TYPES_AXIS))
        self._multiproc = mesh_mod._is_multiprocess(mesh)
        metrics.MESH_DEVICES.set(float(self.mesh.devices.size))

    # -- topology -------------------------------------------------------------
    @property
    def epoch(self) -> int:
        """The topology epoch staged catalogs are stamped with."""
        return self.topology.epoch

    def attach_watchdog(self, watchdog) -> None:
        """Bracket every dispatch with the shard-straggler watchdog's
        started/finished hooks (fleet/straggler.py)."""
        self._watchdog = watchdog

    def _sync_topology(self) -> None:
        """Lazily re-point the engine at the topology's current mesh.
        Double-checked: the unlocked epoch read keeps the healthy-path
        dispatch free of the reshard lock."""
        if self._applied_epoch == self.topology.epoch:
            return
        with self._topo_lock:
            if self._applied_epoch != self.topology.epoch:
                self._reshard()

    def _reshard(self) -> None:
        """Swap the sharding tables onto the topology's current mesh
        (caller holds ``_topo_lock``). The restage seam of the degrade
        ladder: a failure HERE (the ``mesh.restage`` failpoint, or a
        mesh build raising on a half-dead runtime) descends one rung to
        the unsharded single-device path instead of escaping -- the
        engine must always come out of a reshard dispatchable."""
        t0 = time.monotonic()
        target = self.topology.epoch
        try:
            failpoints.eval("mesh.restage")
            self._apply_mesh(self.topology.current_mesh())
            reason = "unsharded" if self.mesh is None else self.topology.mode()
        except RuntimeError:
            metrics.HANDLED_ERRORS.inc(site="mesh.reshard")
            self._apply_mesh(None)
            reason = "restage-failed"
        self._applied_epoch = target
        metrics.MESH_RESHARDS.inc(reason=reason)
        metrics.MESH_RESHARD_SECONDS.observe(time.monotonic() - t0)

    def mark_device_lost(self, index: int, reason: str = "probe") -> bool:
        """Health-probe/operator entry: declare device `index` lost. The
        epoch bumps; the next dispatch reshards onto the survivors."""
        return self.topology.mark_lost(index, reason)

    def mark_device_returned(self, index: int) -> bool:
        """Declare device `index` healthy again; the next dispatch
        re-promotes (up to the full mesh, whose jit cache is kept warm
        by reusing the original Mesh object)."""
        return self.topology.mark_returned(index)

    def quarantine_worst_device(self, reason: str = "straggler") -> Optional[int]:
        """The straggler watchdog's quarantine rung: deterministically
        pick the highest-index healthy device and mark it lost. Returns
        the quarantined index, or None when already unsharded (nothing
        left to shrink -- the watchdog escalates to its next rung)."""
        healthy = self.topology.healthy_indices()
        if self.mesh is None or len(healthy) == 0:
            return None
        idx = healthy[-1]
        self.topology.mark_lost(idx, reason)
        return idx

    def _dispatch(self, entry: str, epoch: Optional[int], fn, *args):
        """Every solve entry funnels through here: sync the topology,
        fence stale epochs, bracket the straggler watchdog, and convert
        a device-loss RuntimeError into the typed ladder rung.

        LADDER_SEAM (analysis/checkers/errflow.py): the only exceptions
        crossing this frame are ``StaleTopologyError`` (typed: staged
        epoch no longer current, or a device died mid-dispatch -- the
        caller's StaleSeqnumError rung restages and retries), plain
        ``RuntimeError`` (a real program error, NOT a device loss --
        re-raised unchanged), and ``OperatorCrashed`` (never absorbed).
        """
        from karpenter_tpu.solver import rpc as rpc_mod

        self._sync_topology()
        if epoch is not None and epoch != self._applied_epoch:
            metrics.MESH_STALE_SOLVES.inc(site=entry)
            raise rpc_mod.StaleTopologyError(
                f"{entry}: staged under topology epoch {epoch}, "
                f"mesh is now at epoch {self._applied_epoch}"
            )
        metrics.MESH_DISPATCHES.inc(entry=entry)
        wd = self._watchdog
        if wd is not None:
            wd.dispatch_started(entry)
        try:
            failpoints.eval("mesh.device.lost")
            failpoints.eval("mesh.shard.stall")
            return fn(*args)
        except RuntimeError as e:
            if isinstance(e, rpc_mod.StaleSeqnumError):
                raise
            reason = topo_mod.classify_device_error(e)
            if reason is None or self.mesh is None:
                raise
            healthy = self.topology.healthy_indices()
            hint = topo_mod.device_index_hint(e)
            idx = hint if hint in healthy else (healthy[-1] if healthy else 0)
            self.topology.mark_lost(idx, reason)
            metrics.MESH_STALE_SOLVES.inc(site=entry)
            raise rpc_mod.StaleTopologyError(
                f"{entry}: device {idx} lost mid-dispatch ({reason}); "
                f"topology epoch now {self.topology.epoch}"
            ) from e
        finally:
            if wd is not None:
                wd.dispatch_finished()

    # -- catalog staging ------------------------------------------------------
    def stage_catalog(self, catalog) -> Tuple[ffd.StagedCatalog, Tuple[int, ...], Tuple[int, ...]]:
        """Sharded analogue of ffd.stage_catalog: the catalog uploads ONCE
        per seqnum, K-sharded over the types axis, and every later solve
        reuses the resident shards (per-solve traffic stays the ~100 KB of
        pod-class tensors, now split across devices by GSPMD)."""
        staged, offsets, words, _ = self.stage_catalog_versioned(catalog)
        return staged, offsets, words

    def stage_catalog_versioned(
        self, catalog
    ) -> Tuple[ffd.StagedCatalog, Tuple[int, ...], Tuple[int, ...], int]:
        """stage_catalog plus the topology epoch the shards were staged
        under -- read under the reshard lock, so the stamp can never
        name a NEWER mesh than the one holding the arrays. Callers keep
        the stamp beside the staged handle and pass it back at dispatch
        (`epoch=`); a membership change in between surfaces as
        StaleTopologyError and one restage."""
        with self._topo_lock:
            self._sync_topology()
            epoch = self._applied_epoch
            if self.mesh is None:
                staged, offsets, words = ffd.stage_catalog(catalog)
                return staged, offsets, words, epoch
            words = tuple(catalog.words)
            offsets = tuple(int(x) for x in np.cumsum((0,) + words[:-1]))
            sh = self._in_shardings
            staged = ffd.StagedCatalog(
                **{
                    name: self._put(getattr(catalog, name), getattr(sh, name))
                    for name in ffd.StagedCatalog._fields
                }
            )
            return staged, offsets, words, epoch

    def _put(self, x, sharding):
        if self._multiproc:
            return mesh_mod._put_multiprocess(x, sharding)
        return jax.device_put(x, sharding)

    def _mask_form(self, inp: ffd.SolveInputs) -> bool:
        """True when this solve's masks ride the packed shardings (a
        dtype metadata read -- the per-dispatch analogue of the kernels'
        trace-time dispatch)."""
        return packing.is_packed(inp.open_allowed) or packing.is_packed(
            inp.join_allowed
        )

    def _put_inputs(self, inp: ffd.SolveInputs) -> ffd.SolveInputs:
        """Multi-process meshes materialize shards per process; on an
        addressable mesh the jit's in_shardings move the host leaves, so
        the inputs pass through untouched (async dispatch preserved)."""
        if not self._multiproc:
            return inp
        sh = (
            self._in_shardings_packed
            if self._mask_form(inp) else self._in_shardings
        )
        return mesh_mod._put_multiprocess(inp, sh)

    # -- jitted entries (cached per statics, replicated outputs) --------------
    def _entry(self, kind: str, statics: tuple):
        key = (self.mesh, kind) + statics
        fn = _JIT_CACHE.get(key)
        if fn is not None:
            return fn
        with _JIT_LOCK:
            fn = _JIT_CACHE.get(key)
            if fn is None:
                fn = self._build(kind, statics)
                _JIT_CACHE[key] = fn
        return fn

    def _build(self, kind: str, statics: tuple):
        # the trailing static selects the mask shardings for solve kinds
        # (packed vs full-width -- part of the cache key, so each form
        # compiles its own sharded program exactly once)
        if kind in ("dense", "compact", "fused"):
            statics, packed = statics[:-1], statics[-1]
            in_sh = self._in_shardings_packed if packed else self._in_shardings
            solve_kw = dict(in_shardings=(in_sh,), out_shardings=self._rep)
        if kind == "dense":
            g_max, offsets, words, objective = statics
            return jax.jit(
                functools.partial(
                    ffd.ffd_solve_impl, g_max=g_max, word_offsets=offsets,
                    words=words, objective=objective,
                ),
                **solve_kw,
            )
        if kind in ("compact", "fused"):
            g_max, nnz_max, offsets, words, objective = statics
            body = (
                ffd.ffd_solve_compact.__wrapped__
                if kind == "compact"
                else ffd.ffd_solve_fused.__wrapped__
            )
            return jax.jit(
                functools.partial(
                    body, g_max=g_max, nnz_max=nnz_max, word_offsets=offsets,
                    words=words, objective=objective,
                ),
                **solve_kw,
            )
        if kind == "bound":
            # quality observatory (solver/bound.py): same input shardings
            # as the solve it shadows, placed counts replicated, [R]
            # totals all-gathered in-jit like every other entry
            offsets, words, packed = statics
            from karpenter_tpu.solver import bound as bound_mod

            in_sh = self._in_shardings_packed if packed else self._in_shardings
            return jax.jit(
                functools.partial(
                    bound_mod.fractional_price_bound_impl,
                    word_offsets=offsets, words=words,
                ),
                in_shardings=(in_sh, self._rep),
                out_shardings=self._rep,
            )
        if kind == "repack":
            from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

            s, rep = self._s_shard, self._rep
            return jax.jit(
                disrupt_kernel.disrupt_repack.__wrapped__,
                in_shardings=(rep, rep, rep, s, s),
                out_shardings=rep,
            )
        if kind == "replace":
            from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

            (od_col,) = statics
            s, rep, k = self._s_shard, self._rep, self._cat_k
            return jax.jit(
                functools.partial(disrupt_kernel.disrupt_replace.__wrapped__, od_col=od_col),
                in_shardings=(s, rep, rep, rep, rep, k, rep, k),
                out_shardings=rep,
            )
        raise ValueError(f"unknown mesh entry kind {kind!r}")

    # -- dispatch -------------------------------------------------------------
    def solve_fused(
        self, inp: ffd.SolveInputs, *, g_max: int, nnz_max: int,
        word_offsets: Tuple[int, ...], words: Tuple[int, ...],
        objective: str = "price", epoch: Optional[int] = None,
    ) -> jax.Array:
        """The production tick's sharded dispatch: async, one replicated
        u32 buffer out (the in-jit all-gather), same fused layout as
        ffd.ffd_solve_fused -- the caller's copy_to_host_async +
        expand_fused path is unchanged. `epoch` is the topology stamp
        the inputs were staged under (stage_catalog_versioned)."""
        def run():
            if self.mesh is None:
                return ffd.ffd_solve_fused(
                    inp, g_max=g_max, nnz_max=nnz_max,
                    word_offsets=word_offsets, words=words, objective=objective,
                )
            fn = self._entry(
                "fused",
                (g_max, nnz_max, word_offsets, words, objective, self._mask_form(inp)),
            )
            return fn(self._put_inputs(inp))

        return self._dispatch("fused", epoch, run)

    def solve_compact(
        self, inp: ffd.SolveInputs, *, g_max: int, nnz_max: int,
        word_offsets: Tuple[int, ...], words: Tuple[int, ...],
        objective: str = "price", epoch: Optional[int] = None,
    ) -> ffd.CompactDecision:
        def run():
            if self.mesh is None:
                return ffd.ffd_solve_compact(
                    inp, g_max=g_max, nnz_max=nnz_max,
                    word_offsets=word_offsets, words=words, objective=objective,
                )
            fn = self._entry(
                "compact",
                (g_max, nnz_max, word_offsets, words, objective, self._mask_form(inp)),
            )
            return fn(self._put_inputs(inp))

        return self._dispatch("compact", epoch, run)

    def solve_dense(
        self, inp: ffd.SolveInputs, *, g_max: int,
        word_offsets: Tuple[int, ...], words: Tuple[int, ...],
        objective: str = "price", epoch: Optional[int] = None,
    ) -> ffd.SolveOutputs:
        def run():
            if self.mesh is None:
                return ffd.ffd_solve(
                    inp, g_max=g_max, word_offsets=word_offsets, words=words,
                    objective=objective,
                )
            fn = self._entry(
                "dense",
                (g_max, word_offsets, words, objective, self._mask_form(inp)),
            )
            return fn(self._put_inputs(inp))

        return self._dispatch("dense", epoch, run)

    def price_bound(
        self, inp: ffd.SolveInputs, placed, *,
        word_offsets: Tuple[int, ...], words: Tuple[int, ...],
        epoch: Optional[int] = None,
    ) -> jax.Array:
        """The optimality-gap bound's sharded dispatch (solver/bound.py):
        async, [R] replicated totals out -- the caller's
        copy_to_host_async + fetch_bound barrier is unchanged."""
        def run():
            if self.mesh is None:
                from karpenter_tpu.solver import bound as bound_mod

                return bound_mod.fractional_price_bound(
                    inp, placed, word_offsets=word_offsets, words=words,
                )
            fn = self._entry(
                "bound", (word_offsets, words, self._mask_form(inp)))
            args = (self._put_inputs(inp), placed)
            if self._multiproc:
                args = (args[0], mesh_mod._put_multiprocess(placed, self._rep))
            return fn(*args)

        return self._dispatch("bound", epoch, run)

    def repack(self, headroom, feas, req, member, excl, *, epoch: Optional[int] = None):
        """Disrupt candidate-pool repack, set axis sharded over every mesh
        axis (embarrassingly parallel; winners all-gather in-jit)."""
        def run():
            if self.mesh is None:
                from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

                return disrupt_kernel.disrupt_repack(headroom, feas, req, member, excl)
            fn = self._entry("repack", ())
            args = (headroom, feas, req, member, excl)
            if self._multiproc:
                shs = (self._rep, self._rep, self._rep, self._s_shard, self._s_shard)
                args = tuple(
                    mesh_mod._put_multiprocess(a, s) for a, s in zip(args, shs)
                )
            return fn(*args)

        return self._dispatch("repack", epoch, run)

    def replace(self, leftover, creq, compat, azone, acap, cap, ovh, price, *,
                od_col: int, epoch: Optional[int] = None):
        """Disrupt replacement search: leftover sharded on the set axis,
        catalog cap/price on their staged K-sharding."""
        def run():
            if self.mesh is None:
                from karpenter_tpu.solver.disrupt import kernel as disrupt_kernel

                return disrupt_kernel.disrupt_replace(
                    leftover, creq, compat, azone, acap, cap, ovh, price,
                    od_col=od_col,
                )
            fn = self._entry("replace", (od_col,))
            args = (leftover, creq, compat, azone, acap, cap, ovh, price)
            if self._multiproc:
                r, k, s = self._rep, self._cat_k, self._s_shard
                shs = (s, r, r, r, r, k, r, k)
                args = tuple(
                    mesh_mod._put_multiprocess(a, sh) for a, sh in zip(args, shs)
                )
            return fn(*args)

        return self._dispatch("replace", epoch, run)

    def fetch(self, out, *, epoch: Optional[int] = None):
        """SANCTIONED_FETCH site (analysis/checkers/jax_discipline.py):
        the mesh engine's designed host barrier. Outputs are already
        replicated ON DEVICE (the in-jit all-gather via out_shardings),
        so this is a local read on every process -- no per-fetch
        re-shard, even on non-addressable meshes. With an `epoch`, the
        barrier is fenced: reading a buffer computed on a mesh that has
        since lost a device would block on a dead chip, so a stale stamp
        raises StaleTopologyError BEFORE the read and the caller's
        staging-gap rung re-solves on the current topology."""
        if epoch is not None and epoch != self.topology.epoch:
            from karpenter_tpu.solver import rpc as rpc_mod

            metrics.MESH_STALE_SOLVES.inc(site="fetch")
            raise rpc_mod.StaleTopologyError(
                f"fetch: buffer computed at topology epoch {epoch}, "
                f"mesh is now at epoch {self.topology.epoch}"
            )
        return jax.tree_util.tree_map(np.asarray, out)

    def describe(self) -> dict:
        """Mesh shape + jit-cache occupancy for /debug and the bench's
        fleet stage."""
        doc = {
            "devices": int(self.mesh.devices.size) if self.mesh is not None else 1,
            "axes": (
                {
                    name: int(size)
                    for name, size in zip(self.mesh.axis_names, self.mesh.devices.shape)
                }
                if self.mesh is not None else {}
            ),
            "multiprocess": bool(self._multiproc),
            "jit_entries": sorted(
                str(k[1:]) for k in _JIT_CACHE if k[0] is self.mesh
            ),
            "topology": self.topology.describe(),
            "mode": self.topology.mode(),
        }
        return doc
