"""Shard-straggler watchdog: the per-shard-dispatch arm of PR-9's
stuck-tick machinery.

The StuckTickWatchdog (overload.py) sees a WHOLE tick wedged; it cannot
tell which layer wedged it. On the mesh fleet path the interesting
failure is one rung lower: a single sharded dispatch stalls -- one
device's program hangs, a collective waits on a dead chip -- while the
rest of the mesh is healthy. This watchdog brackets every
``MeshSolveEngine._dispatch`` and escalates a dispatch wedged past N x
the per-shard budget through its own ladder:

    cancel       (default  4 x budget) -- run the cancel hook (close the
                 solver wire / abort the transfer); a blocked fetch dies
                 with its stream and the dispatch raises
    quarantine   (default  8 x budget) -- mark the WORST device lost on
                 the engine's TopologyTracker: the epoch bumps, the next
                 dispatch resolves the stall as a typed
                 StaleTopologyError, and the reshard lands the solve on
                 the surviving devices
    breaker-open (default 12 x budget) -- force the breaker open so
                 regular traffic stops touching the mesh path at all
    crash        (default 16 x budget) -- async-raise OperatorCrashed
                 into the wedged thread; the PR-6 journal recovery sweep
                 takes over

Same discipline as the template: hooks run OUTSIDE the lock, the crash
raise alone runs UNDER it after re-verifying the same dispatch is still
wedged, and the flight-data black box flushes before the raise.
Deterministic rigs drive ``check_now()``; the production sidecar runs
the background thread (``start()``).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from karpenter_tpu import metrics
from karpenter_tpu.logging import get_logger
from karpenter_tpu.overload import _async_raise_crash


class ShardStragglerWatchdog:
    """Detects one sharded dispatch wedged past N x the per-shard budget
    and escalates cancel -> device-quarantine (epoch bump) -> the
    existing breaker/crash rungs."""

    STAGES = ("cancel", "quarantine", "breaker-open", "crash")
    log = get_logger("straggler")

    def __init__(self, budget: float, *, engine=None,
                 cancel: Optional[Callable[[], None]] = None, breaker=None,
                 multiples=(4.0, 8.0, 12.0, 16.0),
                 clock: Callable[[], float] = time.monotonic):
        self.budget = float(budget)
        self.multiples = tuple(float(m) for m in multiples)
        self._engine = engine
        self._cancel = cancel
        self._breaker = breaker
        self._clock = clock
        self._lock = threading.Lock()
        self._started: Optional[float] = None
        self._entry: Optional[str] = None
        self._thread_id: Optional[int] = None
        self._stage = 0
        # dispatch generation: bumps on every dispatch_started so the
        # crash rung can re-verify under the lock that the SAME dispatch
        # is still wedged immediately before the async raise (see
        # StuckTickWatchdog._generation)
        self._generation = 0
        self.escalations = {s: 0 for s in self.STAGES}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- dispatch bracketing (called by MeshSolveEngine._dispatch) ------------
    def dispatch_started(self, entry: str) -> None:
        with self._lock:
            self._started = self._clock()
            self._entry = str(entry)
            self._thread_id = threading.get_ident()
            self._stage = 0
            self._generation += 1

    def dispatch_finished(self) -> None:
        with self._lock:
            self._started = None
            self._entry = None
            self._stage = 0

    # -- escalation ----------------------------------------------------------
    def check_now(self) -> Optional[str]:
        """Evaluate the ladder once; returns the stage name fired, or
        None. Cancel/quarantine/breaker hooks run OUTSIDE the lock (they
        take other subsystems' locks: the engine's topology lock, the
        breaker's); the crash raise alone runs UNDER it."""
        with self._lock:
            if self._started is None or self._stage >= len(self.STAGES):
                return None
            elapsed = self._clock() - self._started
            if elapsed < self.multiples[self._stage] * self.budget:
                return None
            stage = self._stage
            self._stage += 1
            entry = self._entry
            tid = self._thread_id
            gen = self._generation
        name = self.STAGES[stage]
        if name == "crash":
            # flush the black box BEFORE the raise, from this thread: a
            # C-level hang may never reach a bytecode boundary, so the
            # dispatch-side OperatorCrashed flush may never run
            try:
                from karpenter_tpu.obs import flight

                flight.flush_blackbox(reason="straggler-crash")
            except Exception:  # noqa: BLE001 -- best-effort, like cancel
                metrics.HANDLED_ERRORS.inc(site="fleet.straggler.flush")
            # re-check AND raise under the lock: dispatch_finished takes
            # this same lock, so the exception is pending in the wedged
            # thread before the dispatch can be marked finished
            with self._lock:
                still_wedged = (
                    self._started is not None and self._generation == gen
                    and tid is not None
                )
                if still_wedged:
                    _async_raise_crash(tid)
            if not still_wedged:
                self.log.warning(
                    "straggling shard dispatch un-wedged before the crash "
                    "escalation; standing down")
                return None
        self.escalations[name] += 1
        metrics.MESH_SHARD_WATCHDOG.inc(stage=name)
        self.log.warning(
            "shard-straggler watchdog escalation",
            stage=name, entry=entry, elapsed_s=round(elapsed, 3),
            budget_s=self.budget,
        )
        if name == "cancel":
            if self._cancel is not None:
                try:
                    self._cancel()
                except Exception:  # noqa: BLE001 -- cancel is best-effort
                    metrics.HANDLED_ERRORS.inc(site="fleet.straggler.cancel")
        elif name == "quarantine":
            if self._engine is not None:
                try:
                    idx = self._engine.quarantine_worst_device(reason="straggler")
                    self.log.warning(
                        "straggler quarantine", device=idx,
                        epoch=self._engine.epoch)
                except Exception:  # noqa: BLE001 -- escalation is best-effort
                    metrics.HANDLED_ERRORS.inc(site="fleet.straggler.quarantine")
        elif name == "breaker-open":
            if self._breaker is not None:
                try:
                    self._breaker.force_open(reason="shard-straggler watchdog")
                except Exception:  # noqa: BLE001 -- escalation is best-effort
                    metrics.HANDLED_ERRORS.inc(site="fleet.straggler.breaker")
        # (the crash rung already raised above, under the lock)
        return name

    # -- background loop (the wall-clock sidecar) -----------------------------
    def start(self) -> "ShardStragglerWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="shard-straggler-watchdog"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        interval = max(0.05, self.budget / 2.0)
        while not self._stop.wait(timeout=interval):
            self.check_now()

    def stop(self) -> None:
        self._stop.set()

    def describe(self) -> dict:
        with self._lock:
            active_s = (
                round(self._clock() - self._started, 3)
                if self._started is not None else None
            )
            entry = self._entry
        return {
            "budget_s": self.budget,
            "multiples": list(self.multiples),
            "dispatch_active_for_s": active_s,
            "entry": entry,
            "escalations": dict(self.escalations),
        }
