"""Fleet subsystem: one TPU mesh as the scheduling brain of a fleet.

Two halves (ROADMAP "Mesh-sharded production solve" open item):

- ``fleet/shard.py`` -- the mesh-sharded PRODUCTION solve: promotes the
  multichip dry-run (``parallel/mesh.py``, MULTICHIP_r05) onto the real
  tick. Catalog and candidate-pool tensors shard across the device mesh,
  per-shard winners all-gather INSIDE the jitted entry (replicated
  ``out_shardings``), and the pipelined ``solve_begin``/``solve_finish``
  and delta-epoch contracts hold per shard. ``sharded == unsharded`` is
  bit-identity asserted the way ``host == wire`` is today
  (tests/test_fleet.py, the ``mesh`` sim backend).

- ``fleet/coalesce.py`` -- the multi-tenant dispatch coalescer: the rpc
  sidecar already stages catalogs under per-connection seqnums; the
  coalescer batches concurrent solves from N operator replicas into
  shared device dispatch windows with deterministic tenant ordering,
  per-tenant deadline budgets feeding the existing overload ladder, and
  a per-tenant breaker/degrade so one sick cluster never poisons
  another. ``multi-tenant == isolated`` is asserted via differential sim
  replay (``sim/fleet.py``, the ``multi-cluster-storm`` corpus scenario).

``fleet/service.py`` glues both into a deployable sidecar topology;
``fleet/topology.py`` + ``fleet/straggler.py`` are its failure ladder
(topology epochs, the device-loss degrade ladder, and the shard-straggler
watchdog).
"""
from karpenter_tpu.fleet.coalesce import DispatchCoalescer, TenantRefusal
from karpenter_tpu.fleet.shard import MeshSolveEngine, mesh_from_env, parse_mesh_spec
from karpenter_tpu.fleet.straggler import ShardStragglerWatchdog
from karpenter_tpu.fleet.topology import TopologyTracker, classify_device_error

__all__ = [
    "DispatchCoalescer",
    "MeshSolveEngine",
    "ShardStragglerWatchdog",
    "TenantRefusal",
    "TopologyTracker",
    "classify_device_error",
    "mesh_from_env",
    "parse_mesh_spec",
]
