"""Interruption event schemas + parser registry.

Rebuilds the reference's message layer
(/root/reference/pkg/controllers/interruption/parser.go:1-93 and
messages/{spotinterruption,statechange,scheduledchange,
rebalancerecommendation,noop}) for this cloud's event bus: every body is an
EventBridge-shaped envelope -- `version` / `source` / `detail-type` metadata
with a nested `detail` document -- and a parser is selected by the exact
(version, source, detail-type) triple. Unknown triples, empty bodies, and
malformed JSON all degrade to a no-op message rather than erroring the
batch (parser.go:76-93).

The five message kinds and their wire shapes:

  Spot Instance Interruption Warning   (cloud.compute@SpotInterruption v0)
      detail: {"instance-id": ..., "instance-action": "terminate"}
  Instance State-change Notification   (cloud.compute@StateChange v1)
      detail: {"instance-id": ..., "state": "stopping|stopped|
               shutting-down|terminated"}  (other states parse to None ->
               noop, statechange/parser accepted-states set)
  Health Event                         (cloud.health@HealthEvent v0)
      detail: {"service": "COMPUTE", "eventTypeCategory":
               "scheduledChange", "affectedEntities":
               [{"entityValue": instance-id}, ...]}  (other services /
               categories -> noop, scheduledchange/parser)
  Instance Rebalance Recommendation    (cloud.compute@Rebalance v0)
      detail: {"instance-id": ...}
  no-op                                (everything else)
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# kinds (reference messages/types.go Kind values)
KIND_SPOT_INTERRUPTED = "spot_interrupted"
KIND_SCHEDULED_CHANGE = "scheduled_change"
KIND_INSTANCE_STOPPED = "instance_stopped"
KIND_INSTANCE_TERMINATED = "instance_terminated"
KIND_REBALANCE_RECOMMENDATION = "rebalance_recommendation"
KIND_NOOP = "no_op"

SOURCE_COMPUTE = "cloud.compute"
SOURCE_HEALTH = "cloud.health"

DETAIL_SPOT_INTERRUPTION = "Spot Instance Interruption Warning"
DETAIL_STATE_CHANGE = "Instance State-change Notification"
DETAIL_HEALTH_EVENT = "Health Event"
DETAIL_REBALANCE = "Instance Rebalance Recommendation"

_STOPPED_STATES = {"stopping", "stopped"}
_ACCEPTED_STATES = {"stopping", "stopped", "shutting-down", "terminated"}

_HEALTH_SERVICE = "COMPUTE"
_HEALTH_CATEGORY = "scheduledChange"


@dataclass
class Metadata:
    """The EventBridge envelope (reference messages/types.go Metadata)."""

    version: str = ""
    source: str = ""
    detail_type: str = ""
    id: str = ""
    region: str = ""
    account: str = ""
    time: str = ""
    resources: List[str] = field(default_factory=list)

    @classmethod
    def from_doc(cls, doc: dict) -> "Metadata":
        resources = doc.get("resources")
        return cls(
            version=str(doc.get("version", "")),
            source=str(doc.get("source", "")),
            detail_type=str(doc.get("detail-type", "")),
            id=str(doc.get("id", "")),
            region=str(doc.get("region", "")),
            account=str(doc.get("account", "")),
            time=str(doc.get("time", "")),
            # arbitrary JSON may put a scalar here; never raise on shape
            resources=[str(r) for r in resources] if isinstance(resources, list) else [],
        )


@dataclass
class Message:
    """A parsed interruption event: which instances, what kind."""

    metadata: Metadata
    kind: str
    instance_ids: List[str] = field(default_factory=list)
    state: str = ""

    def start_time(self) -> str:
        return self.metadata.time


def _noop(metadata: Optional[Metadata] = None) -> Message:
    return Message(metadata=metadata or Metadata(), kind=KIND_NOOP)


class SpotInterruptionParser:
    """cloud.compute@SpotInterruption (reference
    messages/spotinterruption/parser.go)."""

    version = "0"
    source = SOURCE_COMPUTE
    detail_type = DETAIL_SPOT_INTERRUPTION

    def parse(self, metadata: Metadata, detail: dict) -> Optional[Message]:
        iid = str(detail.get("instance-id", ""))
        if not iid:
            return None
        return Message(metadata=metadata, kind=KIND_SPOT_INTERRUPTED, instance_ids=[iid])


class StateChangeParser:
    """cloud.compute@StateChange (reference messages/statechange/parser.go:
    only the accepted states produce a message; stopping/stopped map to
    InstanceStopped, shutting-down/terminated to InstanceTerminated)."""

    version = "1"
    source = SOURCE_COMPUTE
    detail_type = DETAIL_STATE_CHANGE

    def parse(self, metadata: Metadata, detail: dict) -> Optional[Message]:
        iid = str(detail.get("instance-id", ""))
        state = str(detail.get("state", "")).lower()
        if not iid or state not in _ACCEPTED_STATES:
            return None
        kind = KIND_INSTANCE_STOPPED if state in _STOPPED_STATES else KIND_INSTANCE_TERMINATED
        return Message(metadata=metadata, kind=kind, instance_ids=[iid], state=state)


class ScheduledChangeParser:
    """cloud.health@HealthEvent (reference messages/scheduledchange/
    parser.go: only COMPUTE scheduledChange events; every affected entity
    is an instance)."""

    version = "0"
    source = SOURCE_HEALTH
    detail_type = DETAIL_HEALTH_EVENT

    def parse(self, metadata: Metadata, detail: dict) -> Optional[Message]:
        if (
            str(detail.get("service", "")) != _HEALTH_SERVICE
            or str(detail.get("eventTypeCategory", "")) != _HEALTH_CATEGORY
        ):
            return None
        entities = detail.get("affectedEntities")
        if not isinstance(entities, list):
            return None
        ids = [
            str(e.get("entityValue", ""))
            for e in entities
            if isinstance(e, dict) and e.get("entityValue")
        ]
        if not ids:
            return None
        return Message(metadata=metadata, kind=KIND_SCHEDULED_CHANGE, instance_ids=ids)


class RebalanceRecommendationParser:
    """cloud.compute@Rebalance (reference
    messages/rebalancerecommendation/parser.go)."""

    version = "0"
    source = SOURCE_COMPUTE
    detail_type = DETAIL_REBALANCE

    def parse(self, metadata: Metadata, detail: dict) -> Optional[Message]:
        iid = str(detail.get("instance-id", ""))
        if not iid:
            return None
        return Message(
            metadata=metadata, kind=KIND_REBALANCE_RECOMMENDATION, instance_ids=[iid]
        )


DEFAULT_PARSERS = (
    SpotInterruptionParser(),
    StateChangeParser(),
    ScheduledChangeParser(),
    RebalanceRecommendationParser(),
)


class EventParser:
    """Parser registry keyed by the (version, source, detail-type) triple
    (reference parser.go:32-74). Everything unrecognized is a no-op."""

    def __init__(self, *parsers):
        ps = parsers or DEFAULT_PARSERS
        self._by_key: Dict[Tuple[str, str, str], object] = {
            (p.version, p.source, p.detail_type): p for p in ps
        }

    def parse(self, raw: str) -> Message:
        if not raw:
            return _noop()
        try:
            doc = json.loads(raw)
        except (json.JSONDecodeError, TypeError):
            return _noop()
        if not isinstance(doc, dict):
            return _noop()
        metadata = Metadata.from_doc(doc)
        parser = self._by_key.get((metadata.version, metadata.source, metadata.detail_type))
        if parser is None:
            return _noop(metadata)
        detail = doc.get("detail")
        if not isinstance(detail, dict):
            return _noop(metadata)
        msg = parser.parse(metadata, detail)
        return msg if msg is not None else _noop(metadata)
