"""Garbage collection: orphaned cloud instances and stale claims.

Rebuilds pkg/controllers/nodeclaim/garbagecollection/controller.go:55-111:
list cluster-owned cloud instances, subtract those with a live NodeClaim,
and terminate the rest (instances whose claim was deleted out-of-band or
whose creation never completed). A freshly-launched instance gets a grace
window before it can be considered orphaned (its claim status may not have
committed yet).
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.apis import NodeClaim, Node
from karpenter_tpu import metrics
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger

LAUNCH_GRACE = 60.0


class GarbageCollectionController:
    log = get_logger("garbagecollection")

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider):
        self.cluster = cluster
        self.cloud_provider = cloud_provider

    def reconcile(self) -> List[str]:
        """Returns terminated instance ids."""
        now = self.cluster.clock.now()
        claimed = {c.provider_id for c in self.cluster.list(NodeClaim) if c.provider_id}
        nodes_by_provider = {n.provider_id: n for n in self.cluster.list(Node) if n.provider_id}
        removed = []
        for inst in self.cloud_provider.list_instances():
            if inst.provider_id in claimed:
                continue
            if now - inst.launch_time < LAUNCH_GRACE:
                continue
            try:
                # instance-level delete (there is no claim to route through
                # CloudProvider.delete); the instance provider still does the
                # reservation bookkeeping
                self.cloud_provider.instances.delete(inst.id)
                removed.append(inst.id)
                self.log.info("garbage-collected orphan instance", instance=inst.id)
                from karpenter_tpu import metrics

                metrics.GARBAGE_COLLECTED.inc()
            except NotFoundError:
                pass
            node = nodes_by_provider.get(inst.provider_id)
            if node is not None:
                self.cluster.unbind_pods(node.metadata.name)
                node.metadata.finalizers = []
                self.cluster.delete(Node, node.metadata.name)
        return removed
