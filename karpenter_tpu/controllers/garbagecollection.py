"""Garbage collection: orphaned cloud instances and stale claims.

Rebuilds pkg/controllers/nodeclaim/garbagecollection/controller.go:55-111:
list cluster-owned cloud instances, subtract those with a live NodeClaim,
and terminate the rest (instances whose claim was deleted out-of-band).

With the intent journal wired (karpenter_tpu/journal.py) GC is DEMOTED to
out-of-band deletions only: an instance whose intent token matches an open
journal intent belongs to the crash-consistency layer -- the recovery
sweep adopts or terminates it -- and is never eligible here, no matter its
age. The launch-grace window remains only as the safety net for instances
with no journal record (pre-journal launches, foreign tooling), and is
inclusive at the boundary: an instance aged EXACTLY the grace whose claim
status has not yet committed was the round-6 race -- eligible here in the
same tick the provisioner was about to commit it.
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.apis import NodeClaim, Node
from karpenter_tpu import metrics
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger

LAUNCH_GRACE = 60.0


class GarbageCollectionController:
    log = get_logger("garbagecollection")

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider, journal=None,
                 recovery=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.journal = journal  # optional IntentJournal
        # optional RecoverySweepController: GC routes STALE intents (open
        # records whose claim left the bus out-of-band, so no restart will
        # ever replay them) through the same replay logic the
        # election-win sweep uses
        self.recovery = recovery

    def reconcile(self) -> List[str]:
        """Returns terminated instance ids."""
        from karpenter_tpu.apis.objects import INTENT_TOKEN_KEY

        now = self.cluster.clock.now()
        if self.journal is not None and self.recovery is not None:
            # stale-intent janitor: an open intent whose claim is gone has
            # no termination controller left to resolve it and no restart
            # guaranteed to come -- replay it here (terminates any
            # half-launched instance immediately, resolves the record)
            for intent in self.journal.open_intents():
                if self.cluster.try_get(NodeClaim, intent.claim_name) is None:
                    try:
                        self.recovery.replay_intent(intent)
                    except Exception as e:  # noqa: BLE001 -- per-intent
                        # isolation, same as the sweep: a cloud fault costs
                        # this record's replay (it stays open for the next
                        # pass), never the whole GC reconcile
                        self.log.warning(
                            "stale-intent replay failed; left open",
                            intent=intent.metadata.name,
                            error=f"{type(e).__name__}: {e}",
                        )
        claimed = {c.provider_id for c in self.cluster.list(NodeClaim) if c.provider_id}
        nodes_by_provider = {n.provider_id: n for n in self.cluster.list(Node) if n.provider_id}
        open_tokens = (
            set(self.journal.open_tokens()) if self.journal is not None else set()
        )
        removed = []
        for inst in self.cloud_provider.list_instances():
            if inst.provider_id in claimed:
                continue
            token = inst.tags.get(INTENT_TOKEN_KEY)
            if token and token in open_tokens:
                # crash-consistency territory: an open launch intent owns
                # this instance; the recovery sweep (not GC) decides its
                # fate. Collecting it here would race the provisioner's
                # status commit at the grace boundary (round-6 race).
                continue
            if now - inst.launch_time <= LAUNCH_GRACE:
                continue
            try:
                # instance-level delete (there is no claim to route through
                # CloudProvider.delete); the instance provider still does the
                # reservation bookkeeping
                self.cloud_provider.instances.delete(inst.id)
                removed.append(inst.id)
                self.log.info("garbage-collected orphan instance", instance=inst.id)
                from karpenter_tpu import metrics

                metrics.GARBAGE_COLLECTED.inc()
            except NotFoundError:
                pass
            node = nodes_by_provider.get(inst.provider_id)
            if node is not None:
                self.cluster.unbind_pods(node.metadata.name)
                node.metadata.finalizers = []
                self.cluster.delete(Node, node.metadata.name)
        return removed
