"""Post-launch instance tagging.

Rebuilds pkg/controllers/nodeclaim/tagging/controller.go:62-131: once a
NodeClaim is launched and registered, stamp the instance with its Name and
cluster-resolution tags (the fleet call already applied the ownership tags;
this adds the ones only known post-registration, e.g. the node name).
"""
from __future__ import annotations

from karpenter_tpu.apis import NodeClaim
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.providers.instance.provider import NODECLAIM_TAG
from karpenter_tpu.utils import parse_instance_id
from karpenter_tpu.logging import get_logger

ANNOTATION_TAGGED = "karpenter.tpu/tagged"


class TaggingController:
    log = get_logger("tagging")

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider):
        self.cluster = cluster
        self.cloud_provider = cloud_provider

    def reconcile_all(self) -> int:
        tagged = 0
        for claim in self.cluster.list(NodeClaim):
            if not claim.launched() or claim.deleting:
                continue
            if claim.metadata.annotations.get(ANNOTATION_TAGGED) == "true":
                continue
            if not claim.node_name:
                continue  # wait for registration so the node name is final
            try:
                self.cloud_provider.instances.create_tags(
                    parse_instance_id(claim.provider_id),
                    {
                        "Name": claim.node_name,
                        NODECLAIM_TAG: claim.metadata.name,
                    },
                )
            except NotFoundError:
                continue
            claim.metadata.annotations[ANNOTATION_TAGGED] = "true"
            self.cluster.update(claim)
            tagged += 1
            self.log.debug("tagged instance", nodeclaim=claim.metadata.name, node=claim.node_name)
        return tagged
