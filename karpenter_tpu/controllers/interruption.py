"""Interruption controller: queue events -> node drain.

Rebuilds pkg/controllers/interruption/controller.go:96-248: polls the
interruption queue, parses each body through the (version, source,
detail-type)-keyed EventBridge parser registry
(interruption_messages.EventParser -- the five kinds: spot interruption,
scheduled health change, instance stopped, instance terminated, rebalance
recommendation, plus no-op), marks reclaimed spot capacity unavailable in
the ICE cache so the scheduler routes around it (controller.go:219-225),
deletes the affected NodeClaim (cordon-and-drain), and deletes the message.

Messages fan out over a worker pool exactly as the reference's
workqueue.ParallelizeUntil(ctx, 10, ...) (controller.go:119); the in-memory
cluster is lock-protected, and each worker keeps per-message isolation (a
bad message never blocks the batch).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from karpenter_tpu.apis import NodeClaim, labels as wk
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
from karpenter_tpu import metrics
from karpenter_tpu.events import Recorder, WARNING
from karpenter_tpu.cloud.api import QueueAPI
from karpenter_tpu.controllers.interruption_messages import (
    KIND_NOOP,
    KIND_REBALANCE_RECOMMENDATION,
    KIND_SPOT_INTERRUPTED,
    EventParser,
    Message,
)
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger

PARALLELISM = 10  # reference: workqueue.ParallelizeUntil(ctx, 10, ...)


class InterruptionController:
    log = get_logger("interruption")

    def __init__(
        self,
        cluster: Cluster,
        queue: QueueAPI,
        unavailable: UnavailableOfferings,
        recorder: Optional[Recorder] = None,
        parser: Optional[EventParser] = None,
        max_per_sweep: int = 1000,
    ):
        self.cluster = cluster
        self.queue = queue
        self.unavailable = unavailable
        self.recorder = recorder or Recorder()
        self.parser = parser or EventParser()
        # bounded per-sweep intake (overload hardening): an interruption
        # STORM must not grow one sweep unboundedly -- past the bound the
        # still-queued remainder carries over to the next sweep (messages
        # stay on the queue; nothing is dropped), counted into
        # karpenter_interruption_deferred_total. 0 = unbounded (the
        # throughput bench's mode).
        self.max_per_sweep = int(max_per_sweep)
        # True when the LAST sweep stopped at its bound: the deferral is
        # counted only when the carried-over messages are actually
        # RECEIVED next sweep (the queue API cannot be peeked, and a
        # sweep whose bound landed exactly on the final message must not
        # report a deferral that never happened)
        self._bound_hit = False
        # serializes the deleting-check + delete + count: two workers
        # handling duplicate events for one instance must terminate (and
        # count) the claim exactly once
        self._drain_lock = threading.Lock()

    def reconcile(self, max_messages: int = 10,
                  max_per_sweep: Optional[int] = None) -> int:
        """One poll sweep; returns messages handled. The reference requeues
        immediately while messages remain (:114-136); callers loop. The
        intake is BOUNDED per sweep (max_per_sweep, default from the
        constructor): past the bound the sweep returns and the remainder
        stays queued for the next sweep, so an interruption storm costs
        bounded tick time instead of one unbounded batch."""
        limit = self.max_per_sweep if max_per_sweep is None else int(max_per_sweep)
        handled = 0
        with ThreadPoolExecutor(max_workers=PARALLELISM) as pool:
            while True:
                want = max_messages if limit <= 0 else min(
                    max_messages, limit - handled)
                batch = self.queue.receive(want)
                if not batch:
                    # the previous sweep's bound left nothing behind after
                    # all: no deferral to report
                    self._bound_hit = False
                    return handled
                if handled == 0 and self._bound_hit:
                    # the previous sweep's bound left work behind and
                    # this sweep found messages waiting: count the
                    # deferral at the moment the carry-over is observed.
                    # A bound landing exactly on the last queued message
                    # counts nothing UNLESS fresh messages arrived in the
                    # gap -- indistinguishable without queue visibility,
                    # and under the arrival stream that makes it happen
                    # the bound genuinely is deferring capacity anyway.
                    self._bound_hit = False
                    metrics.INTERRUPTION_DEFERRED.inc()
                list(pool.map(self._process, batch))
                handled += len(batch)
                if 0 < limit <= handled:
                    # carry-over: whatever is still queued waits for the
                    # next sweep (the queue holds it durably)
                    self._bound_hit = True
                    self.log.info(
                        "interruption intake bound reached; deferring "
                        "any remainder to the next sweep",
                        handled=handled, bound=limit,
                    )
                    return handled

    def _process(self, msg) -> None:
        parsed = None
        try:
            # parsing stays INSIDE the isolation boundary: a pathological
            # body must neither strand the batch nor leave the message
            # undeleted (the contract the module docstring promises)
            parsed = self.parser.parse(msg.body)
            metrics.INTERRUPTION_RECEIVED.inc(kind=parsed.kind)
            self._handle(parsed)
        except Exception as e:  # noqa: BLE001 -- per-message isolation
            self.recorder.publish(
                parsed, "InterruptionHandlingFailed", str(e), type=WARNING
            )
        finally:
            self.queue.delete(msg.receipt)
            metrics.INTERRUPTION_DELETED.inc()

    # -- handling -----------------------------------------------------------
    def _claim_for_instance(self, instance_id: str) -> Optional[NodeClaim]:
        # O(1) via the status.instanceID field index when the operator
        # registered it (reference: NodeClaimInstanceIDIndexer,
        # pkg/operator/operator.go:284-305); a bare controller without the
        # index (unit tests) falls back to the scan
        if self.cluster.has_index(NodeClaim, "status.instanceID"):
            hits = self.cluster.by_index(NodeClaim, "status.instanceID", instance_id)
            return hits[0] if hits else None
        suffix = f"/{instance_id}"
        for claim in self.cluster.list(NodeClaim):
            if claim.provider_id.endswith(suffix):
                return claim
        return None

    def _handle(self, parsed: Message) -> None:
        if parsed.kind == KIND_NOOP:
            return
        for instance_id in parsed.instance_ids:
            claim = self._claim_for_instance(instance_id)
            if claim is None:
                continue
            if parsed.kind == KIND_REBALANCE_RECOMMENDATION:
                # advisory only: record, do not disrupt (reference treats
                # rebalance recommendations as events unless configured)
                self.recorder.publish(
                    claim, "RebalanceRecommendation", "capacity may be reclaimed soon"
                )
                continue
            if parsed.kind == KIND_SPOT_INTERRUPTED:
                # the pool is being reclaimed: negative-cache it so the
                # scheduler stops offering this (type, zone, spot) pool
                # (controller.go:219-225)
                itype = claim.instance_type
                zone = claim.zone
                if itype and zone:
                    self.unavailable.mark_unavailable(
                        itype, zone, wk.CAPACITY_TYPE_SPOT, reason="SpotInterruption"
                    )
            self.recorder.publish(
                claim, "Interrupted", f"{parsed.kind} for {instance_id}", type=WARNING
            )
            with self._drain_lock:
                if claim.deleting:
                    continue
                self.cluster.delete(NodeClaim, claim.metadata.name)
                metrics.NODECLAIMS_TERMINATED.inc(
                    nodepool=claim.nodepool_name or "", reason="interruption"
                )
                self.log.info(
                    "interruption drain",
                    nodeclaim=claim.metadata.name,
                    kind=parsed.kind,
                    instance=instance_id,
                )
