"""Interruption controller: queue events -> node drain.

Rebuilds pkg/controllers/interruption/controller.go:96-248 + parser.go +
messages/: polls the interruption queue, parses the five message kinds
(spot interruption, scheduled maintenance/health change, instance state
change, rebalance recommendation, noop), marks spot capacity unavailable in
the ICE cache so the scheduler routes around it
(:219-225), deletes the affected NodeClaim (cordon-and-drain), and deletes
the message. Parsing fans out over a worker pool in the reference (:119);
here messages are processed in one synchronous sweep per reconcile with the
same per-message isolation (a bad message never blocks the batch).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional

from karpenter_tpu.apis import NodeClaim, Node, labels as wk
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings
from karpenter_tpu import metrics
from karpenter_tpu.events import Recorder, WARNING
from karpenter_tpu.cloud.api import QueueAPI
from karpenter_tpu.kwok.cluster import Cluster

KIND_SPOT_INTERRUPTION = "spot-interruption"
KIND_SCHEDULED_CHANGE = "scheduled-change"
KIND_STATE_CHANGE = "state-change"
KIND_REBALANCE = "rebalance-recommendation"
KIND_NOOP = "noop"

# state-change states that warrant replacing the node
_TERMINAL_STATES = {"stopping", "stopped", "shutting-down", "terminated"}


@dataclass
class ParsedMessage:
    kind: str
    instance_id: str = ""
    zone: str = ""
    state: str = ""


def parse_message(body: str) -> ParsedMessage:
    """Message taxonomy (reference: parser.go:1-93 + messages/*): unknown
    shapes degrade to noop rather than erroring the batch."""
    try:
        doc = json.loads(body)
    except (json.JSONDecodeError, TypeError):
        return ParsedMessage(kind=KIND_NOOP)
    kind = doc.get("kind", "")
    instance_id = doc.get("instance_id", "")
    if kind == KIND_SPOT_INTERRUPTION and instance_id:
        return ParsedMessage(KIND_SPOT_INTERRUPTION, instance_id, doc.get("zone", ""))
    if kind == KIND_SCHEDULED_CHANGE and instance_id:
        return ParsedMessage(KIND_SCHEDULED_CHANGE, instance_id)
    if kind == KIND_STATE_CHANGE and instance_id:
        return ParsedMessage(KIND_STATE_CHANGE, instance_id, state=doc.get("state", ""))
    if kind == KIND_REBALANCE and instance_id:
        return ParsedMessage(KIND_REBALANCE, instance_id)
    return ParsedMessage(kind=KIND_NOOP)


class InterruptionController:
    def __init__(
        self,
        cluster: Cluster,
        queue: QueueAPI,
        unavailable: UnavailableOfferings,
        recorder: Optional[Recorder] = None,
    ):
        self.cluster = cluster
        self.queue = queue
        self.unavailable = unavailable
        self.recorder = recorder or Recorder()

    def reconcile(self, max_messages: int = 10) -> int:
        """One poll sweep; returns messages handled. The reference requeues
        immediately while messages remain (:114-136); callers loop."""
        handled = 0
        while True:
            batch = self.queue.receive(max_messages)
            if not batch:
                return handled
            for msg in batch:
                parsed = parse_message(msg.body)
                metrics.INTERRUPTION_RECEIVED.inc(kind=parsed.kind)
                try:
                    self._handle(parsed)
                except Exception as e:  # noqa: BLE001 -- per-message isolation:
                    # one bad message must not strand the rest of the batch
                    self.recorder.publish(
                        ParsedMessage(parsed.kind), "InterruptionHandlingFailed", str(e), type=WARNING
                    )
                finally:
                    self.queue.delete(msg.receipt)
                    metrics.INTERRUPTION_DELETED.inc()
                handled += 1

    # -- handling -----------------------------------------------------------
    def _claim_for_instance(self, instance_id: str) -> Optional[NodeClaim]:
        suffix = f"/{instance_id}"
        for claim in self.cluster.list(NodeClaim):
            if claim.provider_id.endswith(suffix):
                return claim
        return None

    def _handle(self, parsed: ParsedMessage) -> None:
        if parsed.kind == KIND_NOOP:
            return
        claim = self._claim_for_instance(parsed.instance_id)
        if claim is None:
            return
        if parsed.kind == KIND_STATE_CHANGE and parsed.state not in _TERMINAL_STATES:
            return
        if parsed.kind == KIND_REBALANCE:
            # advisory only: record, do not disrupt (reference treats
            # rebalance recommendations as events unless configured)
            self.recorder.publish(claim, "RebalanceRecommendation", "capacity may be reclaimed soon")
            return
        if parsed.kind == KIND_SPOT_INTERRUPTION:
            # the pool is being reclaimed: negative-cache it so the
            # scheduler stops offering this (type, zone, spot) pool (:219-225)
            itype = claim.instance_type
            zone = parsed.zone or claim.zone
            if itype and zone:
                self.unavailable.mark_unavailable(itype, zone, wk.CAPACITY_TYPE_SPOT, reason="SpotInterruption")
        self.recorder.publish(claim, "Interrupted", f"{parsed.kind} for {parsed.instance_id}", type=WARNING)
        if not claim.deleting:
            self.cluster.delete(NodeClaim, claim.metadata.name)
            metrics.NODECLAIMS_TERMINATED.inc(
                nodepool=claim.nodepool_name or "", reason="interruption"
            )
