"""Eviction gating against PodDisruptionBudgets.

Used at the two voluntary-disruption seams the reference routes through
the eviction API: node drain (controllers/termination.py) and disruption
candidacy (controllers/disruption.py). One guard instance snapshots PDB
state for one sweep and DECREMENTS its remaining allowance as evictions
are granted, so a single drain pass cannot evict five pods because each
looked individually admissible against the same snapshot.
"""
from __future__ import annotations

from typing import Dict, List

from karpenter_tpu.apis.pdb import PodDisruptionBudget
from karpenter_tpu.logging import get_logger


class PDBGuard:
    log = get_logger("pdb")

    def __init__(self, cluster):
        self.cluster = cluster
        self._pdbs: List[PodDisruptionBudget] = cluster.list(PodDisruptionBudget)
        self._remaining: Dict[str, int] = {}
        if self._pdbs:
            from karpenter_tpu.apis import Pod

            pods = cluster.list(Pod)
            for pdb in self._pdbs:
                matching = [p for p in pods if pdb.matches(p)]
                healthy = [p for p in matching if p.node_name and not p.deleting]
                self._remaining[pdb.metadata.name] = pdb.allowed_disruptions(
                    len(matching), len(healthy)
                )

    def try_evict(self, pod) -> bool:
        """Consume allowance from every matching PDB; False (and no
        consumption) when any budget is exhausted -- the eviction API's
        429 path."""
        matching = [p for p in self._pdbs if p.matches(pod)]
        exhausted = [p.metadata.name for p in matching if self._remaining[p.metadata.name] < 1]
        if exhausted:
            self.log.debug(
                "eviction deferred by disruption budget",
                pod=pod.metadata.name, budgets=exhausted,
            )
            return False
        for p in matching:
            self._remaining[p.metadata.name] -= 1
        return True
