"""Eviction gating against PodDisruptionBudgets.

Used at the two voluntary-disruption seams the reference routes through
the eviction API: node drain (controllers/termination.py) and disruption
candidacy (controllers/disruption.py). One guard instance snapshots PDB
state for one sweep and DECREMENTS its remaining allowance as evictions
are granted, so a single drain pass cannot evict five pods because each
looked individually admissible against the same snapshot.
"""
from __future__ import annotations

from typing import Dict, List

from karpenter_tpu.apis.pdb import PodDisruptionBudget
from karpenter_tpu.logging import get_logger


class PDBGuard:
    log = get_logger("pdb")

    def __init__(self, cluster):
        self.cluster = cluster
        self._pdbs: List[PodDisruptionBudget] = cluster.list(PodDisruptionBudget)
        self._remaining: Dict[str, int] = {}
        if self._pdbs:
            from karpenter_tpu.apis import Pod

            pods = cluster.list(Pod)
            for pdb in self._pdbs:
                matching = [p for p in pods if pdb.matches(p)]
                healthy = [p for p in matching if p.node_name and not p.deleting]
                self._remaining[pdb.metadata.name] = pdb.allowed_disruptions(
                    len(matching), len(healthy)
                )

    def try_evict(self, pod) -> bool:
        """Consume allowance from every matching PDB; False (and no
        consumption) when any budget is exhausted -- the eviction API's
        429 path."""
        return self.try_evict_all([pod])

    def try_evict_all(self, pods, charge_on_fail: bool = False) -> bool:
        """Atomic candidacy check: either EVERY pod's eviction is
        admissible and all allowances are consumed, or nothing is
        consumed. A per-pod try_evict loop that short-circuits on the
        first refusal leaves partial consumption behind, wrongly blocking
        sibling candidates whose pods share the same budget (ADVICE
        round 3). With charge_on_fail (the terminationGracePeriod
        force-drain carve-out, where the caller drains regardless of the
        verdict) a failing set still consumes its allowance -- possibly
        past exhaustion -- so later candidates in the pass see it spent."""
        needed = self._needed(pods)
        short = [name for name, n in needed.items() if self._remaining[name] < n]
        ok = not short
        if short:
            self.log.debug(
                "eviction deferred by disruption budget",
                pods=[p.metadata.name for p in pods][:5], budgets=short,
            )
        if ok or charge_on_fail:
            for name, n in needed.items():
                self._remaining[name] -= n
        return ok

    def charge(self, pods) -> None:
        """Unconditionally consume allowance (may go negative) without a
        verdict -- the force-drain accounting for a candidate that never
        reached the atomic check (e.g. failed reschedulability first)."""
        for name, n in self._needed(pods).items():
            self._remaining[name] -= n

    def _needed(self, pods) -> Dict[str, int]:
        """Allowances the eviction of `pods` consumes, per matching PDB --
        the one matching sweep both try_evict_all and charge rely on."""
        needed: Dict[str, int] = {}
        for pod in pods:
            for p in self._pdbs:
                if p.matches(pod):
                    needed[p.metadata.name] = needed.get(p.metadata.name, 0) + 1
        return needed
