"""Restart recovery sweep: replay the intent journal on every election win.

The other half of the crash-consistency protocol (karpenter_tpu/
journal.py): whatever the previous incarnation left mid-flight is exactly
the set of OPEN intents on the coordination bus, and this sweep -- run as
an on-election hook before the first controller sweep, on EVERY win, not
just the first -- replays each one to a safe state:

- launch intent, instance launched (found by its idempotency-token tag),
  claim present but status uncommitted  -> ADOPT: reflect the instance
  into the claim (CloudProvider.adopt) and commit, so the pod binds to
  capacity that already exists instead of a double-launch;
- launch intent, instance launched, claim gone/deleting -> the
  half-launch nobody wants: terminate the instance IMMEDIATELY (no
  60 s GC grace);
- launch intent, no instance -> the crash landed before the cloud
  mutation: drop the record (a surviving claim relaunches through the
  journaled lifecycle path, same token, idempotent);
- terminate intent -> re-issue the (idempotent) instance delete; a
  surviving claim finishes through the termination controller, a vanished
  one resolves here.

Every cloud mutation the sweep issues carries the NEW leader's fencing
epoch, so a deposed predecessor racing this sweep is rejected at the
cloud seam, not merged into it.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from karpenter_tpu import failpoints, metrics
from karpenter_tpu.apis import NodeClaim
from karpenter_tpu.apis.objects import ProvisioningIntent
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.logging import get_logger
from karpenter_tpu.utils import parse_instance_id


class RecoverySweepController:
    log = get_logger("recovery")

    def __init__(self, cluster, cloud_provider, journal, recorder=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.journal = journal
        self.recorder = recorder
        self.last_sweep: Dict[str, int] = {}

    def sweep(self) -> Dict[str, int]:
        """Replay every open intent; returns outcome counts. Idempotent
        and crash-safe itself: a crash mid-sweep leaves the unprocessed
        intents open for the NEXT sweep (the crash.recovery failpoint
        drills exactly that)."""
        t0 = time.perf_counter()
        outcomes: Dict[str, int] = {}
        open_intents = self.journal.open_intents()
        # ONE describe for the whole sweep, indexed by token tag: a
        # per-intent by_token() would issue k unbatched full-fleet
        # describes back-to-back right after a restart -- exactly the
        # burst that trips a throttled cloud during recovery
        token_index = self._token_index() if open_intents else {}
        for intent in open_intents:
            # crash site: the recovery sweep itself dies mid-replay; the
            # remaining intents must survive for the next incarnation
            failpoints.eval("crash.recovery")
            try:
                outcome = self.replay_intent(intent, token_index)
            except Exception as e:  # noqa: BLE001 -- per-intent isolation
                # a throttled/erroring cloud must cost THIS intent's
                # replay, not the new leader's whole first tick (the
                # intent stays open for the next sweep); OperatorCrashed
                # is a BaseException and still propagates -- the
                # crash-during-recovery drill depends on it
                outcome = "failed"
                metrics.RECOVERY_SWEEP_INTENTS.inc(outcome=outcome)
                self.log.warning(
                    "intent replay failed; left open for the next sweep",
                    intent=intent.metadata.name, error=f"{type(e).__name__}: {e}",
                )
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
        metrics.RECOVERY_SWEEP_DURATION.observe(time.perf_counter() - t0)
        self.last_sweep = outcomes
        if outcomes:
            self.log.info("recovery sweep replayed open intents", **outcomes)
        return outcomes

    def _token_index(self) -> Dict[str, object]:
        """Live cluster-owned instances keyed by intent-token tag, from
        ONE describe (the sweep's correlation read)."""
        from karpenter_tpu.apis.objects import INTENT_TOKEN_KEY

        out: Dict[str, object] = {}
        for inst in self.cloud_provider.instances.list():
            token = inst.tags.get(INTENT_TOKEN_KEY)
            if token and inst.state not in ("terminated", "shutting-down"):
                out[token] = inst
        return out

    def replay_intent(self, intent: ProvisioningIntent,
                      token_index: Optional[Dict[str, object]] = None) -> str:
        """Replay ONE open intent to a safe state; also the janitor entry
        point garbage collection uses for intents orphaned DURING a reign
        (a claim deleted out-of-band -- e.g. the kwok lifecycle reaping a
        killed instance's claim -- strands its open intent with no
        restart in sight). Without a prebuilt token index the correlation
        read falls back to a single tag-filtered describe."""
        if intent.op == ProvisioningIntent.OP_LAUNCH:
            outcome = self._replay_launch(intent, token_index)
        else:
            outcome = self._replay_terminate(intent)
        metrics.RECOVERY_SWEEP_INTENTS.inc(outcome=outcome)
        return outcome

    def _owner_of(self, inst) -> "NodeClaim | None":
        """The claim (if any) whose committed provider id points at this
        instance -- the guard every terminate/adopt decision below runs
        first: a misdealt merged fleet batch can cross instances between
        claims, and killing an instance ANOTHER claim owns would turn a
        bookkeeping mixup into a real outage."""
        return next(
            (
                c for c in self.cluster.list(NodeClaim)
                if c.provider_id and parse_instance_id(c.provider_id) == inst.id
            ),
            None,
        )

    def _terminate_half_launch(self, intent: ProvisioningIntent, inst) -> str:
        try:
            self.cloud_provider.instances.delete(inst.id)
        except NotFoundError:
            pass
        self.journal.resolve(intent, "terminated_half_launch")
        self.log.info(
            "terminated half-launched instance", instance=inst.id,
            intent=intent.metadata.name,
        )
        return "terminated_half_launch"

    # -- launch intents ------------------------------------------------------
    def _replay_launch(self, intent: ProvisioningIntent,
                       token_index: Optional[Dict[str, object]] = None) -> str:
        claim = self.cluster.try_get(NodeClaim, intent.claim_name)
        inst = (
            token_index.get(intent.token) if token_index is not None
            else self.cloud_provider.instances.by_token(intent.token)
        )
        if inst is None:
            # crash landed before the cloud mutation: nothing to adopt.
            # A surviving claim relaunches through the journaled lifecycle
            # path with the SAME reused intent name/token (idempotent), so
            # dropping the record here loses nothing.
            self.journal.resolve(intent, "dropped")
            return "dropped"
        owner = self._owner_of(inst)
        if owner is not None and owner.metadata.name != intent.claim_name:
            # a DIFFERENT claim committed this instance (misdealt merged
            # batch): it is accounted for -- the record just goes
            self.journal.resolve(intent, "dropped")
            return "dropped"
        if claim is None or claim.deleting:
            # half-launch: the instance exists, its claim does not (or is
            # on its way out). Terminate NOW -- this is the leak the GC
            # grace window used to carry for 60 s.
            return self._terminate_half_launch(intent, inst)
        if claim.provider_id:
            if parse_instance_id(claim.provider_id) != inst.id:
                # the claim committed against a DIFFERENT instance and
                # nothing owns this token's instance: a true half-launch
                return self._terminate_half_launch(intent, inst)
            # launch AND commit both landed; only the resolve was lost
            self.journal.resolve(intent, "already_committed")
            return "already_committed"
        # the canonical repair: launch committed, claim status did not
        self.cloud_provider.adopt(claim, inst)
        self.cluster.update(claim)
        self.journal.resolve(intent, "adopted")
        if self.recorder is not None:
            self.recorder.publish(
                claim, "Adopted",
                f"recovery sweep adopted instance {inst.id} (uncommitted launch)",
            )
        self.log.info(
            "adopted instance into uncommitted claim",
            nodeclaim=claim.metadata.name, instance=inst.id,
        )
        return "adopted"

    # -- terminate intents ---------------------------------------------------
    def _replay_terminate(self, intent: ProvisioningIntent) -> str:
        claim = self.cluster.try_get(NodeClaim, intent.claim_name)
        if intent.provider_id:
            try:
                # idempotent: already-terminated instances no-op inside the
                # provider's delete
                self.cloud_provider.instances.delete(
                    parse_instance_id(intent.provider_id))
            except NotFoundError:
                pass
        if claim is None:
            # finalizer removal already landed (or the claim never had
            # one); the record is the last survivor
            self.journal.resolve(intent, "orphan_terminated")
            return "orphan_terminated"
        # the claim survives: the level-triggered termination controller
        # finishes the teardown (finalizer, node object) and resolves the
        # intent itself -- leave it open so a crash BETWEEN here and that
        # tick still has its record
        return "resumed_termination"
