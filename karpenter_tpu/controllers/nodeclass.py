"""TPUNodeClass status reconciler.

Rebuilds the reconciler chain of pkg/controllers/nodeclass/controller.go:
97-163: Image -> CapacityReservation -> Subnet -> SecurityGroup ->
InstanceProfile -> Validation -> Readiness, each resolving cloud state into
status and setting its condition; the finalizer tears down owned instance
profiles and launch templates (:165-201). The hash sub-controller stamps
drift annotations (pkg/controllers/nodeclass/hash/controller.go).
"""
from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.apis import TPUNodeClass
from karpenter_tpu.apis.nodeclass import (
    COND_CAPACITY_RESERVATIONS_READY,
    COND_IMAGES_READY,
    COND_INSTANCE_PROFILE_READY,
    COND_READY,
    COND_SECURITY_GROUPS_READY,
    COND_SUBNETS_READY,
    COND_VALIDATION_SUCCEEDED,
    HASH_ANNOTATION,
    HASH_VERSION,
    HASH_VERSION_ANNOTATION,
    CapacityReservationStatus,
    ImageStatus,
    NODECLASS_CONDITIONS,
    SecurityGroupStatus,
    SubnetStatus,
)
from karpenter_tpu.cloud.api import ComputeAPI, IdentityAPI
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.providers.image import ImageProvider
from karpenter_tpu.providers.securitygroup import SecurityGroupProvider
from karpenter_tpu.providers.subnet import SubnetProvider
from karpenter_tpu.logging import ChangeMonitor, get_logger

TERMINATION_FINALIZER = "karpenter.tpu/termination"


class NodeClassController:
    log = get_logger("nodeclass")

    def __init__(
        self,
        cluster: Cluster,
        compute_api: ComputeAPI,
        identity_api: IdentityAPI,
        subnets: SubnetProvider,
        security_groups: SecurityGroupProvider,
        images: ImageProvider,
        launch_templates=None,
        clock=None,
        capacity_reservations=None,
        instance_profiles=None,
        cluster_name: str = "",
    ):
        from karpenter_tpu.providers.instanceprofile import InstanceProfileProvider

        from karpenter_tpu.cache.ttl import TTLCache

        # validation results are cloud-state dependent (profile existence),
        # so the cache is TTL'd like the reference's (validation.go): a
        # fixed spec re-validates every 10 minutes, picking up cloud-side
        # fixes without a spec edit or restart
        self._validation_cache = TTLCache(default_ttl=10 * 60.0, clock=clock)
        self.monitor = ChangeMonitor()  # per-instance: dedup state must not
        # leak across operators (tests, in-process restarts)
        self.cluster = cluster
        self.compute_api = compute_api
        self.identity_api = identity_api
        self.subnets = subnets
        self.security_groups = security_groups
        self.images = images
        self.launch_templates = launch_templates
        self.clock = clock
        self.capacity_reservations = capacity_reservations
        if instance_profiles is None:
            # managed-profile names embed the cluster name so two clusters
            # can never collide on (and finalize-delete) each other's
            # profiles -- a default provider without one would be a trap
            if not cluster_name:
                raise ValueError(
                    "NodeClassController needs either an InstanceProfileProvider "
                    "or a cluster_name to build one"
                )
            instance_profiles = InstanceProfileProvider(identity_api, cluster_name)
        self.instance_profiles = instance_profiles

    def reconcile_all(self) -> None:
        for nc in self.cluster.list(TPUNodeClass):
            self.reconcile(nc)

    def reconcile(self, nc: TPUNodeClass) -> None:
        if nc.deleting:
            self._finalize(nc)
            return
        if TERMINATION_FINALIZER not in nc.metadata.finalizers:
            nc.metadata.finalizers.append(TERMINATION_FINALIZER)
        self._reconcile_hash(nc)
        self._reconcile_images(nc)
        self._reconcile_capacity_reservations(nc)
        self._reconcile_subnets(nc)
        self._reconcile_security_groups(nc)
        self._reconcile_instance_profile(nc)
        self._reconcile_validation(nc)
        nc.status_conditions.compute_root(NODECLASS_CONDITIONS)
        ready = nc.status_conditions.is_true(nc.status_conditions.READY)
        # readiness transitions log once per flip (ChangeMonitor dedup)
        if self.monitor.has_changed(("ready", nc.metadata.name), ready):
            self.log.info("nodeclass readiness", nodeclass=nc.metadata.name, ready=ready)
        self.cluster.update(nc)

    # -- chain stages -------------------------------------------------------
    def _reconcile_hash(self, nc: TPUNodeClass) -> None:
        nc.metadata.annotations[HASH_ANNOTATION] = nc.static_hash()
        nc.metadata.annotations[HASH_VERSION_ANNOTATION] = HASH_VERSION

    def _reconcile_images(self, nc: TPUNodeClass) -> None:
        resolved = self.images.resolve(nc)
        if not resolved:
            nc.status_images = []
            nc.status_conditions.set_false(COND_IMAGES_READY, "ImagesNotFound", "no images matched selector terms")
            return
        nc.status_images = [
            ImageStatus(id=r.id, name=r.name, requirements=list(r.requirements)) for r in resolved
        ]
        nc.status_conditions.set_true(COND_IMAGES_READY)

    def _reconcile_capacity_reservations(self, nc: TPUNodeClass) -> None:
        if not nc.capacity_reservation_selector_terms:
            nc.status_capacity_reservations = []
            nc.status_conditions.set_true(COND_CAPACITY_RESERVATIONS_READY)
            return
        now = self.cluster.clock.now()
        out: List[CapacityReservationStatus] = []
        # read through the reservation provider when wired: its refresh
        # clears the in-memory launch/terminate deltas in the same motion,
        # so described counts and deltas never double-count
        if self.capacity_reservations is not None:
            reservations = self.capacity_reservations.list()
        else:
            reservations = self.compute_api.describe_capacity_reservations()
        for cr in reservations:
            if cr.end_time is not None and cr.end_time <= now:
                continue
            if not any(t.matches(id=cr.id, tags=cr.tags) for t in nc.capacity_reservation_selector_terms):
                continue
            out.append(
                CapacityReservationStatus(
                    id=cr.id,
                    instance_type=cr.instance_type,
                    zone=cr.zone,
                    owner_id=cr.owner_id,
                    reservation_type=cr.reservation_type,
                    state=cr.state,
                    end_time=cr.end_time,
                    available_count=cr.available_count,
                )
            )
        nc.status_capacity_reservations = out
        nc.status_conditions.set_true(COND_CAPACITY_RESERVATIONS_READY)

    def _reconcile_subnets(self, nc: TPUNodeClass) -> None:
        subnets = self.subnets.list(nc)
        if not subnets:
            nc.status_subnets = []
            nc.status_conditions.set_false(COND_SUBNETS_READY, "SubnetsNotFound", "no subnets matched selector terms")
            return
        nc.status_subnets = [SubnetStatus(s.id, s.zone, s.zone_id) for s in subnets]
        nc.status_conditions.set_true(COND_SUBNETS_READY)

    def _reconcile_security_groups(self, nc: TPUNodeClass) -> None:
        groups = self.security_groups.list(nc)
        if not groups:
            nc.status_security_groups = []
            nc.status_conditions.set_false(
                COND_SECURITY_GROUPS_READY, "SecurityGroupsNotFound", "no security groups matched selector terms"
            )
            return
        nc.status_security_groups = [SecurityGroupStatus(g.id, g.name) for g in groups]
        nc.status_conditions.set_true(COND_SECURITY_GROUPS_READY)

    def _reconcile_instance_profile(self, nc: TPUNodeClass) -> None:
        if nc.instance_profile:
            # user-supplied profile: reference it, never manage it
            nc.status_instance_profile = nc.instance_profile
            nc.status_conditions.set_true(COND_INSTANCE_PROFILE_READY)
            return
        nc.status_instance_profile = self.instance_profiles.ensure(nc.name, nc.role)
        nc.status_conditions.set_true(COND_INSTANCE_PROFILE_READY)

    def _reconcile_validation(self, nc: TPUNodeClass) -> None:
        """Launchability dry-run (reference: nodeclass/validation.go does
        cached dry-run authorization/launch checks, keyed by the nodeclass
        hash so they don't re-run every reconcile). Static spec invariants
        belong to admission (apis/validation.py); this stage owns the
        checks that need the CLOUD or the render pipeline:
          - userdata must render for the image family (bad user TOML would
            otherwise only fail at launch time)
          - a USER-specified instance profile must actually exist (the
            managed path creates its own)"""
        cache_key = nc.static_hash()
        hit, fresh = self._validation_cache.get(nc.metadata.name)
        if fresh and hit[0] == cache_key:
            ok, message = hit[1], hit[2]
            self._set_validation_condition(nc, ok, message)
            return
        problems = []
        from karpenter_tpu.providers.launchtemplate import bootstrap

        try:
            bootstrap.render(
                nc.image_family,
                cluster_name="validation",
                endpoint="https://validation.invalid",
                ca_bundle="validation",
                nodeclass=nc,
                labels={},
                taints=[],
                max_pods=None,
            )
        except ValueError as e:
            problems.append(f"userdata does not render: {e}")
        if nc.instance_profile:
            if self.identity_api.get_instance_profile(nc.instance_profile) is None:
                problems.append(f"instance profile {nc.instance_profile!r} not found")
        message = "; ".join(problems)
        self._validation_cache.set(nc.metadata.name, (cache_key, not problems, message))
        self._set_validation_condition(nc, not problems, message)

    @staticmethod
    def _set_validation_condition(nc: TPUNodeClass, ok: bool, message: str) -> None:
        if ok:
            nc.status_conditions.set_true(COND_VALIDATION_SUCCEEDED)
        else:
            nc.status_conditions.set_false(COND_VALIDATION_SUCCEEDED, "ValidationFailed", message)

    # -- finalizer ----------------------------------------------------------
    def _finalize(self, nc: TPUNodeClass) -> None:
        from karpenter_tpu.apis import NodeClaim

        blocking = [
            c
            for c in self.cluster.list(NodeClaim)
            if c.node_class_ref.name == nc.name and not c.deleting
        ]
        if blocking:
            return  # nodeclaims must drain first (reference blocks deletion)
        if self.launch_templates is not None:
            self.launch_templates.delete_all(nc)
        if not nc.instance_profile:  # only delete profiles we created
            self.instance_profiles.delete(nc.name)
        # a recreated nodeclass of the same name must re-validate
        self._validation_cache.delete(nc.metadata.name)
        self.cluster.remove_finalizer(nc, TERMINATION_FINALIZER)
