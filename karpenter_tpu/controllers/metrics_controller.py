"""NodeClaim metrics controller.

Rebuilds pkg/controllers/metrics/controller.go:33-106: export per-NodeClaim
cloud dimensions (instance type, zone, capacity type, nodepool, reservation)
as an info gauge, pruning series for claims that no longer exist so the
registry never leaks cardinality across claim churn.
"""
from __future__ import annotations

from typing import Dict, Tuple

from karpenter_tpu.apis import NodeClaim, NodePool, labels as wk
from karpenter_tpu import metrics
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.logging import get_logger

INSTANCE_INFO = metrics.REGISTRY.gauge(
    "karpenter_cloudprovider_instance_info",
    "Per-nodeclaim cloud instance dimensions (value is always 1).",
    labels=("nodeclaim", "instance_type", "zone", "capacity_type", "nodepool", "reservation_id"),
)

# generic status-condition metrics (reference: the operatorpkg
# status.Controller registered per watched kind,
# pkg/controllers/controllers.go:98): object counts aggregated by
# (kind, condition type, status, reason) -- bounded cardinality no matter
# how many objects churn -- plus a transition counter bumped whenever an
# object's condition changes status between sweeps.
STATUS_CONDITION_COUNT = metrics.REGISTRY.gauge(
    "karpenter_status_condition_count",
    "Objects per (kind, condition type, condition status, reason).",
    labels=("kind", "type", "condition_status", "reason"),
)
STATUS_CONDITION_TRANSITIONS = metrics.REGISTRY.counter(
    "karpenter_status_condition_transitions_total",
    "Condition status changes observed between metric sweeps.",
    labels=("kind", "type", "condition_status"),
)


class MetricsController:
    log = get_logger("metrics")

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._series: Dict[str, Tuple] = {}  # claim name -> label values
        # (kind, object name, condition type) -> status, for transitions
        self._cond_last: Dict[Tuple[str, str, str], str] = {}
        self._cond_series: set = set()  # live (kind, type, status, reason) keys

    def _labels_of(self, claim: NodeClaim) -> Dict[str, str]:
        l = claim.metadata.labels
        return {
            "nodeclaim": claim.metadata.name,
            "instance_type": l.get(wk.INSTANCE_TYPE_LABEL, ""),
            "zone": l.get(wk.ZONE_LABEL, ""),
            "capacity_type": l.get(wk.CAPACITY_TYPE_LABEL, ""),
            "nodepool": l.get(wk.NODEPOOL_LABEL, ""),
            "reservation_id": l.get(wk.LABEL_CAPACITY_RESERVATION_ID, ""),
        }

    def reconcile_all(self) -> int:
        live = {}
        for claim in self.cluster.list(NodeClaim):
            if not claim.launched():
                continue
            labels = self._labels_of(claim)
            live[claim.metadata.name] = tuple(labels[n] for n in INSTANCE_INFO.label_names)
            INSTANCE_INFO.set(1.0, **labels)
        # prune series for claims that disappeared or changed dimensions --
        # remove, never zero, so claim churn cannot grow cardinality
        label_names = INSTANCE_INFO.label_names
        for name, values in list(self._series.items()):
            if live.get(name) != values:
                INSTANCE_INFO.remove(**dict(zip(label_names, values)))
        if len(live) != len(self._series):
            self.log.debug(
                "instance info series", series=len(live), pruned=len(self._series) - len(live)
            )
        self._series = live
        self._sweep_conditions()
        self._aggregate_pool_status()
        return len(live)

    def _aggregate_pool_status(self) -> None:
        """NodePool.status.resources: aggregate capacity of the pool's
        launched claims (the core's nodepool counter controller --
        `kubectl get nodepool` shows it; limits are judged against live
        usage elsewhere, this is the observability surface). DELETING
        claims still count: a draining instance holds real (billed)
        capacity until it is actually gone, and INSTANCE_INFO above uses
        the same membership. Updated only on change so steady state
        writes nothing."""
        totals: Dict[str, Resources] = {}
        for claim in self.cluster.list(NodeClaim):
            pool_name = claim.nodepool_name
            if not pool_name or not claim.launched():
                continue
            totals[pool_name] = totals.get(pool_name, Resources()) + claim.capacity
        from karpenter_tpu.kube.client import ApiError, NotFound as HttpNotFound
        from karpenter_tpu.kwok.cluster import Conflict, NotFound

        for pool in self.cluster.list(NodePool):
            want = totals.get(pool.metadata.name, Resources())
            if pool.status_resources != want:
                pool.status_resources = want
                try:
                    self.cluster.update(pool)
                except (Conflict, NotFound, HttpNotFound):
                    pass  # stale read vs a concurrent writer/deleter: next sweep retries
                except (ApiError, OSError) as e:  # kube mode: a racing delete
                    # or apiserver hiccup (HTTP error or transport failure --
                    # socket/ssl errors are OSErrors) must not abort the whole
                    # operator tick (ADVICE round 4); the sweep is idempotent
                    # next tick
                    self.log.warning("pool status update failed", error=str(e))


    def _sweep_conditions(self) -> None:
        """Aggregate every object's status conditions into the bounded
        (kind, type, status, reason) gauge and count transitions."""
        from karpenter_tpu.apis import NodePool, TPUNodeClass

        counts: Dict[Tuple[str, str, str, str], int] = {}
        seen: Dict[Tuple[str, str, str], str] = {}
        for kind in (NodeClaim, TPUNodeClass, NodePool):
            for obj in self.cluster.list(kind):
                for cond in obj.status_conditions.all():
                    key = (kind.KIND, cond.type, cond.status, cond.reason or "")
                    counts[key] = counts.get(key, 0) + 1
                    # creation timestamp in the key: a deleted object and a
                    # same-named successor are different objects, and the
                    # successor's first status must not read as a transition
                    tkey = (kind.KIND, obj.metadata.name, obj.metadata.creation_timestamp, cond.type)
                    seen[tkey] = cond.status
                    prev = self._cond_last.get(tkey)
                    if prev is not None and prev != cond.status:
                        STATUS_CONDITION_TRANSITIONS.inc(
                            kind=kind.KIND, type=cond.type, condition_status=cond.status
                        )
        self._cond_last = seen
        label_names = ("kind", "type", "condition_status", "reason")
        for key, n in counts.items():
            STATUS_CONDITION_COUNT.set(float(n), **dict(zip(label_names, key)))
        # prune series whose (kind,type,status,reason) disappeared so the
        # gauge never reports stale objects
        for key in self._cond_series - set(counts):
            STATUS_CONDITION_COUNT.remove(**dict(zip(label_names, key)))
        self._cond_series = set(counts)
