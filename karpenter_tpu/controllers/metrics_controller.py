"""NodeClaim metrics controller.

Rebuilds pkg/controllers/metrics/controller.go:33-106: export per-NodeClaim
cloud dimensions (instance type, zone, capacity type, nodepool, reservation)
as an info gauge, pruning series for claims that no longer exist so the
registry never leaks cardinality across claim churn.
"""
from __future__ import annotations

from typing import Dict, Tuple

from karpenter_tpu.apis import NodeClaim, labels as wk
from karpenter_tpu import metrics
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger

INSTANCE_INFO = metrics.REGISTRY.gauge(
    "karpenter_cloudprovider_instance_info",
    "Per-nodeclaim cloud instance dimensions (value is always 1).",
    labels=("nodeclaim", "instance_type", "zone", "capacity_type", "nodepool", "reservation_id"),
)


class MetricsController:
    log = get_logger("metrics")

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._series: Dict[str, Tuple] = {}  # claim name -> label values

    def _labels_of(self, claim: NodeClaim) -> Dict[str, str]:
        l = claim.metadata.labels
        return {
            "nodeclaim": claim.metadata.name,
            "instance_type": l.get(wk.INSTANCE_TYPE_LABEL, ""),
            "zone": l.get(wk.ZONE_LABEL, ""),
            "capacity_type": l.get(wk.CAPACITY_TYPE_LABEL, ""),
            "nodepool": l.get(wk.NODEPOOL_LABEL, ""),
            "reservation_id": l.get(wk.LABEL_CAPACITY_RESERVATION_ID, ""),
        }

    def reconcile_all(self) -> int:
        live = {}
        for claim in self.cluster.list(NodeClaim):
            if not claim.launched():
                continue
            labels = self._labels_of(claim)
            live[claim.metadata.name] = tuple(labels[n] for n in INSTANCE_INFO.label_names)
            INSTANCE_INFO.set(1.0, **labels)
        # prune series for claims that disappeared or changed dimensions --
        # remove, never zero, so claim churn cannot grow cardinality
        label_names = INSTANCE_INFO.label_names
        for name, values in list(self._series.items()):
            if live.get(name) != values:
                INSTANCE_INFO.remove(**dict(zip(label_names, values)))
        if len(live) != len(self._series):
            self.log.debug(
                "instance info series", series=len(live), pruned=len(self._series) - len(live)
            )
        self._series = live
        return len(live)
