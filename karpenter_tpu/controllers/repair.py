"""Node auto-repair controller.

The consumer of CloudProvider.repair_policies() (VERDICT round 2, item 8;
reference: /root/reference/pkg/cloudprovider/cloudprovider.go:264-305 defines
the policies, the core's node-repair controller consumes them): a node whose
condition matches a policy's (type, status) is TOLERATED for the policy's
window -- transient kubelet or accelerator blips must not churn nodes --
then force-replaced by deleting its NodeClaim (the termination controller
taints, drains, and terminates; the provisioner replaces the evicted pods).

Unhealthy windows are measured on the cluster's injectable clock from when
this controller first OBSERVES the matching condition (the same discipline
as kwok/lifecycle.py: wall-clock condition transition stamps cannot be
compared against a fake clock). A condition that heals -- or changes to a
different non-matching status -- resets its window.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from karpenter_tpu.apis import NodeClaim, Node
from karpenter_tpu import metrics
from karpenter_tpu.events import Recorder, WARNING
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger


class NodeRepairController:
    log = get_logger("repair")

    def __init__(self, cluster: Cluster, cloud_provider, recorder: Optional[Recorder] = None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.recorder = recorder or Recorder()
        self.policies = list(cloud_provider.repair_policies())
        # (node, condition type, status) -> first observation time
        self._first_seen: Dict[Tuple[str, str, str], float] = {}

    def _claim_for_node(self, node: Node) -> Optional[NodeClaim]:
        for claim in self.cluster.list(NodeClaim):
            if claim.node_name == node.metadata.name or (
                node.provider_id and claim.provider_id == node.provider_id
            ):
                return claim
        return None

    def reconcile(self) -> int:
        """One sweep; returns the number of nodes sent for replacement."""
        now = self.cluster.clock.now()
        live_keys = set()
        repaired = 0
        for node in self.cluster.list(Node):
            if node.deleting:
                continue
            for policy in self.policies:
                cond = node.status_conditions.get(policy.condition_type)
                if cond is None or cond.status != policy.condition_status:
                    continue
                key = (node.metadata.name, policy.condition_type, policy.condition_status)
                live_keys.add(key)
                first = self._first_seen.setdefault(key, now)
                if now - first < policy.toleration_seconds:
                    continue
                claim = self._claim_for_node(node)
                if claim is None or claim.deleting:
                    continue
                self.recorder.publish(
                    node,
                    "NodeRepairing",
                    f"{policy.condition_type}={policy.condition_status} for "
                    f"{now - first:.0f}s (tolerated {policy.toleration_seconds:.0f}s)",
                    type=WARNING,
                )
                self.cluster.delete(NodeClaim, claim.metadata.name)
                metrics.NODECLAIMS_TERMINATED.inc(
                    nodepool=claim.nodepool_name or "", reason="repair"
                )
                self.log.warning(
                    "repairing unhealthy node",
                    node=node.metadata.name,
                    nodeclaim=claim.metadata.name,
                    condition=policy.condition_type,
                    status=policy.condition_status,
                    unhealthy_seconds=round(now - first, 1),
                )
                repaired += 1
                break  # one replacement per node per sweep
        # healed / departed conditions reset their windows
        self._first_seen = {k: t for k, t in self._first_seen.items() if k in live_keys}
        return repaired
