"""Provider-refresh controllers.

Rebuilds the periodic refresh reconcilers of pkg/controllers/providers/:
- instancetype: 12h catalog + offerings refresh (controller.go:43-59)
- instancetype/capacity: learn true node memory from registered nodes
  (capacity/controller.go:1-133)
- pricing: 12h on-demand + spot refresh (pricing/controller.go:43-59)
- version: periodic cluster-version discovery (version/controller.go)
- ssm invalidation: drop image-alias cache entries when images churn
  (ssm/invalidation/controller.go:55-89)
- capacityreservation/expiration + capacitytype: expire capacity blocks and
  flip reserved claims to on-demand when their reservation lapses
  (capacityreservation/*.go)
"""
from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis import NodeClaim, Node, TPUNodeClass, labels as wk
from karpenter_tpu.cache.ttl import Clock
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import ChangeMonitor, get_logger

REFRESH_INTERVAL = 12 * 3600.0


class _Periodic:
    def __init__(self, clock: Clock, interval: float = REFRESH_INTERVAL):
        self.clock = clock
        self.interval = interval
        self._last: Optional[float] = None

    def due(self) -> bool:
        now = self.clock.now()
        if self._last is None or now - self._last >= self.interval:
            self._last = now
            return True
        return False


class InstanceTypeRefreshController(_Periodic):
    log = get_logger("providers.instancetype")

    def __init__(self, provider, clock: Clock, interval: float = REFRESH_INTERVAL):
        super().__init__(clock, interval)
        self.provider = provider
        self.monitor = ChangeMonitor()  # per-instance dedup state

    def reconcile(self) -> bool:
        if not self.due():
            return False
        self.provider.update_instance_types()
        self.provider.update_instance_type_offerings()
        # log only when the catalog actually changed (reference dedupes the
        # same message with a ChangeMonitor, instancetype.go:267-271); the
        # provider's seq counters bump only on observed change
        seq = (self.provider.instance_types_seq, self.provider.offerings_seq)
        if self.monitor.has_changed("catalog", seq):
            self.log.info(
                "instance types updated",
                instance_types_seq=seq[0], offerings_seq=seq[1],
            )
        return True


class PricingRefreshController(_Periodic):
    log = get_logger("providers.pricing")

    def __init__(self, pricing, clock: Clock, interval: float = REFRESH_INTERVAL):
        super().__init__(clock, interval)
        self.pricing = pricing
        self.monitor = ChangeMonitor()  # per-instance dedup state

    def reconcile(self) -> bool:
        if not self.due():
            return False
        self.pricing.update_on_demand_pricing()
        self.pricing.update_spot_pricing()
        snapshot = self.pricing.snapshot_hash()
        if self.monitor.has_changed("pricing", snapshot):
            self.log.info("pricing updated", snapshot=snapshot)
        return True


class DiscoveredCapacityController:
    """Learns actual (instance type, image) memory from registered nodes
    into the catalog provider's discovered-capacity cache."""

    def __init__(self, cluster: Cluster, instance_types):
        self.cluster = cluster
        self.instance_types = instance_types

    def reconcile_all(self) -> int:
        from karpenter_tpu.scheduling import resources as res

        updated = 0
        for node in self.cluster.list(Node):
            if not node.ready:
                continue
            claim = self.cluster.nodeclaim_for_node(node)
            if claim is None or not claim.image_id:
                continue
            itype = node.instance_type
            mem = node.capacity.get(res.MEMORY)
            if itype and mem:
                self.instance_types.update_capacity_from_node(itype, claim.image_id, mem)
                updated += 1
        return updated


class VersionController(_Periodic):
    """Periodic cluster-version refresh through the version provider
    (reference: providers/version/controller.go drives version.Provider)."""

    def __init__(self, version_provider, clock: Clock, interval: float = 5 * 60.0):
        super().__init__(clock, interval)
        self.version_provider = version_provider

    @property
    def version(self) -> str:
        return self.version_provider.get()

    def reconcile(self) -> bool:
        if not self.due():
            return False
        self.version_provider.invalidate()
        self.version_provider.get()
        return True


class ImageCacheInvalidationController:
    """Drops the param-store (image alias) cache when resolved images no
    longer exist upstream, so new launches pick fresh images."""

    def __init__(self, images, compute_api):
        self.images = images
        self.compute_api = compute_api

    def reconcile(self) -> int:
        live = {i.id for i in self.compute_api.describe_images()}
        return self.images.invalidate_missing(live)


class CapacityTypeController:
    """Flips claims on expired/vanished reservations to on-demand accounting
    (reference: capacityreservation/capacitytype/controller.go:1-157).
    Expiry is judged directly against the cloud's reservation list -- by the
    time this runs, the nodeclass controller may already have scrubbed the
    lapsed entry from status, so status cannot be the source of truth."""

    def __init__(self, cluster: Cluster, reservations):
        self.cluster = cluster
        self.reservations = reservations  # CapacityReservationProvider (cached)

    def reconcile_all(self) -> int:
        now = self.cluster.clock.now()
        flipped = 0
        claims_with_reservation = [
            (claim, claim.metadata.labels.get(wk.LABEL_CAPACITY_RESERVATION_ID))
            for claim in self.cluster.list(NodeClaim)
        ]
        if not any(rid for _, rid in claims_with_reservation):
            return 0  # no reserved claims: skip the cloud read entirely
        live = {
            cr.id
            for cr in self.reservations.list()
            if cr.state == "active" and (cr.end_time is None or cr.end_time > now)
        }
        for claim, rid in claims_with_reservation:
            if rid and rid not in live:
                claim.metadata.labels[wk.CAPACITY_TYPE_LABEL] = wk.CAPACITY_TYPE_ON_DEMAND
                del claim.metadata.labels[wk.LABEL_CAPACITY_RESERVATION_ID]
                node = self.cluster.node_for_nodeclaim(claim)
                if node is not None:
                    node.metadata.labels[wk.CAPACITY_TYPE_LABEL] = wk.CAPACITY_TYPE_ON_DEMAND
                    node.metadata.labels.pop(wk.LABEL_CAPACITY_RESERVATION_ID, None)
                    self.cluster.update(node)
                self.cluster.update(claim)
                flipped += 1
        return flipped


# expiration lead: start draining capacity-block claims this long before the
# reservation's hard end so pods reschedule while capacity still exists
# (reference: capacityreservation/expiration/controller.go)
CAPACITY_BLOCK_EXPIRATION_LEAD = 10 * 60.0


class CapacityReservationExpirationController:
    """Initiates graceful NodeClaim deletion for capacity-BLOCK claims whose
    reservation is about to end (reference:
    capacityreservation/expiration/controller.go:1-135). Capacity blocks
    hard-reclaim their instances at end time, so waiting for the
    capacitytype flip (which handles default ODCRs) would strand pods; this
    controller drains ahead of the cliff instead."""

    def __init__(self, cluster: Cluster, reservations, lead: float = CAPACITY_BLOCK_EXPIRATION_LEAD):
        self.cluster = cluster
        self.reservations = reservations
        self.lead = lead

    def reconcile_all(self) -> int:
        now = self.cluster.clock.now()
        expiring_blocks = {
            cr.id: cr.end_time
            for cr in self.reservations.list()
            if cr.reservation_type == "capacity-block" and cr.end_time is not None
        }
        if not expiring_blocks:
            return 0
        expired = 0
        for claim in self.cluster.list(NodeClaim):
            if claim.deleting:
                continue
            rid = claim.metadata.labels.get(wk.LABEL_CAPACITY_RESERVATION_ID)
            end = expiring_blocks.get(rid)
            if end is not None and now >= end - self.lead:
                # cordon-and-drain via the termination flow
                self.cluster.delete(NodeClaim, claim.metadata.name)
                expired += 1
        return expired
