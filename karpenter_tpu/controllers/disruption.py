"""Disruption controller: expiration, drift, emptiness, consolidation.

Rebuilds the single-deprovisioning-controller design the reference documents
(designs/deprovisioning.md; consolidation mechanics in
designs/consolidation.md -- HOT LOOP #3 in SURVEY.md section 3.2) around
the same decision order and safety rails:

- candidates: initialized, past consolidate-after, pods all evictable
  (owned, no do-not-disrupt), nodepool disruption budgets respected
- reasons, in priority order: Expired -> Drifted -> Empty -> Underutilized
- consolidation evaluates candidates in ascending *disruption cost*
  (pods x (1 + deletion-cost + priority/1e6), weighted by remaining
  lifetime), then simulates rescheduling the candidate's pods against the
  rest of the cluster:
    deletion     -- pods fit on existing capacity
    replacement  -- pods fit on existing capacity + ONE strictly cheaper
                    new node (spot-to-spot guarded by the feature gate)
- stabilization: no consolidation while pods are pending or capacity is
  still materializing (the reference waits for cluster-state sync)

Execution is delegated to the termination controller by deleting the
NodeClaim (taint -> drain -> terminate), mirroring Delete at
pkg/cloudprovider/cloudprovider.go:209-220.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis import (
    CONSOLIDATION_WHEN_EMPTY,
    NodeClaim,
    NodePool,
    Node,
    Pod,
    TPUNodeClass,
    labels as wk,
)
from karpenter_tpu.apis.nodeclaim import COND_CONSOLIDATABLE, COND_DRIFTED, COND_EMPTY
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import CloudError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.logging import get_logger
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.scheduling import resources as res
from karpenter_tpu.solver.oracle import ExistingNode, Scheduler

MIN_NODE_LIFETIME = 5 * 60.0  # consolidation waits for PVC binding etc.
# brownout rung 1 (overload.BrownoutController): with a device evaluator
# wired, the sweep DOWNGRADES to a bounded singleton-only device pass
# over this many cheapest-to-disrupt candidates instead of standing down
# entirely -- one dispatch, no drift/replacement/multi-node host work
BROWNOUT_MAX_CANDIDATES = 16
# spot->spot consolidation keeps at least this many cheaper instance-type
# options on the replacement (upstream's flexibility minimum: replacing a
# spot node with a single cheaper spot type would trade price for a much
# higher re-interruption probability)
MIN_TYPES_SPOT_TO_SPOT = 15

REASON_EXPIRED = "Expired"
REASON_DRIFTED = "Drifted"
REASON_EMPTY = "Empty"
REASON_UNDERUTILIZED = "Underutilized"


@dataclass
class Candidate:
    claim: NodeClaim
    node: Node
    # None for a STANDALONE claim (no NodePool): eligible for the
    # claim-level reasons (expiration, drift) but not the pool-policy
    # reasons (emptiness, consolidation), as in the core
    nodepool: Optional[NodePool]
    pods: List[Pod]
    price: float
    disruption_cost: float
    # node/claim-level karpenter.sh/do-not-disrupt: blocks the GRACEFUL
    # voluntary reasons (drift, emptiness, consolidation); expiration is a
    # forceful method upstream and proceeds regardless
    do_not_disrupt: bool = False


class DisruptionController:
    log = get_logger("disruption")

    def __init__(
        self,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        pricing,
        feature_gates: Optional[dict] = None,
        evaluator=None,
        recorder=None,
        brownout=None,
        repack=None,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.pricing = pricing
        self.feature_gates = feature_gates or {}
        self.recorder = recorder  # optional events.Recorder
        # optional overload.BrownoutController: consolidation/disruption
        # is the brownout ladder's FIRST shed (rung 1) -- under sustained
        # tick-deadline pressure the whole sweep stands down (counted)
        # until the ladder recovers hysteretically
        self.brownout = brownout
        # batched device evaluator (solver/consolidate.py): all candidate
        # sets are judged in one dispatch; candidates with stateful
        # constraints fall back to the per-candidate oracle simulation
        self.evaluator = evaluator
        # optional convex.repack.RepackOracle: fleet-wide regret scoring
        # proposes candidate sets the local prefix/pair enumerations miss;
        # proposals only NOMINATE -- stage 6 judges each through the same
        # simulate/price differential as the controller's own enumerations
        self.repack = repack
        self.last_decisions: List[Tuple[str, str]] = []  # (claim name, reason)
        # per-sweep stats for the flight recorder (obs/flight.py): sweep
        # mode (full / bounded / shed), wall ms, candidate-set counts by
        # enumeration kind, and the engine's dispatch route
        self.last_sweep_stats: dict = {}
        # candidate-set counts accumulated across the CURRENT pass's
        # batched dispatches (singleton batch + prefix/pair batch +
        # mid-pass re-judges)
        self._pass_set_counts: Dict[str, int] = {}
        # nodes disrupted in the CURRENT pass: their NodeClaims are deleting
        # but the Node objects are not yet marked (termination runs later),
        # so simulations must exclude them explicitly or later candidates
        # would repack onto capacity that is already going away
        self._pass_disrupted: List[str] = []
        # per-pass pool/catalog snapshot (None outside a pass: helpers
        # called directly, e.g. from tests, fetch fresh)
        self._pass_pools: Optional[List[NodePool]] = None
        self._pass_catalogs: Optional[Dict[str, list]] = None
        self._pass_pdb_guard = None
        self._pass_daemon_overhead: Optional[Dict[str, Resources]] = None
        # per-pass claim/class snapshot for volume lowering (built once in
        # _reconcile; helpers called directly, e.g. from tests, build fresh)
        self._pass_vol_index = None
        # pods whose simulation exclusion was already logged this pass
        self._pass_blocked_logged: set = set()
        # (budget id, minute) -> bool; bounded, cleared on overflow
        self._budget_active_memo: Dict[tuple, bool] = {}

    # -- helpers ------------------------------------------------------------
    def _price_of(self, claim: NodeClaim) -> float:
        it = claim.instance_type
        if not it:
            return float("inf")
        if claim.capacity_type == wk.CAPACITY_TYPE_SPOT and claim.zone:
            p, ok = self.pricing.spot_price(it, claim.zone)
        else:
            p, ok = self.pricing.on_demand_price(it)
        return p if ok else float("inf")

    def _disruption_cost(self, claim: NodeClaim, pods: Sequence[Pod]) -> float:
        """designs/consolidation.md 'Selecting Nodes for Consolidation':
        pod count + deletion-cost + priority, weighted by lifetime left."""
        cost = 0.0
        for p in pods:
            cost += 1.0 + p.deletion_cost() + p.priority / 1e6
        lifetime_factor = 1.0
        if claim.expire_after:
            age = self.cluster.clock.now() - claim.metadata.creation_timestamp
            lifetime_factor = max(0.0, 1.0 - age / claim.expire_after)
        return cost * lifetime_factor

    def _candidates(self) -> List[Candidate]:
        now = self.cluster.clock.now()
        out = []
        for claim in self.cluster.list(NodeClaim):
            if claim.deleting or not claim.initialized():
                continue
            node = self.cluster.node_for_nodeclaim(claim)
            if node is None or node.deleting or node.unschedulable:
                continue
            dnd = (
                node.metadata.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true"
                or claim.metadata.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true"
            )
            pool_name = claim.nodepool_name
            pool = self.cluster.try_get(NodePool, pool_name) if pool_name else None
            if pool_name and pool is None:
                continue  # pool-owned claim whose pool is mid-delete
            pods = self.cluster.pods_on_node(node.metadata.name)
            out.append(
                Candidate(
                    claim=claim,
                    node=node,
                    nodepool=pool,
                    pods=pods,
                    price=self._price_of(claim),
                    disruption_cost=self._disruption_cost(claim, pods),
                    do_not_disrupt=dnd,
                )
            )
        return out

    def _budget_allows(self, pool: Optional[NodePool], reason: str, disrupting: Dict[str, int], totals: Dict[str, int]) -> bool:
        if pool is None:
            return True  # standalone claims carry no pool budgets
        total = totals.get(pool.name, 0)
        current = disrupting.get(pool.name, 0)
        now = self.cluster.clock.now()
        for budget in pool.disruption.budgets:
            if budget.reasons is not None and reason not in budget.reasons:
                continue
            # activity memoized per (budget, minute): the window scan walks
            # duration/60 cron checks, and _budget_allows runs per
            # candidate -- hundreds of candidates x a 24h window would be
            # ~10^5 redundant parses per pass
            akey = (budget.schedule, budget.duration, int(now // 60))
            active = self._budget_active_memo.get(akey)
            if active is None:
                active = self._budget_active_memo[akey] = budget.active(now)
                if len(self._budget_active_memo) > 256:
                    self._budget_active_memo.clear()
            if not active:
                continue  # scheduled budget outside its window
            if current + 1 > budget.allowed(total):
                return False
        return True

    def _all_pods_evictable(self, pods: Sequence[Pod], charge_always: bool = False) -> bool:
        """Every pod is controller-replaced, consented (no do-not-disrupt),
        AND currently evictable under its PodDisruptionBudgets -- a node
        whose drain would immediately stall on an exhausted budget is not
        a voluntary-disruption candidate this pass (the budget freeing up
        later makes it one again). ONE guard serves the whole pass
        (_pass_pdb_guard): disrupting a claim does not unbind its pods, so
        per-call guards would let several nodes sharing one allowance all
        pass candidacy and then jointly stall the drain; the shared guard
        consumes allowance across candidates exactly as the drains will.
        Accounting is ATOMIC per candidate (try_evict_all): a rejected
        candidate consumes nothing, so it cannot block a sibling node
        sharing the same budget (ADVICE round 3). With charge_always (the
        terminationGracePeriod carve-out, where the caller force-drains
        regardless of the verdict) a failing candidate still charges its
        pods, so a later candidate cannot double-book allowance the forced
        drain will consume; the charge is conservative when a downstream
        gate (disruption budget, failed simulation) then skips the drift
        -- siblings just defer to the next pass."""
        from karpenter_tpu.controllers.pdb_guard import PDBGuard

        if self._pass_pools is not None:
            # inside a pass: one shared guard
            guard = self._pass_pdb_guard
            if guard is None:
                guard = self._pass_pdb_guard = PDBGuard(self.cluster)
        else:
            # helper called directly (tests): fresh snapshot
            guard = PDBGuard(self.cluster)
        if all(p.reschedulable() for p in pods):
            return guard.try_evict_all(pods, charge_on_fail=charge_always)
        if charge_always:
            guard.charge(pods)
        return False

    # -- simulation ---------------------------------------------------------
    def _vol_index(self):
        from karpenter_tpu.apis.storage import VolumeIndex

        if self._pass_vol_index is not None:
            return self._pass_vol_index
        return VolumeIndex.from_cluster(self.cluster)

    def _effective_in_flight(self, vol_index) -> List[Pod]:
        """Resolved in-flight pods (see _in_flight_pods). Vol-blocked ones
        are DROPPED, not vetoes: they are unschedulable with or without
        the disruption under evaluation, and letting one frozen PVC
        freeze consolidation cluster-wide starves every other candidate
        (ADVICE round 4). Each drop is logged once per pass so the
        exclusion is operator-visible."""
        from karpenter_tpu.apis.storage import effective_pods

        pods, blocked = effective_pods(self._in_flight_pods(), vol_index)
        for name, reason in blocked.items():
            if name not in self._pass_blocked_logged:
                self._pass_blocked_logged.add(name)
                self.log.warning(
                    "in-flight pod excluded from disruption simulation",
                    pod=name, reason=reason,
                )
        return pods

    def _other_nodes(self, excluded: Sequence[str]) -> List[ExistingNode]:
        out = []
        vol_index = self._vol_index()
        live = [
            n for n in self.cluster.list(Node)
            if n.metadata.name not in excluded
            and not n.deleting and not n.unschedulable and n.ready
        ]
        # ONE pod pass for every node's usage (node_usage per node is
        # O(all pods) per call on index-less stores -- round 5)
        usage_map = self.cluster.node_usage_map(
            [n.metadata.name for n in live], vol_index)
        for node in live:
            out.append(
                ExistingNode(
                    name=node.metadata.name,
                    labels=dict(node.metadata.labels),
                    allocatable=node.allocatable,
                    taints=list(node.taints),
                    used=usage_map[node.metadata.name],
                )
            )
        return out

    def _pods_by_node(self) -> Dict[str, List[Pod]]:
        out: Dict[str, List[Pod]] = {}
        for p in self.cluster.list(Pod):
            if p.node_name:
                out.setdefault(p.node_name, []).append(p)
        return out

    def _in_flight_pods(self) -> List[Pod]:
        """Reschedulable pods still bound to nodes disrupted earlier in this
        pass. They have not rebound yet, so later simulations must place
        them ALONGSIDE the candidate's pods -- otherwise two candidates
        would each claim the same surviving headroom (ADVICE round 1)."""
        by_node = self._pods_by_node()
        return [
            p
            for n in self._pass_disrupted
            for p in by_node.get(n, [])
            if p.reschedulable()
        ]

    def _simulate(self, candidates: Sequence[Candidate], allow_new_node: bool):
        """Can every pod on the candidate set reschedule elsewhere (plus at
        most one new node when allow_new_node)? Returns (ok, new_groups)."""
        from karpenter_tpu.apis.storage import effective_pods

        excluded = [c.node.metadata.name for c in candidates] + list(self._pass_disrupted)
        # volume-backed pods re-simulate with their attach counts and
        # bound-zone pins (claims are bound by now: the pod ran), so
        # consolidation never plans a move a zonal volume forbids. A
        # vol-blocked pod VETOES only when it runs on a candidate: evicting
        # it would strand a pod that cannot rebind. In-flight pods from
        # nodes disrupted earlier this pass are dropped instead of vetoing
        # -- they are unschedulable with or without this disruption, and
        # letting one frozen PVC freeze all consolidation cluster-wide
        # starves every other candidate (ADVICE round 4).
        vol_index = self._vol_index()
        in_flight = self._effective_in_flight(vol_index)
        own = [p for c in candidates for p in c.pods if p.reschedulable()]
        own, vol_blocked = effective_pods(own, vol_index)
        if vol_blocked:
            return False, []
        pods = in_flight + own
        nodepools, pass_catalogs = self._pool_context()
        catalogs: Dict[str, list] = {}
        zones: set = set()
        if allow_new_node:
            catalogs = pass_catalogs
            for items in catalogs.values():
                for it in items:
                    for o in it.available_offerings():
                        zones.add(o.zone)
        sched = Scheduler(
            nodepools=nodepools if allow_new_node else [],
            instance_types=catalogs,
            existing_nodes=self._other_nodes(excluded),
            pods_by_node={k: v for k, v in self._pods_by_node().items() if k not in excluded},
            nodepool_usage={p.name: self.cluster.nodepool_usage(p.name) for p in nodepools},
            zones=zones,
            daemon_overhead=self._daemon_overhead(nodepools),
        )
        result = sched.schedule(pods)
        if result.unschedulable:
            return False, []
        if not allow_new_node and result.new_groups:
            return False, []
        if allow_new_node and len(result.new_groups) > 1:
            return False, []
        return True, result.new_groups

    # -- reconcile ----------------------------------------------------------
    def reconcile(self, max_disruptions: int = 1) -> List[Tuple[str, str]]:
        """One disruption pass; returns [(claim, reason)] acted on."""
        import time as _time

        from karpenter_tpu import metrics, tracing

        bounded = False
        if self.brownout is not None and self.brownout.sheds_disruption():
            if self.evaluator is None:
                # brownout ladder rung 1, no device engine wired: the
                # sweep stands down entirely -- the per-candidate oracle
                # simulations are exactly the host-side cost a pressured
                # tick cannot afford. Nothing is lost: candidates
                # re-judge once the ladder recovers.
                metrics.OVERLOAD_SKIPPED_SWEEPS.inc(stage="disruption")
                tracing.annotate(disruption="shed-brownout")
                self.last_decisions = []
                self.last_sweep_stats = {"mode": "shed", "consolidation_ms": 0.0}
                return []
            # with the batched device engine the sweep is cheap enough to
            # LEAVE ON during brownout: rung 1 downgrades to a bounded
            # singleton-only device pass (one dispatch over the cheapest
            # candidates, deletion verdicts only) instead of standing down
            bounded = True
        t0 = _time.perf_counter()
        self._pass_set_counts = {}
        mode = "bounded" if bounded else "full"
        try:
            with tracing.span("disruption"):
                if bounded:
                    metrics.DISRUPTION_DEVICE_BOUNDED_SWEEPS.inc()
                    tracing.annotate(disruption="brownout-bounded")
                    return self._reconcile_bounded(max_disruptions)
                return self._reconcile(max_disruptions)
        finally:
            self._pass_pools, self._pass_catalogs = None, None
            self._pass_pdb_guard = None
            self._pass_daemon_overhead = None
            # drop the claim snapshot too: helpers called between passes
            # (tests, ad-hoc verdicts) must see the live cluster, not the
            # last pass's volume world
            self._pass_vol_index = None
            self._pass_blocked_logged = set()
            elapsed = _time.perf_counter() - t0
            metrics.DISRUPTION_EVAL_DURATION.observe(elapsed)
            if self.evaluator is None:
                path = "oracle"
            elif not self._pass_set_counts:
                # THIS pass made no device dispatch; last_dispatch would
                # report a previous sweep's route
                path = "none"
            else:
                path = getattr(self.evaluator, "last_dispatch", {}).get("path", "none")
            self.last_sweep_stats = {
                "mode": mode,
                "consolidation_ms": round(elapsed * 1e3, 3),
                "sets": dict(self._pass_set_counts),
                "path": path,
            }

    def _daemon_overhead(self, pools) -> Dict[str, "Resources"]:
        """Per-pool fresh-node daemonset reserve, SNAPSHOT per pass like
        _pool_context: every candidate in one pass must be judged against
        the same node sizing (a mid-pass DaemonSet change applies next
        pass)."""
        if self._pass_daemon_overhead is not None:
            return self._pass_daemon_overhead
        from karpenter_tpu.apis import DaemonSet
        from karpenter_tpu.apis.daemonset import overhead_by_pool

        out = overhead_by_pool(self.cluster.list(DaemonSet), pools)
        if self._pass_pools is not None:
            self._pass_daemon_overhead = out
        return out

    def _pool_context(self) -> Tuple[List[NodePool], Dict[str, list]]:
        """(live pools, their catalogs). Inside a pass this is the snapshot
        taken at pass start -- catalogs change on the 12h refresh cadence,
        not mid-pass, so verdict re-judges must not re-fetch them."""
        if self._pass_pools is not None and self._pass_catalogs is not None:
            return self._pass_pools, self._pass_catalogs
        pools = [p for p in self.cluster.list(NodePool) if not p.deleting]
        catalogs: Dict[str, list] = {}
        for pool in pools:
            try:
                catalogs[pool.name] = self.cloud_provider.get_instance_types(pool)
            except CloudError:
                catalogs[pool.name] = []
        return pools, catalogs

    def _pass_setup(self) -> None:
        """Per-pass snapshot state shared by the full and bounded sweeps
        (torn down by reconcile's finally)."""
        from karpenter_tpu.apis.storage import VolumeIndex

        self.last_decisions = []
        self._pass_disrupted = []
        self._pass_blocked_logged = set()
        self._pass_vol_index = VolumeIndex.from_cluster(self.cluster)
        self._pass_pools, self._pass_catalogs = None, None
        self._pass_pdb_guard = None
        self._pass_daemon_overhead = None
        self._pass_pools, self._pass_catalogs = self._pool_context()

    def _disruption_counts(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """(claims currently disrupting, claim totals) per pool -- the
        budget denominators."""
        disrupting: Dict[str, int] = {}
        totals: Dict[str, int] = {}
        for claim in self.cluster.list(NodeClaim):
            if claim.nodepool_name:
                totals[claim.nodepool_name] = totals.get(claim.nodepool_name, 0) + 1
                if claim.deleting:
                    disrupting[claim.nodepool_name] = disrupting.get(claim.nodepool_name, 0) + 1
        return disrupting, totals

    def _consolidatable(self, candidates: Sequence[Candidate]) -> List[Candidate]:
        """Pool-owned, consenting, past the consolidation age gate, in
        ascending disruption-cost order -- the candidate assembly both
        sweep modes share."""
        now = self.cluster.clock.now()
        acted = [n for n, _ in self.last_decisions]
        return sorted(
            (
                c
                for c in candidates
                if not c.do_not_disrupt
                and c.claim.metadata.name not in acted
                and c.nodepool is not None  # pool-policy reasons only
                and now - c.claim.metadata.creation_timestamp
                >= max(MIN_NODE_LIFETIME, c.nodepool.disruption.consolidate_after)
            ),
            key=lambda c: c.disruption_cost,
        )

    def _reconcile_bounded(self, max_disruptions: int) -> List[Tuple[str, str]]:
        """The brownout rung-1 sweep: candidate assembly capped at the
        BROWNOUT_MAX_CANDIDATES cheapest-to-disrupt nodes, ONE singleton-
        only device dispatch with no replacement context, emptiness +
        deletion verdicts applied under the usual budget/PDB gates. No
        drift, no expiration, no replacement launches, no multi-node work
        -- the host-side cost is candidate assembly plus verdict
        application, which is exactly what a pressured tick can afford."""
        self._pass_setup()
        if self.cluster.pending_pods():
            return self.last_decisions
        disrupting, totals = self._disruption_counts()
        consolidatable = self._consolidatable(self._candidates())[:BROWNOUT_MAX_CANDIDATES]
        verdicts = self._device_verdicts(consolidatable, replacement=False)
        decided = len(self.last_decisions)
        for i, c in enumerate(consolidatable):
            if len(self.last_decisions) >= max_disruptions:
                break
            if len(self.last_decisions) != decided:
                # same re-judge discipline as the full sweep: an earlier
                # disruption this pass consumed surviving headroom, and a
                # stale verdict would double-book it -- one fresh bounded
                # dispatch per decision, still O(max_disruptions) cheap
                decided = len(self.last_decisions)
                verdicts = self._device_verdicts(
                    consolidatable[i:], replacement=False)
            reschedulable = [p for p in c.pods if p.owner_kind != "Node"]
            if not reschedulable:
                c.claim.status_conditions.set_true(COND_EMPTY)
                if self._budget_allows(c.nodepool, REASON_EMPTY, disrupting, totals):
                    self._disrupt(c, REASON_EMPTY, disrupting)
                continue
            if c.nodepool.disruption.consolidation_policy == CONSOLIDATION_WHEN_EMPTY:
                continue
            v = verdicts.get(c.claim.metadata.name)
            if v is None or not v.can_delete:
                continue
            if not self._all_pods_evictable(c.pods):
                continue
            if not self._budget_allows(c.nodepool, REASON_UNDERUTILIZED, disrupting, totals):
                continue
            c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
            self._disrupt(c, REASON_UNDERUTILIZED, disrupting)
        return self.last_decisions

    def _reconcile(self, max_disruptions: int) -> List[Tuple[str, str]]:
        self._pass_setup()
        disrupting, totals = self._disruption_counts()
        candidates = self._candidates()
        now = self.cluster.clock.now()

        # 1) expiration (forceful; budget-exempt in the core's model for
        #    expired-by-spec, but we respect budgets like modern karpenter)
        for c in candidates:
            if len(self.last_decisions) >= max_disruptions:
                return self.last_decisions
            if c.claim.expire_after is not None and now - c.claim.metadata.creation_timestamp >= c.claim.expire_after:
                if self._budget_allows(c.nodepool, REASON_EXPIRED, disrupting, totals):
                    self._disrupt(c, REASON_EXPIRED, disrupting)

        # 2) drift (graceful: requires replacement simulation)
        for c in candidates:
            if len(self.last_decisions) >= max_disruptions:
                return self.last_decisions
            if c.claim.metadata.name in [n for n, _ in self.last_decisions]:
                continue
            if c.do_not_disrupt:
                continue
            drift = self._drift_reason(c)
            if not drift:
                continue
            # With a terminationGracePeriod on the claim, drift proceeds
            # even when the evictability check fails (do-not-disrupt pods
            # or exhausted budgets): the grace force-drain guarantees
            # completion, exactly the upstream carve-out. charge_always
            # makes that forced drain's pods charge the shared per-pass
            # PDB guard even on a failing verdict, so a later candidate
            # cannot double-book the same allowance and stall its drain.
            has_grace = c.claim.termination_grace_period is not None
            evictable = self._all_pods_evictable(c.pods, charge_always=has_grace)
            if evictable or has_grace:
                if not self._budget_allows(c.nodepool, REASON_DRIFTED, disrupting, totals):
                    continue
                c.claim.status_conditions.set_true(COND_DRIFTED, drift)
                ok, groups = self._simulate([c], allow_new_node=True)
                if ok:
                    self._replace_then_disrupt(c, groups, REASON_DRIFTED, disrupting)

        # 3) emptiness + 4) consolidation share the stabilization gate
        if self.cluster.pending_pods():
            return self.last_decisions
        consolidatable = self._consolidatable(candidates)
        verdicts = self._device_verdicts(consolidatable)
        decided = len(self.last_decisions)
        for i, c in enumerate(consolidatable):
            if len(self.last_decisions) >= max_disruptions:
                return self.last_decisions
            if len(self.last_decisions) != decided:
                # a disruption earlier in this pass consumed surviving
                # headroom; stale verdicts would double-book it (ADVICE
                # round 1) -- re-judge the remaining candidates in one
                # fresh batched dispatch
                decided = len(self.last_decisions)
                verdicts = self._device_verdicts(consolidatable[i:])
            reschedulable = [p for p in c.pods if p.owner_kind != "Node"]
            if not reschedulable:
                c.claim.status_conditions.set_true(COND_EMPTY)
                if self._budget_allows(c.nodepool, REASON_EMPTY, disrupting, totals):
                    self._disrupt(c, REASON_EMPTY, disrupting)
                continue
            if c.nodepool.disruption.consolidation_policy == CONSOLIDATION_WHEN_EMPTY:
                continue
            if not self._all_pods_evictable(c.pods):
                continue
            if not self._budget_allows(c.nodepool, REASON_UNDERUTILIZED, disrupting, totals):
                continue
            v = verdicts.get(c.claim.metadata.name)
            if v is not None:
                # device verdict: deletion decisions are oracle-equivalent
                # (differential tests); replacement is a pre-filter -- the
                # oracle re-derives the actual group before acting
                if v.can_delete:
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                    self._disrupt(c, REASON_UNDERUTILIZED, disrupting)
                    continue
                if not self._device_replacement_cheaper(c, v):
                    continue
                ok, groups = self._simulate([c], allow_new_node=True)
                if ok and groups and self._replacement_cheaper(c, groups):
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                    self._replace_then_disrupt(c, groups, REASON_UNDERUTILIZED, disrupting)
                continue
            # oracle path: deletion first, then single-node replacement
            ok, _ = self._simulate([c], allow_new_node=False)
            if ok:
                c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._disrupt(c, REASON_UNDERUTILIZED, disrupting)
                continue
            ok, groups = self._simulate([c], allow_new_node=True)
            if ok and groups and self._replacement_cheaper(c, groups):
                c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._replace_then_disrupt(c, groups, REASON_UNDERUTILIZED, disrupting)

        # 5) multi-node consolidation: try deleting the k cheapest-to-disrupt
        #    candidates together; when pure deletion fails, collapse them
        #    into ONE cheaper replacement node, and when no PREFIX of the
        #    disruption-cost order works, try underutilized PAIRS outside
        #    it (two nodes whose pods only fold together)
        #    (reference: designs/consolidation.md:5-36 node replacement)
        if len(self.last_decisions) < max_disruptions and len(consolidatable) >= 2:
            remaining = [
                c
                for c in consolidatable
                if c.claim.metadata.name not in [n for n, _ in self.last_decisions]
                and self._all_pods_evictable(c.pods)
            ]
            device_verdicts = self._device_prefix_verdicts(remaining)
            subset = self._largest_deletable_prefix(remaining, device_verdicts)
            if subset:
                # budgets re-checked per disruption as the count grows;
                # deleting a prefix of the simulated subset is safe
                # (fewer exclusions than simulated only adds capacity)
                for c in subset:
                    if not self._budget_allows(c.nodepool, REASON_UNDERUTILIZED, disrupting, totals):
                        break
                    self._disrupt(c, REASON_UNDERUTILIZED, disrupting)
            elif len(remaining) >= 2:
                acted = self._multi_node_replacement(
                    remaining, device_verdicts, disrupting, totals)
                if not acted:
                    self._pair_consolidation(
                        remaining, device_verdicts, disrupting, totals,
                        max_disruptions)

        # 6) global repack oracle (convex tier, opt-in): fleet-wide regret
        #    scoring over the survivors nominates the sets whose members
        #    sit too far apart in disruption-cost order for the prefix/pair
        #    enumerations to ever co-select; each nomination passes the
        #    SAME simulate/price differential before anything is touched
        if self.repack is not None and len(self.last_decisions) < max_disruptions:
            survivors = [
                c
                for c in consolidatable
                if c.claim.metadata.name not in [n for n, _ in self.last_decisions]
                and not c.do_not_disrupt
                and c.nodepool.disruption.consolidation_policy != CONSOLIDATION_WHEN_EMPTY
                and self._all_pods_evictable(c.pods)
            ]
            self._repack_consolidation(
                survivors, disrupting, totals, max_disruptions)
        return self.last_decisions

    def _repack_consolidation(
        self,
        remaining: List[Candidate],
        disrupting: Dict[str, int],
        totals: Dict[str, int],
        max_disruptions: int,
    ) -> bool:
        """Stage 6: judge the repack oracle's nominated candidate sets
        with the controller's own machinery -- pure deletion when the
        set's pods fold into the survivors, else ONE cheaper replacement
        node. The oracle only nominates; the simulate/price differential
        decides, so a bad proposal costs planning time, never capacity."""
        if not remaining:
            return False
        try:
            sets = self.repack.propose(
                remaining, self._pass_pools or [], self._pass_catalogs)
        except Exception:  # noqa: BLE001 -- an oracle fault costs this
            # sweep its stage-6 nominations only; the local enumerations
            # above already ran (OperatorCrashed is BaseException and
            # still propagates)
            self.log.warning("repack oracle failed; skipping stage 6")
            return False
        acted = False
        for idx in sets:
            if len(self.last_decisions) >= max_disruptions:
                break
            decided = {n for n, _ in self.last_decisions}
            sel = [remaining[i] for i in idx]
            if any(c.claim.metadata.name in decided for c in sel):
                continue
            if not self._budget_allows_set(sel, disrupting, totals):
                continue
            self._pass_set_counts["repack"] = (
                self._pass_set_counts.get("repack", 0) + 1)
            ok, _ = self._simulate(sel, allow_new_node=False)
            if ok:
                for c in sel:
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                    self._disrupt(c, REASON_UNDERUTILIZED, disrupting)
                acted = True
                continue
            ok, groups = self._simulate(sel, allow_new_node=True)
            if ok and groups and self._replacement_cheaper(sel, groups):
                for c in sel:
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._replace_then_disrupt(
                    sel, groups, REASON_UNDERUTILIZED, disrupting)
                acted = True
        return acted

    def _multi_node_replacement(
        self,
        remaining: List[Candidate],
        device_verdicts: Optional[Dict[object, object]],
        disrupting: Dict[str, int],
        totals: Dict[str, int],
    ) -> bool:
        """Replace N underutilized nodes with one cheaper node: largest
        prefix (by the disruption-cost order) whose pods fit the survivors
        plus ONE new node strictly cheaper than the prefix's aggregate
        price. `device_verdicts` is the per-prefix batch already dispatched
        for the deletion decision (replacement context included); the oracle
        re-derives the replacement group before acting. True when a
        replacement launched (the pair stage only runs when nothing did)."""
        for k in range(len(remaining), 1, -1):
            prefix = remaining[:k]
            if device_verdicts is not None:
                v = device_verdicts.get(k)
                if v is None or not self._device_replacement_cheaper_multi(prefix, v):
                    continue
            # the whole prefix drains behind one launch, so budget-check it
            # as a unit: members from one pool count against that pool's
            # budget cumulatively
            if not self._budget_allows_set(prefix, disrupting, totals):
                continue
            ok, groups = self._simulate(prefix, allow_new_node=True)
            if ok and groups and self._replacement_cheaper(prefix, groups):
                for c in prefix:
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._replace_then_disrupt(prefix, groups, REASON_UNDERUTILIZED, disrupting)
                return True
        return False

    def _budget_allows_set(self, cands: List[Candidate], disrupting: Dict[str, int],
                           totals: Dict[str, int]) -> bool:
        """Budget-check a candidate set as a UNIT (the whole set drains
        behind one decision): members from one pool count against that
        pool's budget cumulatively."""
        trial = dict(disrupting)
        for c in cands:
            if not self._budget_allows(c.nodepool, REASON_UNDERUTILIZED, trial, totals):
                return False
            trial[c.nodepool.name] = trial.get(c.nodepool.name, 0) + 1
        return True

    def _pair_consolidation(
        self,
        remaining: List[Candidate],
        device_verdicts: Optional[Dict[object, object]],
        disrupting: Dict[str, int],
        totals: Dict[str, int],
        max_disruptions: int,
    ) -> bool:
        """Underutilized pairs OUTSIDE the prefix order: two nodes whose
        pods only fold together (or onto one cheaper replacement) even
        though no contiguous disruption-cost prefix worked -- the
        multi-node shape the reference's descending-k loop cannot see.
        Pairs come from solver/disrupt.enumerate_pairs over the cheapest
        candidates (bounded window, (0, 1) excluded: that set IS the k=2
        prefix already judged). The device batch pre-filters; deletion
        verdicts apply directly (exact equivalence) while replacement
        re-derives through the oracle -- and the oracle-only path runs
        the same pair order through the same simulations, so decisions
        agree with and without the engine."""
        from karpenter_tpu.solver.disrupt import enumerate_pairs

        def delete_pair(pair: List[Candidate]) -> None:
            # _budget_allows_set above already proved both members fit the
            # pool budgets with exactly this accumulation
            for c in pair:
                c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._disrupt(c, REASON_UNDERUTILIZED, disrupting)

        def replace_pair(pair: List[Candidate]) -> bool:
            ok, groups = self._simulate(pair, allow_new_node=True)
            if ok and groups and self._replacement_cheaper(pair, groups):
                for c in pair:
                    c.claim.status_conditions.set_true(COND_CONSOLIDATABLE)
                self._replace_then_disrupt(pair, groups, REASON_UNDERUTILIZED, disrupting)
                return True
            return False

        for i, j in enumerate_pairs(len(remaining)):
            if len(self.last_decisions) >= max_disruptions:
                return False
            pair = [remaining[i], remaining[j]]
            if not self._budget_allows_set(pair, disrupting, totals):
                continue
            if device_verdicts is not None:
                v = device_verdicts.get(("pair", i, j))
                if v is None:
                    continue
                if v.can_delete:
                    # deletion decisions are oracle-equivalent
                    # (differential tests): act without re-simulation
                    delete_pair(pair)
                    return True
                if self._device_replacement_cheaper_multi(pair, v) and replace_pair(pair):
                    return True
                continue
            # oracle path: same order, same checks
            ok, _ = self._simulate(pair, allow_new_node=False)
            if ok:
                delete_pair(pair)
                return True
            if replace_pair(pair):
                return True
        return False

    def _device_prefix_verdicts(self, remaining: List[Candidate]):
        """Multi-node candidate-set batch, ONE device dispatch with
        replacement context: a SetVerdict for every prefix (keyed k =
        2..N of the disruption-cost order) AND every underutilized pair
        (keyed ("pair", i, j) from solver/disrupt.enumerate_pairs) --
        serves the deletion decisions, the multi-node replacement price
        gate, and the pair stage. None when any pod is device-ineligible
        (the oracle loops judge the same sets themselves)."""
        if self.evaluator is None or len(remaining) < 2:
            return None
        from karpenter_tpu import metrics
        from karpenter_tpu.apis.storage import effective_pods
        from karpenter_tpu.solver.disrupt import device_eligible, enumerate_pairs

        # same volume lowering as _device_verdicts: raw claim-carrying
        # pods would under-state attach demand in the prefix repacks
        vol_index = self._vol_index()
        resched = {}
        for c in remaining:
            eff, blocked = effective_pods(
                [p for p in c.pods if p.reschedulable()], vol_index
            )
            if blocked:
                return None
            resched[c.claim.metadata.name] = eff
        # vol-blocked in-flight pods are dropped, same as _simulate: they
        # must not push every candidate onto the oracle path either
        in_flight = self._effective_in_flight(vol_index)
        if not all(
            device_eligible(resched[c.claim.metadata.name]) for c in remaining
        ) or not device_eligible(in_flight):
            return None

        def one_set(members: List[Candidate]):
            return (
                in_flight + [p for c in members for p in resched[c.claim.metadata.name]],
                [c.node.metadata.name for c in members],
            )

        sets = []
        keys: List[object] = []
        for k in range(2, len(remaining) + 1):
            sets.append(one_set(remaining[:k]))
            keys.append(k)
        n_prefix = len(sets)
        for i, j in enumerate_pairs(len(remaining)):
            sets.append(one_set([remaining[i], remaining[j]]))
            keys.append(("pair", i, j))
        self._pass_set_counts["prefix"] = (
            self._pass_set_counts.get("prefix", 0) + n_prefix)
        self._pass_set_counts["pair"] = (
            self._pass_set_counts.get("pair", 0) + len(sets) - n_prefix)
        metrics.DISRUPTION_DEVICE_SETS.inc(n_prefix, kind="prefix")
        metrics.DISRUPTION_DEVICE_SETS.inc(len(sets) - n_prefix, kind="pair")
        pools, catalogs = self._pool_context()
        verdicts = self.evaluator.evaluate(
            self._other_nodes(list(self._pass_disrupted)), sets,
            pools=pools, catalogs=catalogs,
            daemon_overhead=self._daemon_overhead(pools),
        )
        return dict(zip(keys, verdicts))

    def _device_replacement_cheaper_multi(self, prefix: List[Candidate], v) -> bool:
        import math

        price = v.replace_price
        if any(
            c.claim.capacity_type == wk.CAPACITY_TYPE_SPOT for c in prefix
        ) and not self.feature_gates.get("SpotToSpotConsolidation"):
            price = v.replace_od_price
        return math.isfinite(price) and price < sum(c.price for c in prefix)

    def _largest_deletable_prefix(
        self, remaining: List[Candidate],
        device_verdicts: Optional[Dict[object, object]] = None,
    ) -> List[Candidate]:
        """Largest k such that candidates[0:k] can all be deleted with their
        pods repacked on surviving capacity. `device_verdicts` is the
        per-prefix batch from _device_prefix_verdicts (one dispatch serves
        deletion AND the replacement price gate); None falls back to the
        oracle's descending-k simulation loop."""
        if len(remaining) < 2:
            return []
        if device_verdicts is not None:
            for k in range(len(remaining), 1, -1):  # largest k first
                v = device_verdicts.get(k)
                if v is not None and v.can_delete:
                    return remaining[:k]
            return []
        k = len(remaining)
        while k >= 2:
            subset = remaining[:k]
            ok, _ = self._simulate(subset, allow_new_node=False)
            if ok:
                return subset
            k -= 1
        return []

    def _device_verdicts(self, consolidatable: Sequence[Candidate],
                         replacement: bool = True) -> Dict[str, object]:
        """One batched device evaluation of every eligible single-node
        candidate; ineligible candidates (stateful constraints) are absent
        from the result and take the oracle path. ``replacement=False``
        (the brownout-bounded sweep) skips the per-pool replacement
        context entirely: deletion verdicts only, minimum host encode."""
        if self.evaluator is None or not consolidatable:
            return {}
        from karpenter_tpu import metrics
        from karpenter_tpu.apis.storage import effective_pods
        from karpenter_tpu.solver.disrupt import device_eligible

        # volume-backed pods evaluate as their RESOLVED scheduling copies
        # (attach counts on the volume axis, bound zones as selector pins
        # -- apis/storage): the raw objects would under-state demand and
        # let can_delete overcommit surviving nodes' attach budgets.
        # Survivor headroom already counts attachments (_other_nodes ->
        # node_usage), so both sides of the repack see the same axis.
        vol_index = self._vol_index()
        # vol-blocked in-flight pods are dropped (logged), same as
        # _simulate: one frozen PVC must not disable the fast path
        in_flight = self._effective_in_flight(vol_index)
        if in_flight and not device_eligible(in_flight):
            # in-flight pods carry stateful constraints the evaluator does
            # not model; every remaining candidate takes the oracle path
            return {}
        eligible: List[Candidate] = []
        sets = []
        for c in consolidatable:
            resched = [p for p in c.pods if p.reschedulable()]
            resched, blocked = effective_pods(resched, vol_index)
            if blocked or not resched or not device_eligible(resched):
                continue  # unresolvable claims etc.: the oracle path decides
            eligible.append(c)
            # in-flight pods repack jointly with the candidate's: the
            # verdict only says can_delete when BOTH fit the survivors
            sets.append((in_flight + resched, [c.node.metadata.name]))
        if not eligible:
            return {}
        self._pass_set_counts["singleton"] = (
            self._pass_set_counts.get("singleton", 0) + len(sets))
        metrics.DISRUPTION_DEVICE_SETS.inc(len(sets), kind="singleton")
        if replacement:
            pools, catalogs = self._pool_context()
            verdicts = self.evaluator.evaluate(
                self._other_nodes(list(self._pass_disrupted)), sets,
                pools=pools, catalogs=catalogs,
                daemon_overhead=self._daemon_overhead(pools),
            )
        else:
            verdicts = self.evaluator.evaluate(
                self._other_nodes(list(self._pass_disrupted)), sets,
            )
        return {c.claim.metadata.name: v for c, v in zip(eligible, verdicts)}

    def _device_replacement_cheaper(self, c: Candidate, v) -> bool:
        """Price gate over the device verdict, mirroring
        _replacement_cheaper's spot-to-spot feature gating."""
        price = v.replace_price
        if c.claim.capacity_type == wk.CAPACITY_TYPE_SPOT and not self.feature_gates.get(
            "SpotToSpotConsolidation"
        ):
            price = v.replace_od_price
        return price < c.price

    def _drift_reason(self, c: Candidate) -> Optional[str]:
        if c.nodepool is None:
            # standalone claim: only the cloud-side drift kinds apply
            # (incl. the nodeclass static hash the lifecycle controller
            # stamps); there is no pool to drift against
            try:
                return self.cloud_provider.is_drifted(c.claim)
            except CloudError:
                return None
        # nodepool static drift via stamped hash
        pool_hash = c.claim.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION)
        if pool_hash is not None and pool_hash != c.nodepool.static_hash():
            return "NodePoolDrifted"
        # dynamic requirement drift (the upstream NodeRequirement kind):
        # requirements are deliberately OUTSIDE the static hash -- a pool
        # whose requirements changed only drifts the claims whose concrete
        # labels the CURRENT requirements no longer admit. Same machinery
        # and absence semantics as scheduling compatibility everywhere
        # else: only well-known labels may be undefined on the claim side,
        # so a newly demanded custom label drifts pre-existing nodes.
        from karpenter_tpu.scheduling import Requirements

        labels = {**c.claim.metadata.labels, **c.node.metadata.labels}
        if not Requirements.from_labels(labels).compatible(
            c.nodepool.requirements(), allow_undefined=wk.WELL_KNOWN_LABELS
        ):
            return "NodeRequirementDrifted"
        try:
            return self.cloud_provider.is_drifted(c.claim)
        except CloudError:
            return None

    def _replacement_cheaper(self, cands, groups) -> bool:
        """Replacement must be strictly cheaper than the candidate set's
        aggregate price; spot->spot consolidation is feature-gated
        (reference gates SpotToSpotConsolidation). Accepts one Candidate or
        a list (multi-node consolidation compares against the sum)."""
        if isinstance(cands, Candidate):
            cands = [cands]
        if not groups:
            return True
        any_spot = any(c.claim.capacity_type == wk.CAPACITY_TYPE_SPOT for c in cands)
        od_only = any_spot and not self.feature_gates.get("SpotToSpotConsolidation")

        def group_price(g) -> tuple:
            """(price, capacity type) of the cheapest offering the group
            can actually LAUNCH: restricted to the group's narrowed
            zone/captype requirements (a group whose pods demand on-demand
            must not be priced at spot), and to on-demand under the
            spot->spot gate."""
            zreq = g.requirements.get(wk.ZONE_LABEL)
            creq = g.requirements.get(wk.CAPACITY_TYPE_LABEL)
            best = float("inf")
            best_ct = None
            for it in g.instance_types:
                for o in it.available_offerings():
                    if zreq is not None and not zreq.matches(o.zone):
                        continue
                    if creq is not None and not creq.matches(o.capacity_type):
                        continue
                    if od_only and o.capacity_type != wk.CAPACITY_TYPE_ON_DEMAND:
                        continue
                    if o.price < best:
                        best = o.price
                        best_ct = o.capacity_type
            return best, best_ct

        priced = [group_price(g) for g in groups]
        if any(p == float("inf") for p, _ in priced):
            return False  # a group with no launchable offering cannot be priced
        total_new = sum(p for p, _ in priced)
        budget = sum(c.price for c in cands)
        # the SUM of the replacement groups' launch prices must beat the
        # candidate set's aggregate -- comparing only the cheapest group
        # against the full budget (the pre-r4 check) let a multi-group
        # replacement whose total exceeded the candidates' pass (ADVICE
        # round 3)
        if total_new >= budget:
            return False
        if any_spot and not od_only:
            # spot->spot: EVERY group whose cheapest launchable offering is
            # spot must keep >= 15 cheaper launchable spot options, or the
            # savings buy re-interruption churn; one well-diversified group
            # must not ungate its siblings. "Cheaper" is judged against the
            # group's RESIDUAL budget (candidate-set price minus what the
            # other groups cost), not the aggregate -- for single-node
            # consolidation this is exactly the candidate node's price.
            # Groups launching on-demand are exempt.
            def cheaper_spot_types(g, target: float) -> int:
                zreq = g.requirements.get(wk.ZONE_LABEL)
                creq = g.requirements.get(wk.CAPACITY_TYPE_LABEL)
                n = 0
                for it in g.instance_types:
                    for o in it.available_offerings():
                        if o.capacity_type != wk.CAPACITY_TYPE_SPOT:
                            continue
                        if creq is not None and not creq.matches(o.capacity_type):
                            continue
                        if zreq is not None and not zreq.matches(o.zone):
                            continue
                        if o.price < target:
                            n += 1
                            break
                return n

            for g, (price, ct) in zip(groups, priced):
                if ct != wk.CAPACITY_TYPE_SPOT:
                    continue  # spot -> on-demand: gate does not apply
                residual = budget - (total_new - price)
                if cheaper_spot_types(g, residual) < MIN_TYPES_SPOT_TO_SPOT:
                    return False
        return True

    # -- execution ----------------------------------------------------------
    def _disrupt(self, c: Candidate, reason: str, disrupting: Dict[str, int]) -> None:
        from karpenter_tpu import metrics

        self.cluster.delete(NodeClaim, c.claim.metadata.name)
        self._pass_disrupted.append(c.node.metadata.name)
        pool_name = c.nodepool.name if c.nodepool is not None else "<standalone>"
        disrupting[pool_name] = disrupting.get(pool_name, 0) + 1
        self.last_decisions.append((c.claim.metadata.name, reason))
        metrics.DISRUPTION_DECISIONS.inc(reason=reason)
        if self.recorder is not None:
            # the core publishes a Disrupted event per acted candidate
            # (events.Recorder through the disruption controller)
            self.recorder.publish(
                c.claim, "Disrupted",
                f"disrupting via {reason} ({len(c.pods)} pods reschedule)",
            )
        self.log.info(
            "disrupting node",
            nodeclaim=c.claim.metadata.name,
            nodepool=pool_name,
            reason=reason,
            pods=len(c.pods),
        )

    def _replace_then_disrupt(self, cands, groups, reason: str, disrupting: Dict[str, int]) -> None:
        """Launch the replacement before draining (consolidation.md: delete
        the expensive node only 'when [the replacement] is ready'). If the
        replacement launch fails (e.g. ICE at fleet time), the old nodes are
        KEPT -- disrupting without a live replacement is the capacity gap
        this ordering exists to prevent. Accepts one Candidate or a list
        (multi-node consolidation drains the whole set behind one launch)."""
        from karpenter_tpu.controllers.provisioner import Provisioner
        from karpenter_tpu.solver.oracle import SchedulingResult

        from karpenter_tpu import failpoints

        if isinstance(cands, Candidate):
            cands = [cands]
        prov = Provisioner(self.cluster, self.cloud_provider)
        result = SchedulingResult()
        result.new_groups = list(groups)
        prov._launch(result)
        if result.unschedulable:
            return  # replacement did not materialize; try again next tick
        # chaos site: a crash HERE is the half-applied verdict -- the
        # replacement launched (journaled through the provisioner's
        # intent path) but no victim deleted yet. The crash soak asserts
        # the next incarnation's recovery sweep + consolidation passes
        # converge with no pod lost, no orphan instance, and no node
        # disrupted twice (tests/test_chaos.py).
        failpoints.eval("crash.disruption.apply")
        for c in cands:
            self._disrupt(c, reason, disrupting)
