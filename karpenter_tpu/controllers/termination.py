"""NodeClaim termination: graceful drain -> instance delete -> finalizer.

Rebuilds the core termination controller behavior the reference plugs into
(CloudProvider.Delete at pkg/cloudprovider/cloudprovider.go:209-220; the
disrupted taint + cordon-and-drain flow the interruption controller also
uses, pkg/controllers/interruption/controller.go:233-248):

deleting NodeClaim -> taint+cordon its node -> evict reschedulable pods
(grace-period aware) -> when empty (or grace expired) terminate the cloud
instance -> drop finalizer -> node object removed.
"""
from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis import NodeClaim, Node
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.scheduling import Taint
from karpenter_tpu.logging import get_logger

TERMINATION_FINALIZER = "karpenter.sh/termination"
DISRUPTED_TAINT = Taint("karpenter.sh/disrupted", effect="NoSchedule")


class TerminationController:
    log = get_logger("termination")

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self._drain_started: dict = {}

    def reconcile_all(self) -> None:
        for claim in self.cluster.list(NodeClaim):
            if claim.deleting:
                self.reconcile(claim)

    def reconcile(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_nodeclaim(claim)
        now = self.cluster.clock.now()
        if node is not None and not node.deleting:
            # cordon + disrupted taint
            if not node.unschedulable:
                node.unschedulable = True
                if all(t.key != DISRUPTED_TAINT.key for t in node.taints):
                    node.taints.append(DISRUPTED_TAINT)
                self.cluster.update(node)
            started = self._drain_started.setdefault(claim.metadata.name, now)
            pods = self.cluster.pods_on_node(node.metadata.name)
            evictable = [p for p in pods if p.reschedulable()]
            blocked = [p for p in pods if not p.reschedulable()]
            grace = claim.termination_grace_period
            grace_expired = grace is not None and now - started >= grace
            # evictions go through the PDB guard (the eviction API's
            # disruptionsAllowed); budget-exhausted pods stay bound and the
            # drain retries next tick as budgets free up -- until the
            # claim's termination grace expires, after which pods are
            # drained regardless (the reference's terminationGracePeriod
            # force-drain semantics)
            from karpenter_tpu.controllers.pdb_guard import PDBGuard

            guard = PDBGuard(self.cluster)
            pdb_deferred = 0
            for p in evictable:
                if not grace_expired and not guard.try_evict(p):
                    pdb_deferred += 1
                    continue
                p.node_name = ""
                p.phase = "Pending"
                self.cluster.update(p)
            if pdb_deferred:
                self.log.info(
                    "drain waiting on pod disruption budgets",
                    nodeclaim=claim.metadata.name, deferred=pdb_deferred,
                )
                return
            if blocked and not grace_expired:
                return  # wait for do-not-disrupt pods until grace expires
            # grace expired: non-reschedulable pods (static pods, bare pods)
            # die with the node rather than being requeued -- requeueing
            # would make the provisioner launch capacity for pods that are
            # not controller-replaced
            from karpenter_tpu.apis import Pod as PodKind

            for p in blocked:
                p.metadata.finalizers = []
                self.cluster.delete(PodKind, p.metadata.name)
        # node drained (or gone): delete the instance, then the objects
        try:
            self.cloud_provider.delete(claim)
        except NotFoundError:
            pass
        if node is not None:
            node.metadata.finalizers = []
            self.cluster.delete(Node, node.metadata.name)
        self.cluster.remove_finalizer(claim, TERMINATION_FINALIZER)
        self._drain_started.pop(claim.metadata.name, None)
        self.log.info("terminated node", nodeclaim=claim.metadata.name)
