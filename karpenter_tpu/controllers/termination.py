"""NodeClaim termination: graceful drain -> instance delete -> finalizer.

Rebuilds the core termination controller behavior the reference plugs into
(CloudProvider.Delete at pkg/cloudprovider/cloudprovider.go:209-220; the
disrupted taint + cordon-and-drain flow the interruption controller also
uses, pkg/controllers/interruption/controller.go:233-248):

deleting NodeClaim -> taint+cordon its node -> evict reschedulable pods
(grace-period aware) -> when empty (or grace expired) terminate the cloud
instance -> drop finalizer -> node object removed.
"""
from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis import NodeClaim, Node
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.scheduling import Taint
from karpenter_tpu.logging import get_logger

TERMINATION_FINALIZER = "karpenter.sh/termination"
DISRUPTED_TAINT = Taint("karpenter.sh/disrupted", effect="NoSchedule")
# system-cluster-critical / system-node-critical priority band: these pods
# drain LAST so the services they provide (DNS, CNI agents) outlive the
# workloads that depend on them during the drain (the reference's
# terminator drains in priority waves)
SYSTEM_CRITICAL_PRIORITY = 2_000_000_000


class TerminationController:
    log = get_logger("termination")

    def __init__(self, cluster: Cluster, cloud_provider: CloudProvider, recorder=None,
                 journal=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.recorder = recorder  # optional events.Recorder
        # optional IntentJournal: a durable terminate intent is written
        # once the drain completes, BEFORE the cloud delete, so a crash
        # between the two resumes promptly at the next recovery sweep
        # instead of waiting for the level-triggered retry to rediscover it
        self.journal = journal
        self._drain_started: dict = {}

    def reconcile_all(self) -> None:
        for claim in self.cluster.list(NodeClaim):
            if claim.deleting:
                self.reconcile(claim)

    def reconcile(self, claim: NodeClaim) -> None:
        node = self.cluster.node_for_nodeclaim(claim)
        now = self.cluster.clock.now()
        if node is not None and not node.deleting:
            # cordon + disrupted taint
            if not node.unschedulable:
                node.unschedulable = True
                if all(t.key != DISRUPTED_TAINT.key for t in node.taints):
                    node.taints.append(DISRUPTED_TAINT)
                self.cluster.update(node)
            started = self._drain_started.setdefault(claim.metadata.name, now)
            pods = self.cluster.pods_on_node(node.metadata.name)
            evictable = [p for p in pods if p.reschedulable()]
            blocked = [p for p in pods if not p.reschedulable()]
            grace = claim.termination_grace_period
            grace_expired = grace is not None and now - started >= grace
            # evictions go through the PDB guard (the eviction API's
            # disruptionsAllowed); budget-exhausted pods stay bound and the
            # drain retries next tick as budgets free up -- until the
            # claim's termination grace expires, after which pods are
            # drained regardless (the reference's terminationGracePeriod
            # force-drain semantics)
            from karpenter_tpu.controllers.pdb_guard import PDBGuard

            # priority waves: non-critical pods drain first; cluster-
            # critical pods (DNS, node agents) go only once no lower-
            # priority pod remains bound, one wave per reconcile
            noncritical = [p for p in evictable if p.priority < SYSTEM_CRITICAL_PRIORITY]
            critical = [p for p in evictable if p.priority >= SYSTEM_CRITICAL_PRIORITY]
            # the critical wave waits for EVERY lower-priority pod to leave
            # the node -- including blocked (do-not-disrupt/static) ones
            # that only clear at grace expiry; evicting DNS while a blocked
            # workload keeps running would be exactly the outage the waves
            # exist to prevent
            lower_blocked = [p for p in blocked if p.priority < SYSTEM_CRITICAL_PRIORITY]
            wave = noncritical or (
                critical if (grace_expired or not lower_blocked) else []
            )
            guard = PDBGuard(self.cluster)
            pdb_deferred = 0
            for p in wave:
                if not grace_expired and not guard.try_evict(p):
                    pdb_deferred += 1
                    continue
                p.node_name = ""
                p.phase = "Pending"
                self.cluster.update(p)
            if pdb_deferred:
                self.log.info(
                    "drain waiting on pod disruption budgets",
                    nodeclaim=claim.metadata.name, deferred=pdb_deferred,
                )
                return
            if noncritical and critical:
                return  # critical pods drain on the next pass
            if blocked and not grace_expired:
                return  # wait for do-not-disrupt pods until grace expires
            # grace expired: non-reschedulable pods (static pods, bare pods)
            # die with the node rather than being requeued -- requeueing
            # would make the provisioner launch capacity for pods that are
            # not controller-replaced
            from karpenter_tpu.apis import Pod as PodKind

            for p in blocked:
                p.metadata.finalizers = []
                self.cluster.delete(PodKind, p.metadata.name)
        # node drained (or gone): delete the instance, then the objects.
        # The terminate intent lands FIRST (write-ahead): a crash between
        # the cloud delete and the finalizer removal leaves a record the
        # recovery sweep resumes immediately
        intent = None
        if self.journal is not None and claim.provider_id:
            intent = self.journal.begin_terminate(claim)
        try:
            self.cloud_provider.delete(claim)
        except NotFoundError:
            pass
        # crash site: instance terminated, finalizer (and node object)
        # still in place -- restart must finish the teardown, not relaunch
        from karpenter_tpu import failpoints

        failpoints.eval("crash.termination")
        if node is not None:
            node.metadata.finalizers = []
            self.cluster.delete(Node, node.metadata.name)
        self.cluster.remove_finalizer(claim, TERMINATION_FINALIZER)
        if intent is not None:
            self.journal.resolve(intent, "committed")
        self._drain_started.pop(claim.metadata.name, None)
        if self.recorder is not None:
            # the core publishes a terminated event per claim through its
            # events.Recorder at the end of the drain flow
            self.recorder.publish(claim, "Terminated", "drained and deleted")
        self.log.info("terminated node", nodeclaim=claim.metadata.name)
