"""Provisioning controller: pending pods -> NodeClaims -> launched capacity.

Rebuilds the core provisioner reconcile (SURVEY.md section 3.1): snapshot
pending pods and cluster capacity, run the scheduling simulation (oracle or
TPU solver), create one NodeClaim per simulated node group, and call
CloudProvider.Create. In-flight NodeClaims participate in the next
simulation as virtual nodes so repeated ticks don't double-provision.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from itertools import chain
from typing import Dict, List, Optional

import time

from karpenter_tpu.apis import NodeClaim, NodePool, Node, labels as wk
from karpenter_tpu import events, metrics, tracing
from karpenter_tpu.logging import get_logger
from karpenter_tpu.apis.nodeclass import HASH_ANNOTATION, HASH_VERSION, HASH_VERSION_ANNOTATION, TPUNodeClass
from karpenter_tpu.apis.objects import generate_name
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.errors import CloudError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.scheduling import resources as res
from karpenter_tpu.solver.oracle import ExistingNode, NewNodeGroup, Scheduler, SchedulingResult

MAX_TYPES_PER_CLAIM = 60  # mirror of the launch truncation for claim size


def launch_all(cloud_provider, claims, max_workers: int):
    """Shared cloud-launch fan-out: returns one outcome (None | CloudError)
    per claim, in order. The launch-window expectation announces the wave
    size to the fleet batcher so identical requests rendezvous into one
    merged fleet call; it is capped at the worker-pool size because only
    that many calls can be in flight at once, and an expectation the pool
    cannot satisfy would stall every wave on the batcher's idle timeout
    (pkg/batcher/createfleet.go:36-46). Used by the provisioner AND the
    standalone nodeclaim lifecycle -- one copy of the protocol."""
    # fan-out workers inherit the dispatching thread's span context, so
    # the coalesced fleet calls' batcher spans land under the tick's
    # launch span instead of vanishing on the pool threads
    parent_span = tracing.TRACER.current()

    def launch_one(claim):
        with tracing.TRACER.attach(parent_span):
            try:
                cloud_provider.create(claim)
                return None
            except CloudError as e:
                return e
            except Exception as e:  # noqa: BLE001
                # a failure OUTSIDE the cloud-error taxonomy (a batcher
                # executor bug, an injected fault) must cost its one claim,
                # not escape the pool.map and kill the whole launch fan-out
                return CloudError(f"{type(e).__name__}: {e}")

    if len(claims) == 1:
        return [launch_one(claims[0])]
    expected = min(len(claims), max_workers)
    with cloud_provider.launch_window(expected):
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            return list(pool.map(launch_one, claims))


class _PodRef:
    """Event-target shim: unschedulable reasons are keyed by pod NAME in
    SchedulingResult (the pod object may be an effective volume copy)."""

    KIND = "Pod"

    def __init__(self, name: str):
        self.name = name

TERMINATION_FINALIZER = "karpenter.sh/termination"
# virtual-capacity pseudo-node prefix: launched-but-not-ready claims join
# the scheduling snapshot under this name (never a real node; k8s node
# names cannot contain '/'). Shared by the snapshot construction and the
# binder-hint strip below.
INFLIGHT_PREFIX = "inflight/"


class Provisioner:
    log = get_logger("provisioner")

    def __init__(
        self, cluster: Cluster, cloud_provider: CloudProvider, solver=None,
        recorder=None, pipeline: Optional[bool] = None, journal=None,
        admission_max_pods: int = 0, launch_max_groups: int = 0,
    ):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.solver = solver  # optional TPU solver; None = oracle
        self.recorder = recorder  # optional events.Recorder
        # optional IntentJournal (karpenter_tpu/journal.py): every launch
        # writes a durable intent BEFORE the cloud call and resolves it
        # after the claim status commit -- the crash-consistency protocol
        self.journal = journal
        self.last_result: Optional[SchedulingResult] = None
        # pod name -> claim name from the last scheduling decisions: the
        # binder tries the DECIDED node first instead of re-searching the
        # whole fleet per pod (round 5: the generic scan was O(pods x
        # nodes) per tick at 50k scale). Purely a fast path -- every hint
        # is re-validated by the same fit/affinity/spread checks, and a
        # failed hint falls back to the full scan.
        self._assignment_hints: Dict[str, str] = {}
        # double-buffered tick (the pipelined PRODUCTION path): under
        # SUSTAINED load the solver's device dispatch for tick N stays in
        # flight across the rest of the controller sweep, and tick N+1
        # drains it FIRST (launching its claims), then snapshots and
        # dispatches the next batch -- so the device round trip overlaps
        # everything between two provisioner reconciles instead of
        # blocking inside one. Drain-before-snapshot keeps every solve's
        # input consistent (no two in-flight batches can double-book
        # existing capacity), which is what makes each batch's decision
        # bit-identical to a synchronous solve of the same snapshot.
        # The pipeline engages only from the SECOND consecutive tick with
        # pending pods (cold ticks run the synchronous path: a single
        # burst still gets its decision the same tick).
        self.pipeline = pipeline if pipeline is not None else True
        # (ticket, vol_blocked, host_s, n_pods, dispatched_at) -- the
        # dispatch timestamp feeds the overlap-fraction attribution at
        # the next tick's drain barrier
        self._inflight = None
        self._sustained = False
        # bounded admission (overload tentpole, karpenter_tpu/overload.py):
        # admission_max_pods caps how many pending pods one tick may solve
        # (0 = unbounded); launch_max_groups caps the launch fan-out in
        # whole decision groups (0 = unbounded). Over the caps, the tick
        # solves a deterministic priority/age-ordered PREFIX and defers
        # the rest -- see _admit.
        self.admission_max_pods = int(admission_max_pods)
        self.launch_max_groups = int(launch_max_groups)
        # EWMA of the per-pod solve cost (seconds/pod), fed by
        # _apply_decision: the deadline-budget admission sizing divides
        # the tick's solve budget by this to size the admitted prefix
        self._solve_cost_ewma = 0.0
        # last logged shed shape, so a sustained storm logs level changes
        # rather than one line per tick
        self._last_shed_logged: Optional[tuple] = None

    # -- snapshot -----------------------------------------------------------
    def _existing_nodes(self) -> List[ExistingNode]:
        from karpenter_tpu.apis.storage import VolumeIndex

        out = []
        vol_index = VolumeIndex.from_cluster(self.cluster)
        live = [
            n for n in self.cluster.list(Node)
            if not n.deleting and not n.unschedulable and n.ready
        ]
        usage = self.cluster.node_usage_map(
            [n.metadata.name for n in live], vol_index)
        for node in live:
            out.append(
                ExistingNode(
                    name=node.metadata.name,
                    labels=dict(node.metadata.labels),
                    allocatable=node.allocatable,
                    taints=list(node.taints),
                    used=usage[node.metadata.name],
                )
            )
        # launched-but-not-YET-ready claims are virtual capacity
        for claim in self.cluster.list(NodeClaim):
            if claim.deleting or not claim.launched():
                continue
            node = self.cluster.node_for_nodeclaim(claim)
            if node is not None and node.ready:
                continue  # already counted above
            if claim.initialized() and node is not None:
                # the node initialized and LOST readiness: an unhealthy node
                # awaiting repair, not in-flight capacity. Counting it as an
                # empty virtual node wedges provisioning -- pending pods
                # simulate onto it every tick while the binder (correctly)
                # refuses to bind to a NotReady node.
                continue
            labels = dict(claim.metadata.labels)
            labels.update(claim.requirements.labels())
            out.append(
                ExistingNode(
                    name=INFLIGHT_PREFIX + claim.metadata.name,
                    labels=labels,
                    allocatable=claim.allocatable,
                    taints=list(claim.taints),  # startup taints excluded: they lift before pods land
                    used=Resources(),
                )
            )
        return out

    def _pods_by_node(self) -> Dict[str, List]:
        out: Dict[str, List] = {}
        from karpenter_tpu.apis import Pod

        for p in self.cluster.list(Pod):
            if p.node_name:
                out.setdefault(p.node_name, []).append(p)
        return out

    # -- reconcile ----------------------------------------------------------
    def reconcile(self) -> SchedulingResult:
        with tracing.span("provisioner"):
            return self._reconcile()

    def _reconcile(self) -> SchedulingResult:
        from karpenter_tpu import failpoints
        from karpenter_tpu.apis.storage import VolumeIndex, effective_pods

        # crash site: the operator dies at the top of the provisioner
        # dispatch (nothing launched yet; restart must re-simulate cleanly)
        failpoints.eval("crash.provisioner.dispatch")
        # stall site: the tick WEDGES here (before any solver dispatch) --
        # the stuck-tick watchdog's escalation drill
        failpoints.eval("stall.provisioner.solve")
        # pipeline barrier FIRST: the decision dispatched last tick lands
        # and its claims launch before this tick snapshots, so the new
        # snapshot sees that capacity in flight (drain-before-snapshot --
        # see __init__) and no two batches ever overlap
        prev = self._drain_pipeline()
        pods = self._admit(self.cluster.pending_pods())
        result = SchedulingResult()
        if not pods:
            self._sustained = False
            self.last_result = prev if prev is not None else result
            return self.last_result
        # lower volume claims into solver vocabulary (attach counts on the
        # attachable-volumes axis, bound zones as selector pins); pods
        # whose claims cannot resolve are unschedulable this tick
        # (apis/storage module docstring; the reference core's volume
        # topology translation does the same lowering)
        pods, vol_blocked = effective_pods(pods, VolumeIndex.from_cluster(self.cluster))
        result.unschedulable.update(vol_blocked)
        if not pods:
            self._sustained = False
            metrics.IGNORED_PODS.set(len(result.unschedulable))
            self._publish_unschedulable(result)
            self.last_result = result
            return result
        with tracing.span("snapshot") as snap_sp:
            nodepools = [p for p in self.cluster.list(NodePool) if not p.deleting]
            catalogs: Dict[str, List] = {}
            zones = set()
            for pool in nodepools:
                try:
                    items = self.cloud_provider.get_instance_types(pool)
                except CloudError:
                    items = []
                catalogs[pool.name] = items
                for it in items:
                    for o in it.available_offerings():
                        zones.add(o.zone)
            from karpenter_tpu.apis import DaemonSet
            from karpenter_tpu.apis.daemonset import overhead_by_pool

            scheduler = Scheduler(
                nodepools=nodepools,
                instance_types=catalogs,
                existing_nodes=self._existing_nodes(),
                pods_by_node=self._pods_by_node(),
                nodepool_usage={p.name: self.cluster.nodepool_usage(p.name) for p in nodepools},
                zones=zones,
                # fresh nodes reserve the daemonsets that will land on them
                # (apis/daemonset; the reference core sizes simulated nodes
                # the same way)
                daemon_overhead=overhead_by_pool(self.cluster.list(DaemonSet), nodepools),
            )
            snap_sp.set(pods=len(pods), nodepools=len(nodepools))
        t0 = time.perf_counter()
        sustained = self._sustained
        self._sustained = True
        if (
            self.pipeline and sustained and self.solver is not None
            and hasattr(self.solver, "schedule_begin")
            # degraded wire (solver breaker open): tick SYNCHRONOUSLY.
            # The CPU fallback leaves nothing remote in flight to overlap,
            # and a synchronous tick applies its decision immediately --
            # no decision rides a barrier into a tick that may degrade
            # differently (solver/breaker.py)
            and getattr(self.solver, "wire_healthy", lambda: True)()
        ):
            # sustained load: dispatch this batch and let the device round
            # trip ride under the rest of the sweep; the barrier lands at
            # the top of the next reconcile. Batches that route off the
            # plain device path come back already completed (nothing in
            # flight to overlap) and apply immediately below.
            with tracing.span("dispatch", mode="pipelined") as disp_sp:
                ticket = self.solver.schedule_begin(scheduler, pods)
                disp_sp.set(completed_at_begin=ticket.completed)
                self._annotate_group_stats(disp_sp)
            if not ticket.completed:
                metrics.SOLVER_PIPELINE_TICKS.inc(mode="pipelined")
                self._inflight = (
                    ticket, vol_blocked, time.perf_counter() - t0, len(pods),
                    time.perf_counter(),
                )
                self.last_result = prev if prev is not None else result
                return self.last_result
            decision = ticket.done
        elif self.solver is not None:
            with tracing.span("dispatch", mode="synchronous") as disp_sp:
                decision = self.solver.schedule(scheduler, pods)
                self._annotate_group_stats(disp_sp)
        else:
            with tracing.span("dispatch", mode="oracle"):
                decision = scheduler.schedule(pods)
        metrics.SOLVER_PIPELINE_TICKS.inc(mode="synchronous")
        return self._apply_decision(
            decision, vol_blocked, time.perf_counter() - t0, len(pods)
        )

    # bounded-admission progress floor: even a fully blown deadline budget
    # admits this many pods, so a storm can never starve provisioning
    MIN_ADMIT = 8

    def _admit(self, pods: List) -> List:
        """Bounded admission with priority-aware shedding (the overload
        tentpole): when the pending set exceeds what this tick can solve
        -- the explicit admission cap, or what the tick-deadline budget
        can afford at the EWMA per-pod solve cost -- solve a
        deterministic priority/age-ordered PREFIX and defer the rest.
        Deferred pods simply stay pending and re-enter next tick's
        ordering, so nothing is lost, only delayed; as placed pods leave
        the pending set, the FIFO-within-priority order guarantees every
        deferred pod eventually admits.

        The prefix is a pure function of the pod set -- priority desc,
        creation asc, name asc, with creation stamps from the injectable
        cluster clock -- so sim replays shed identically on every
        backend, and the admitted prefix's decision is bit-identical to
        an unloaded solve of those same pods (it flows through exactly
        the same solve)."""
        from karpenter_tpu import overload

        n = len(pods)
        limit, reason = n, ""
        if 0 < self.admission_max_pods < limit:
            limit, reason = self.admission_max_pods, "admission-cap"
        budget = overload.current()
        if budget is not None and self._solve_cost_ewma > 0.0:
            afford = max(
                self.MIN_ADMIT, int(budget.solve_budget() / self._solve_cost_ewma)
            )
            if afford < limit:
                limit, reason = afford, "deadline"
        if limit >= n:
            metrics.OVERLOAD_DEFERRED.set(0.0)
            self._last_shed_logged = None
            return pods
        admitted = sorted(
            pods,
            key=lambda p: (
                -p.priority, p.metadata.creation_timestamp, p.metadata.name,
            ),
        )[:limit]
        shed = n - limit
        metrics.OVERLOAD_SHED.inc(shed, reason=reason)
        metrics.OVERLOAD_DEFERRED.set(float(shed))
        tracing.annotate(admitted=limit, shed=shed, shed_reason=reason)
        if self._last_shed_logged != (limit, reason):
            self._last_shed_logged = (limit, reason)
            self.log.info(
                "overload: admission shed", admitted=limit, shed=shed,
                reason=reason,
            )
        return admitted

    def _annotate_group_stats(self, sp) -> None:
        """Surface the solver's dirty-tracking grouping stats (incremental
        tick engine) on the dispatch span: how much of the pending set
        actually churned since the last tick is the number that explains
        why a warm tick was cheap (or was not)."""
        st = getattr(self.solver, "last_group_stats", None)
        if not st:
            return
        sp.set(
            group_classes=st.get("classes", 0),
            group_dirty=st.get("dirty_classes", 0),
            group_dirty_fraction=round(st.get("dirty_fraction", 1.0), 4),
        )

    def _drain_pipeline(self) -> Optional[SchedulingResult]:
        """The explicit pipeline barrier: complete the decision dispatched
        last tick (fetch + decode via the solver's schedule_finish, which
        handles mid-flight catalog changes and wire degrades) and launch
        its claims. Returns None when nothing was in flight."""
        infl = self._inflight
        if infl is None:
            return None
        self._inflight = None
        ticket, vol_blocked, host_s, n_pods, dispatched_at = infl
        with tracing.span("drain", pods=n_pods) as sp:
            t0 = time.perf_counter()
            decision = self.solver.schedule_finish(ticket)
            barrier_s = time.perf_counter() - t0
            # overlap fraction: how much of the decision's device+wire
            # round trip was HIDDEN under the sweep between dispatch and
            # this barrier. hidden = dwell between dispatch return and the
            # barrier (the fetch streamed through it); barrier = the wait
            # this tick actually paid. 1.0 = the device time cost the
            # controller nothing; -> 0 = the pipeline hid nothing.
            hidden_s = max(0.0, t0 - dispatched_at)
            round_trip = hidden_s + barrier_s
            overlap = hidden_s / round_trip if round_trip > 0 else 1.0
            metrics.PIPELINE_OVERLAP.observe(overlap)
            sp.set(
                overlap_fraction=round(overlap, 4),
                hidden_ms=round(hidden_s * 1e3, 3),
                barrier_ms=round(barrier_s * 1e3, 3),
            )
            # decision latency = host stages at dispatch + the barrier's own
            # work; the deliberate overlap dwell between ticks is not decision
            # time (the fetch was streaming through it)
            return self._apply_decision(
                decision, vol_blocked, host_s + barrier_s, n_pods
            )

    def _apply_decision(
        self, result: SchedulingResult, vol_blocked: Dict[str, str],
        duration_s: float, n_pods: int,
    ) -> SchedulingResult:
        result.unschedulable.update(vol_blocked)
        metrics.SCHEDULING_DURATION.observe(duration_s)
        if n_pods > 0 and duration_s > 0:
            # per-pod solve cost EWMA: the deadline-budget admission
            # sizing's denominator (_admit). Alpha 0.3: reactive enough to
            # track a degrading sidecar within a few ticks, smooth enough
            # that one outlier tick does not collapse admission.
            per_pod = duration_s / n_pods
            self._solve_cost_ewma = (
                per_pod if self._solve_cost_ewma <= 0.0
                else 0.7 * self._solve_cost_ewma + 0.3 * per_pod
            )
        metrics.IGNORED_PODS.set(len(result.unschedulable))
        self._publish_unschedulable(result)
        # existing-node decisions hint the binder directly (node names).
        # A still-pending pod re-decided onto IN-FLIGHT virtual capacity
        # ("inflight/<claim>") hints to the claim name itself -- that is
        # the node name it will register under; hinting the pseudo-name
        # verbatim would overwrite a good hint with one that never
        # resolves and push every such pod onto the full binder scan
        # (round-5 regression: a one-tick readiness lag made 50k binds
        # quadratic again).
        for pod_name, node_name in result.existing_assignments.items():
            if node_name.startswith(INFLIGHT_PREFIX):
                node_name = node_name[len(INFLIGHT_PREFIX):]
            self._assignment_hints[pod_name] = node_name
        if result.new_groups or result.unschedulable:
            self.log.info(
                "scheduling decision",
                pods=n_pods,
                new_groups=len(result.new_groups),
                bound_existing=len(result.existing_assignments),
                unschedulable=len(result.unschedulable),
            )
        self._launch(result)
        self.last_result = result
        return result

    def _publish_unschedulable(self, result: SchedulingResult) -> None:
        """Per-pod FailedScheduling events with the decision's reason (the
        core publishes the same through its events.Recorder); the
        recorder's window dedups repeats across ticks."""
        if self.recorder is None:
            return
        for pod_name, reason in result.unschedulable.items():
            self.recorder.publish(
                _PodRef(pod_name), "FailedScheduling", reason, type=events.WARNING,
            )

    # -- NodeClaim creation + launch ---------------------------------------
    # worker parallelism for cloud launches, mirroring the reference's
    # MaxConcurrentReconciles: 10 (SURVEY.md section 2.4 row 1). Running
    # launches concurrently is also what makes the fleet batching window
    # effective: identical requests land in the same bucket before the
    # first waiter's flush fires (pkg/batcher/createfleet.go:36-46)
    MAX_CONCURRENT_LAUNCHES = 10

    def _launch(self, result: SchedulingResult) -> None:
        from karpenter_tpu import failpoints

        # stall site: the launch fan-out WEDGES before any cloud call
        # (watchdog escalation drill; nothing is in flight yet)
        failpoints.eval("stall.launch")
        groups = result.new_groups
        if not groups:
            return
        if 0 < self.launch_max_groups < len(groups):
            # bounded launch fan-out (overload tentpole): whole decision
            # groups past the bound are DEFERRED -- their claims are never
            # created, their pods simply stay pending and re-solve next
            # tick. The launched prefix's decision is untouched.
            deferred = groups[self.launch_max_groups:]
            groups = groups[: self.launch_max_groups]
            n_deferred = sum(len(g.pods) for g in deferred)
            metrics.OVERLOAD_SHED.inc(n_deferred, reason="launch-bound")
            self.log.info(
                "overload: launch fan-out bound",
                launched_groups=len(groups), deferred_groups=len(deferred),
                deferred_pods=n_deferred,
            )
        with tracing.span("launch", groups=len(groups)):
            self._launch_groups(result, groups)

    def _launch_groups(self, result: SchedulingResult, groups) -> None:
        from karpenter_tpu.providers.instance.provider import INTENT_TOKEN_ANNOTATION

        claims = []
        intents = []
        for group in groups:
            claim = self._to_nodeclaim(group)
            self.cluster.create(claim)
            # write-ahead intent AFTER the claim exists but BEFORE any
            # cloud call: the durable record a restart replays, its token
            # threaded to the fleet call via the claim annotation
            intent = None
            if self.journal is not None:
                intent = self.journal.begin_launch(claim)
                claim.metadata.annotations[INTENT_TOKEN_ANNOTATION] = intent.token
            claims.append(claim)
            intents.append(intent)
        # cloud calls fan out via the shared protocol (launch_all above);
        # cluster mutations stay on this thread
        outcomes = launch_all(self.cloud_provider, claims, self.MAX_CONCURRENT_LAUNCHES)
        for group, claim, intent, err in zip(groups, claims, intents, outcomes):
            if err is None:
                self.cluster.update(claim)
                if intent is not None:
                    # status committed: the intent has served its purpose
                    self.journal.resolve(intent, "committed")
                metrics.NODECLAIMS_CREATED.inc(nodepool=group.nodepool.name)
                for pod in group.pods:
                    self._assignment_hints[pod.metadata.name] = claim.metadata.name
            else:
                # ICE already recorded by the instance provider; drop the
                # claim so the next tick re-simulates around it
                for pod in group.pods:
                    result.unschedulable[pod.metadata.name] = str(err)
                claim.metadata.finalizers = []
                self.cluster.delete(NodeClaim, claim.metadata.name)
                # the intent stays OPEN: a CloudError does not prove no
                # instance was minted (a post-mint failure inside the
                # launch path, a misdealt merged batch). GC's stale-intent
                # janitor replays it THIS sweep -- no instance found means
                # a cheap "dropped"; a minted-but-unowned one is
                # terminated immediately instead of leaking until grace

    def _to_nodeclaim(self, group: NewNodeGroup) -> NodeClaim:
        pool = group.nodepool
        nodeclass = self.cluster.try_get(TPUNodeClass, pool.template.node_class_ref.name)
        from karpenter_tpu.scheduling import Operator, Requirement

        reqs = group.requirements.copy()
        from karpenter_tpu.scheduling.requirements import truncate_preserving_min_values

        by_price = sorted(group.instance_types, key=lambda i: i.cheapest_price())
        kept = truncate_preserving_min_values(reqs, by_price, MAX_TYPES_PER_CLAIM)
        reqs.add(Requirement(wk.INSTANCE_TYPE_LABEL, Operator.IN, [it.name for it in kept]))
        claim = NodeClaim(
            name=generate_name(f"{pool.name}-"),
            requirements=list(reqs),
            resources_requested=group.requested,
            node_class_ref=pool.template.node_class_ref,
            taints=list(pool.template.taints),
            startup_taints=list(pool.template.startup_taints),
            expire_after=pool.template.expire_after,
        )
        claim.metadata.labels = {
            **pool.template.labels,
            wk.NODEPOOL_LABEL: pool.name,
            wk.LABEL_NODECLASS: pool.template.node_class_ref.name,
        }
        claim.metadata.annotations = {
            **pool.template.annotations,
            wk.NODEPOOL_HASH_ANNOTATION: pool.static_hash(),
            wk.NODEPOOL_HASH_VERSION_ANNOTATION: HASH_VERSION,
        }
        if nodeclass is not None:
            claim.metadata.annotations[HASH_ANNOTATION] = nodeclass.static_hash()
            claim.metadata.annotations[HASH_VERSION_ANNOTATION] = HASH_VERSION
        claim.metadata.finalizers.append(TERMINATION_FINALIZER)
        claim.termination_grace_period = pool.template.termination_grace_period
        return claim


class PodBinder:
    """kube-scheduler stand-in for the kwok cluster: binds pending pods onto
    ready compatible nodes, first fit (the reference relies on the real
    kube-scheduler for this; the kwok rig needs it in-process)."""

    def __init__(self, cluster: Cluster, assignment_hints: Optional[Dict[str, str]] = None):
        self.cluster = cluster
        # shared with the Provisioner (operator wiring): pod name -> node/
        # claim name from the scheduling decision; see Provisioner's
        # _assignment_hints docstring
        self._assignment_hints: Dict[str, str] = (
            assignment_hints if assignment_hints is not None else {}
        )

    def reconcile(self) -> int:
        with tracing.span("bind") as sp:
            bound = self._reconcile()
            sp.set(bound=bound)
            return bound

    def _reconcile(self) -> int:
        from karpenter_tpu import failpoints
        from karpenter_tpu.apis.storage import VolumeIndex
        from karpenter_tpu.scheduling import tolerates_all

        # crash site: the operator dies before binding (claims launched and
        # committed, pods still pending; restart must just bind, not
        # relaunch)
        failpoints.eval("crash.bind")
        bound = 0
        nodes = [n for n in self.cluster.list(Node) if n.ready and not n.unschedulable and not n.deleting]
        # per-(topology key, selector) domain counts, built on first use per
        # reconcile (one cluster scan per distinct constraint) and updated
        # incrementally on each bind -- kube-scheduler's skew bookkeeping
        counts_cache: Dict[tuple, Dict[str, int]] = {}
        node_by_name = {n.metadata.name: n for n in nodes}
        from karpenter_tpu.solver.spread import soft_zone_tsc

        # built once per reconcile: node_usage consults it for bound pods'
        # attachments in the per-(pod, node) loop below
        vol_index = VolumeIndex.from_cluster(self.cluster)
        # incremental usage accounting (round 5): calling node_usage per
        # (pod, candidate node) try re-summed every bound pod's requests
        # -- quadratic at 50k pods (the full-loop E2E spent >80% of its
        # wall there). ONE snapshot per reconcile, O(1) add per bind.
        usage: Dict[str, Resources] = self.cluster.node_usage_map(
            [n.metadata.name for n in nodes], vol_index)
        for pod in self.cluster.pending_pods():
            needed = pod.requests + Resources.from_base_units({res.PODS: 1})
            vol_zone = None
            if pod.volume_claims:
                # claims charge the node's attach budget and, once bound,
                # pin the zone (apis/storage); unresolvable claims leave
                # the pod pending for a later tick
                n_vols, vol_zone, blocked = vol_index.lookup(pod)
                if blocked is not None:
                    continue
                needed = needed + Resources.from_base_units(
                    {res.ATTACHABLE_VOLUMES: float(n_vols)}
                )
            tscs = self._matching_spread(pod)
            spread_counts = [
                (tsc, self._counts_for(tsc, nodes, node_by_name, counts_cache))
                for tsc in tscs
            ]
            # preferences are SCORED, not filtered, exactly as
            # kube-scheduler does (PodTopologySpread scoring for
            # ScheduleAnyway, InterPodAffinity scoring for weighted
            # (anti-)affinity): among feasible nodes prefer the one with
            # the highest satisfied preference weight, least-loaded zone
            # as the tie-break (the decision layer already honored these;
            # scoring at bind time keeps the assignment from drifting)
            soft = soft_zone_tsc(pod)
            soft_counts = (
                self._counts_for(soft, nodes, node_by_name, counts_cache)
                if soft is not None else None
            )
            # soft HOSTNAME spread is also scored here (kube-scheduler
            # does); the decision plane cannot express a per-node
            # preference for NEW nodes, so bind time is where it lives
            soft_host = [
                (t, self._counts_for(t, nodes, node_by_name, counts_cache))
                for t in pod.topology_spread
                if not t.hard() and t.topology_key == wk.HOSTNAME_LABEL
                and all(pod.metadata.labels.get(k) == v for k, v in t.label_selector.items())
            ]
            prefs = pod.preferred_affinity_terms
            pref_zone_counts = {
                id(term): self._pref_zone_counts(term, node_by_name, counts_cache)
                for _, term in prefs
                if term.topology_key == wk.ZONE_LABEL
            }
            chosen = None
            chosen_key = None
            # decision-hint fast path: try the node the scheduling decision
            # assigned FIRST (claim names double as kwok node names). Only
            # for pods with no scoring pass -- scored pods must still see
            # every candidate. A hint that fails any check falls through
            # to the full scan below.
            hinted = (
                node_by_name.get(self._assignment_hints.get(pod.metadata.name, ""))
                if soft is None and not prefs and not soft_host else None
            )
            # chain, not list-concat: copying the full node list per hinted
            # pod would cost O(nodes) allocations at 50k scale
            candidates = nodes if hinted is None else chain((hinted,), nodes)
            for node in candidates:
                if not tolerates_all(pod.tolerations, node.taints):
                    continue
                if not any(alt.matches_labels(node.metadata.labels) for alt in pod.scheduling_requirements()):
                    continue
                if vol_zone is not None and node.metadata.labels.get(wk.ZONE_LABEL) != vol_zone:
                    continue
                used = usage[node.metadata.name]
                if not (used + needed).fits(node.allocatable):
                    continue
                if not self._anti_affinity_ok(pod, node):
                    continue
                if not self._spread_ok(node, spread_counts):
                    continue
                if soft is None and not prefs and not soft_host:
                    chosen = node
                    break
                if soft is not None:
                    z = node.metadata.labels.get(soft.topology_key)
                    # a node lacking the topology key scores WORST, as in
                    # kube-scheduler's PodTopologySpread (outside every
                    # domain); still eligible when nothing else fits
                    c = soft_counts.get(z, 0) if z is not None else float("inf")
                else:
                    c = 0
                h = sum(
                    counts.get(node.metadata.name, 0) for _, counts in soft_host
                )
                # higher satisfied preference weight wins; fewer same-
                # selector pods in the zone, then on the node, break ties;
                # then first-fit
                key = (-self._preference_score(pod, node, prefs, pref_zone_counts), c, h)
                if chosen is None or key < chosen_key:
                    chosen, chosen_key = node, key
            if chosen is None:
                continue
            self.cluster.bind_pod(pod, chosen)
            usage[chosen.metadata.name] = usage[chosen.metadata.name] + needed
            self._assignment_hints.pop(pod.metadata.name, None)
            if pod.volume_claims:
                # first-consumer binding: the landing zone binds the pod's
                # still-unbound WaitForFirstConsumer claims (the PV
                # controller's job upstream)
                vol_index.bind_on_schedule(
                    pod, chosen.metadata.labels.get(wk.ZONE_LABEL), self.cluster
                )
            # ONE cache update covers every consumer: a bound pod counts
            # toward EVERY cached (topology key / preferred-affinity)
            # selector it matches -- kube-scheduler's bookkeeping counts
            # pods by selector regardless of the bound pod's own
            # constraints, and the per-list updates this replaces went
            # stale exactly when a matching pod WITHOUT the constraint
            # bound mid-reconcile (round-4 review). The spread/soft/pref
            # lists above alias these same cached dicts.
            node_labels = chosen.metadata.labels
            for (kind, sel), counts in counts_cache.items():
                if not all(pod.metadata.labels.get(k) == v for k, v in sel):
                    continue
                dkey = wk.ZONE_LABEL if kind == "prefzone" else kind
                d = (
                    chosen.metadata.name
                    if dkey == wk.HOSTNAME_LABEL and dkey not in node_labels
                    else node_labels.get(dkey)
                )
                if d is not None:
                    counts[d] = counts.get(d, 0) + 1
            bound += 1
        if bound:
            metrics.PODS_BOUND.inc(bound)
        # stale-hint purge: keep only hints for pods that are still
        # pending (bounded by the pending set; a vanished pod's hint
        # would otherwise live forever). IN PLACE: this dict is shared
        # with the Provisioner by reference (operator wiring) -- a
        # reassignment would sever it and silently kill the fast path
        # (round-5 review finding).
        if self._assignment_hints:
            pending = {p.metadata.name for p in self.cluster.pending_pods()}
            for stale in [k for k in self._assignment_hints if k not in pending]:
                del self._assignment_hints[stale]
        metrics.NODES_READY.set(float(len(nodes)))
        return bound

    @staticmethod
    def _matching_spread(pod):
        return [
            t
            for t in pod.topology_spread
            if t.hard()
            and all(pod.metadata.labels.get(k) == v for k, v in t.label_selector.items())
        ]

    def _counts_for(self, tsc, nodes, node_by_name, cache):
        """Per-domain pod counts for one constraint, cached per reconcile
        (domain universe = the ready nodes' domains)."""
        from karpenter_tpu.apis import Pod

        key = (tsc.topology_key, tuple(sorted(tsc.label_selector.items())))
        counts = cache.get(key)
        if counts is not None:
            return counts
        counts = cache[key] = {}
        for n in nodes:
            d = n.metadata.labels.get(tsc.topology_key)
            if d is not None:
                counts.setdefault(d, 0)
        for other in self.cluster.list(Pod):
            if not other.node_name:
                continue
            if not all(other.metadata.labels.get(k) == v for k, v in tsc.label_selector.items()):
                continue
            onode = node_by_name.get(other.node_name) or self.cluster.try_get(Node, other.node_name)
            if onode is None:
                continue
            d = onode.metadata.labels.get(tsc.topology_key)
            if d is not None:
                counts[d] = counts.get(d, 0) + 1
        return counts

    @staticmethod
    def _spread_ok(node, spread_counts) -> bool:
        """Adding the pod to this node's domain must keep skew <= max_skew."""
        for tsc, counts in spread_counts:
            domain = node.metadata.labels.get(tsc.topology_key)
            if domain is None:
                return False
            global_min = min(counts.values(), default=0)
            if counts.get(domain, 0) + 1 - global_min > tsc.max_skew:
                return False
        return True

    def _pref_zone_counts(self, term, node_by_name, cache):
        """Per-zone count of bound pods matching a preferred-affinity
        term's selector: ONE cluster scan per distinct selector per
        reconcile (same pattern as _counts_for; a per-candidate-node scan
        would be O(pods x nodes) -- round-4 review), updated on bind."""
        from karpenter_tpu.apis import Pod as _Pod

        key = ("prefzone", tuple(sorted(term.label_selector.items())))
        counts = cache.get(key)
        if counts is not None:
            return counts
        counts = cache[key] = {}
        for p in self.cluster.list(_Pod):
            if not p.node_name:
                continue
            if not all(p.metadata.labels.get(k) == v for k, v in term.label_selector.items()):
                continue
            pn = node_by_name.get(p.node_name) or self.cluster.try_get(Node, p.node_name)
            if pn is None:
                continue
            z = pn.metadata.labels.get(wk.ZONE_LABEL)
            if z is not None:
                counts[z] = counts.get(z, 0) + 1
        return counts

    def _preference_score(self, pod, node, prefs, zone_counts) -> int:
        """Total weight of the pod's preferred (anti-)affinity terms a bind
        to `node` would satisfy -- kube-scheduler's InterPodAffinity
        scoring over the hostname and zone topology keys. `zone_counts`
        maps id(term) -> the term's per-zone matched-pod counts
        (_pref_zone_counts, cached per reconcile)."""
        if not prefs:
            return 0
        score = 0
        node_zone = node.metadata.labels.get(wk.ZONE_LABEL)
        for w, term in prefs:
            if term.topology_key == wk.HOSTNAME_LABEL:
                matched = any(
                    all(o.metadata.labels.get(k) == v for k, v in term.label_selector.items())
                    for o in self.cluster.pods_on_node(node.metadata.name)
                )
            elif term.topology_key == wk.ZONE_LABEL and node_zone is not None:
                matched = zone_counts[id(term)].get(node_zone, 0) > 0
            else:
                continue
            if matched != term.anti:
                score += w
        return score

    def _anti_affinity_ok(self, pod, node) -> bool:
        on_node = self.cluster.pods_on_node(node.metadata.name)
        for term in pod.affinity_terms:
            if not term.anti or term.topology_key != wk.HOSTNAME_LABEL:
                continue
            for other in on_node:
                if all(other.metadata.labels.get(k) == v for k, v in term.label_selector.items()):
                    return False
        return True
