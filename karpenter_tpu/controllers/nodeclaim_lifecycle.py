"""Standalone NodeClaim launcher: claims are a launch API, not just a
provisioner artifact.

The reference core's nodeclaim lifecycle controller launches ANY pending
NodeClaim resource -- users create claims directly for static capacity
(a claim with its own requirements + nodeclass ref, no NodePool
involved), and the same machinery drives registration/initialization
afterwards. In this framework the provisioner launches the claims IT
creates synchronously inside its own reconcile, so any claim that is
still unlaunched when this controller runs is a standalone one (or a
leftover the provisioner chose to delete -- it never leaves unlaunched
claims behind). Launching reuses the exact provider path
(CloudProvider.create resolves everything from the claim itself) under
the SAME launch_window + worker-pool rendezvous the provisioner uses, so
static capacity gets real fleet batching, ICE handling, and the kwok
lifecycle's registration flow.

Failures stay level-triggered: a claim whose nodeclass is not ready or
whose capacity is unavailable retries next tick, with a Warning event
(deduped by the recorder window) instead of silent stalling.
"""
from __future__ import annotations

from karpenter_tpu.apis import NodeClaim, labels as wk
from karpenter_tpu.apis.nodeclass import HASH_ANNOTATION, HASH_VERSION, HASH_VERSION_ANNOTATION, TPUNodeClass
from karpenter_tpu import metrics
from karpenter_tpu.logging import get_logger


class NodeClaimLifecycleController:
    log = get_logger("nodeclaim.lifecycle")

    # same fan-out as the provisioner's launch wave (SURVEY §2.4 row 1)
    MAX_CONCURRENT_LAUNCHES = 10

    def __init__(self, cluster, cloud_provider, recorder=None, journal=None):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.recorder = recorder
        # optional IntentJournal: standalone launches get the same
        # write-ahead crash-consistency protocol as provisioned ones
        self.journal = journal

    def reconcile_all(self) -> int:
        from karpenter_tpu.controllers.provisioner import launch_all
        from karpenter_tpu.providers.instance.provider import INTENT_TOKEN_ANNOTATION

        pending = [
            c for c in self.cluster.list(NodeClaim)
            if not c.launched() and not c.deleting
        ]
        if not pending:
            return 0
        intents = []
        for claim in pending:
            intent = None
            if self.journal is not None:
                intent = self.journal.begin_launch(claim)
                claim.metadata.annotations[INTENT_TOKEN_ANNOTATION] = intent.token
            intents.append(intent)
        outcomes = launch_all(self.cloud_provider, pending, self.MAX_CONCURRENT_LAUNCHES)
        launched = 0
        for claim, intent, err in zip(pending, intents, outcomes):
            if err is not None:
                if self.recorder is not None:
                    self.recorder.publish(claim, "LaunchFailed", str(err), type="Warning")
                # intent stays OPEN: unlike the provisioner the claim is
                # not dropped (level-triggered retry next tick reuses the
                # same intent and token, so the retry stays idempotent)
                continue
            # stamp the nodeclass static hash so drift detection covers
            # static capacity exactly as it covers provisioned capacity
            # (the provisioner stamps the same pair in _to_nodeclaim)
            nodeclass = self.cluster.try_get(TPUNodeClass, claim.node_class_ref.name)
            if nodeclass is not None and HASH_ANNOTATION not in claim.metadata.annotations:
                claim.metadata.annotations[HASH_ANNOTATION] = nodeclass.static_hash()
                claim.metadata.annotations[HASH_VERSION_ANNOTATION] = HASH_VERSION
            self.cluster.update(claim)
            if intent is not None:
                self.journal.resolve(intent, "committed")
            launched += 1
            metrics.NODECLAIMS_CREATED.inc(
                nodepool=claim.metadata.labels.get(wk.NODEPOOL_LABEL, "<standalone>")
            )
            self.log.info(
                "launched standalone nodeclaim",
                nodeclaim=claim.metadata.name,
                provider_id=claim.provider_id,
            )
        return launched
