"""KWOK node-lifecycle simulator.

The reference's kwok harness fabricates corev1.Nodes for launched fake
instances so the whole controller stack sees a live cluster without kubelets
(kwok/ec2/ec2.go:884+ registers KWOK-backed nodes; node kill thread
:253-281). This simulator is step-driven:

step() advances, for every NodeClaim:
  launched + register delay elapsed  -> fabricate+register a Node carrying
                                        the claim's single-value labels,
                                        capacity/allocatable from the claim
  registered + initialize delay      -> node Ready, startup taints dropped,
                                        claim Initialized
and for every Node whose backing instance died -> node gone, pods unbound
(back to Pending), exercising repair/GC paths.
"""
from __future__ import annotations

from typing import Dict, Optional

from karpenter_tpu.apis import NodeClaim, Node, labels as wk
from karpenter_tpu.apis.nodeclaim import COND_INITIALIZED, COND_LAUNCHED, COND_REGISTERED
from karpenter_tpu.kwok.cloud import FakeCloud
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.scheduling import resources as res


class NodeLifecycle:
    def __init__(
        self,
        cluster: Cluster,
        cloud: FakeCloud,
        register_delay: float = 3.0,
        initialize_delay: float = 2.0,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.register_delay = register_delay
        self.initialize_delay = initialize_delay
        # Delays are measured on the cluster's (injectable) clock from when
        # this simulator first *observes* each state -- condition transition
        # timestamps use wall time and cannot be compared to a fake clock.
        self._launched_seen: Dict[str, float] = {}
        self._registered_at: Dict[str, float] = {}

    def step(self) -> None:
        now = self.cluster.clock.now()
        self._register_nodes(now)
        self._initialize_nodes(now)
        self._propagate_impairments()
        self._reap_dead_instances()
        self._sweep_orphan_csinodes()

    # -- registration -------------------------------------------------------
    def _register_nodes(self, now: float) -> None:
        for claim in self.cluster.list(NodeClaim):
            if not claim.launched() or claim.registered() or claim.deleting:
                continue
            first_seen = self._launched_seen.setdefault(claim.metadata.name, now)
            if now - first_seen < self.register_delay:
                continue
            node_name = claim.metadata.name
            if self.cluster.try_get(Node, node_name) is not None:
                continue
            labels = dict(claim.metadata.labels)
            labels.update(claim.requirements.labels())
            labels[wk.HOSTNAME_LABEL] = node_name
            node = Node(
                name=node_name,
                labels=labels,
                capacity=claim.capacity,
                allocatable=claim.allocatable,
                taints=list(claim.taints) + list(claim.startup_taints),
                provider_id=claim.provider_id,
            )
            self.cluster.create(node)
            # the kubelet-analogue also publishes the node's CSI driver
            # registry: attach limits live on CSINode in real clusters
            # (kube adapter overlays them onto the node at read time),
            # and node STATUS writes never carry the derived axis
            attach = node.allocatable.get(res.ATTACHABLE_VOLUMES)
            if attach:
                from karpenter_tpu.apis.storage import CSINode

                self.cluster.create(
                    CSINode(node_name, drivers=[("csi.kwok.dev", int(attach))])
                )
            claim.node_name = node_name
            claim.status_conditions.set_true(COND_REGISTERED, "NodeRegistered")
            self.cluster.update(claim)
            self._registered_at[node_name] = now

    def _initialize_nodes(self, now: float) -> None:
        for claim in self.cluster.list(NodeClaim):
            if not claim.registered() or claim.initialized() or claim.deleting:
                continue
            reg_time = self._registered_at.get(claim.node_name)
            if reg_time is None:
                # a restarted operator lost the in-memory observation
                # timestamps (they are deliberately not durable -- delays
                # are a kubelet emulation, not cluster state): re-observe
                # NOW so an already-registered node initializes one delay
                # later instead of never (pre-journal this could not
                # happen; operator restarts over live state can hit it)
                reg_time = self._registered_at[claim.node_name] = now
            if now - reg_time < self.initialize_delay:
                continue
            node = self.cluster.try_get(Node, claim.node_name)
            if node is None:
                continue
            startup_keys = {t.key for t in claim.startup_taints}
            node.taints = [t for t in node.taints if t.key not in startup_keys]
            node.ready = True
            self.cluster.update(node)
            claim.status_conditions.set_true(COND_INITIALIZED, "NodeInitialized")
            self.cluster.update(claim)

    # -- failure propagation ------------------------------------------------
    def _propagate_impairments(self) -> None:
        """A degraded-but-running instance (FakeCloud.degrade_instance)
        surfaces its condition as False on the Node -- the kubelet/agent
        health reporting the auto-repair controller consumes. The node also
        stops accepting new pods (NotReady)."""
        impaired = {
            i.provider_id: i.impaired_condition
            for i in self.cloud.describe_instances()
            if i.impaired_condition and i.state in ("pending", "running")
        }
        if not impaired:
            return
        for node in self.cluster.list(Node):
            cond = impaired.get(node.provider_id)
            if cond and (node.ready or not node.status_conditions.is_false(cond)):
                # guard on the actual transition: unconditional updates
                # would emit a MODIFIED event per node per tick for the
                # whole toleration window
                node.status_conditions.set_false(cond, "InstanceImpaired")
                node.ready = False
                self.cluster.update(node)

    def _reap_dead_instances(self) -> None:
        live = {i.provider_id for i in self.cloud.describe_instances() if i.state in ("pending", "running")}
        for node in self.cluster.list(Node):
            if node.provider_id and node.provider_id not in live:
                self.cluster.unbind_pods(node.metadata.name)
                node.metadata.finalizers = []
                self.cluster.delete(Node, node.metadata.name)
        # A claim whose instance died is phantom capacity: if it survived,
        # the provisioner would keep counting it as an in-flight node and
        # never replace the lost pods (core nodeclaim-lifecycle behavior).
        for claim in self.cluster.list(NodeClaim):
            if claim.launched() and claim.provider_id and claim.provider_id not in live:
                claim.metadata.finalizers = []
                self.cluster.delete(NodeClaim, claim.metadata.name)
                self._launched_seen.pop(claim.metadata.name, None)
                self._registered_at.pop(claim.node_name, None)

    def _sweep_orphan_csinodes(self) -> None:
        """CSINode lifetime is this kubelet-analogue's job (as on a real
        cluster): whatever deleted the Node -- termination, GC, the reap
        above -- the companion CSINode follows on the next step, so no
        deletion call site needs to know about the cascade."""
        from karpenter_tpu.apis.storage import CSINode

        names = {n.metadata.name for n in self.cluster.list(Node)}
        for c in self.cluster.list(CSINode):
            if c.metadata.name not in names:
                self.cluster.delete(CSINode, c.metadata.name)
