"""In-memory cloud emulator -- the kwok/ec2 analogue and scale-benchmark rig.

Rebuilds the behavior of the reference's kwok harness (kwok/ec2/ec2.go:56-944):

- serves the instance-type/subnet/image catalog (ours from the deterministic
  gen_catalog pipeline rather than a live account, ec2.go:77-116)
- emulates CreateFleet: scores overrides lowest-price-first
  (ec2.go:432-461 + kwok/strategy/strategy.go:28-60), fabricates instances,
  and reports InsufficientInstanceCapacity per-override when a capacity pool
  is exhausted -- feeding the ICE cache exactly like real fleet errors
- per-API token-bucket rate limiting (kwok/ec2/ratelimiting.go:95-136)
- checkpoint/restore of the fabricated fleet (ec2.go:118-251 persists to
  ConfigMaps; here to a JSON-able dict)
- random kill switch to exercise repair/interruption paths
  (StartKillNodeThread ec2.go:253-281)

Also implements the Pricing/Queue/ParamStore/Identity/Cluster interfaces so
one object can back the whole provider graph in tests (the role of pkg/fake's
api fixtures, pkg/fake/ec2api.go et al.).
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloud.api import (
    ClusterAPI,
    ComputeAPI,
    IdentityAPI,
    ParamStoreAPI,
    PricingAPI,
    QueueAPI,
)
from karpenter_tpu.cloud.types import (
    CapacityReservationInfo,
    CloudInstance,
    FleetError,
    FleetRequest,
    FleetResult,
    ImageInfo,
    InstanceTypeInfo,
    LaunchTemplateInfo,
    QueueMessage,
    SecurityGroupInfo,
    SubnetInfo,
    ZoneInfo,
)
from karpenter_tpu.providers.instancetype import gen_catalog

ICE_CODE = "InsufficientInstanceCapacity"
RATE_LIMIT_CODE = "RequestLimitExceeded"
# idempotency-token tag: the journal's launch token rides onto the
# instance so a restart can correlate a launched instance with the intent
# whose claim status never committed (karpenter_tpu/journal.py). The key
# itself lives in apis/objects (core, not the emulator) -- this is a
# re-export for the suites that read instance tags.
from karpenter_tpu.apis.objects import INTENT_TOKEN_KEY as INTENT_TOKEN_TAG  # noqa: E402


class RateLimitError(Exception):
    code = RATE_LIMIT_CODE


class RateLimiter:
    """Token bucket (reference: kwok/ec2/ratelimiting.go:95-136)."""

    def __init__(self, rate_per_sec: float, burst: int, clock=None):
        self.rate = rate_per_sec
        self.burst = burst
        self._tokens = float(burst)
        self._last = None
        self._clock = clock
        self._lock = threading.Lock()

    def _now(self) -> float:
        return self._clock.now() if self._clock else time.monotonic()

    def allow(self) -> bool:
        with self._lock:
            now = self._now()
            if self._last is not None:
                self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class FakeCloud(ComputeAPI, PricingAPI, QueueAPI, ParamStoreAPI, IdentityAPI, ClusterAPI):
    def __init__(
        self,
        clock=None,
        rate_limit: Optional[float] = None,
        capacity_pools: Optional[Dict[Tuple[str, str, str], int]] = None,
        subnet_ip_count: int = 4096,
    ):
        self._clock = clock
        self._lock = threading.RLock()
        self._id_seq = itertools.count(1)

        # catalog
        self._types: List[InstanceTypeInfo] = gen_catalog.generate_instance_types()
        self._types_by_name = {t.name: t for t in self._types}
        self._zones = list(gen_catalog.ZONES)
        self.region = gen_catalog.REGION

        # networking fixtures: one cluster subnet + SG per zone
        self._subnets = [
            SubnetInfo(
                id=f"subnet-{z.zone_id}",
                zone=z.name,
                zone_id=z.zone_id,
                available_ip_count=subnet_ip_count,
                tags={"karpenter.tpu/discovery": "testing", "Name": f"private-{z.name}"},
            )
            for z in self._zones
        ]
        self._security_groups = [
            SecurityGroupInfo(id="sg-nodes", name="cluster-nodes", tags={"karpenter.tpu/discovery": "testing"}),
            SecurityGroupInfo(id="sg-extra", name="cluster-extra", tags={"other": "tag"}),
        ]
        self._images = [
            ImageInfo(id="img-std-amd64", name="standard-k8s-1.32-amd64", arch="amd64", family="Standard", creation_time=100.0),
            ImageInfo(id="img-std-arm64", name="standard-k8s-1.32-arm64", arch="arm64", family="Standard", creation_time=100.0),
            ImageInfo(id="img-min-amd64", name="minimal-k8s-1.32-amd64", arch="amd64", family="Minimal", creation_time=90.0),
            ImageInfo(id="img-acc-amd64", name="accelerated-k8s-1.32-amd64", arch="amd64", family="Accelerated", creation_time=100.0),
        ]
        self._params = {
            "/images/standard/latest/amd64": "img-std-amd64",
            "/images/standard/latest/arm64": "img-std-arm64",
            "/images/minimal/latest/amd64": "img-min-amd64",
            "/images/accelerated/latest/amd64": "img-acc-amd64",
        }
        self._reservations: List[CapacityReservationInfo] = []

        # fleet state
        self._instances: Dict[str, CloudInstance] = {}
        # client-token idempotency (the EC2 ClientToken analogue): token ->
        # instance id. A replayed launch slot with a known token returns
        # the existing instance -- the cloud-side half of the journal's
        # launch-at-most-once contract.
        self._fleet_tokens: Dict[str, str] = {}
        self.idempotent_hits = 0
        self._launch_templates: Dict[str, LaunchTemplateInfo] = {}
        self._instance_profiles: Dict[str, Dict] = {}
        self._queue: List[QueueMessage] = []
        self._inflight: Dict[str, QueueMessage] = {}

        # capacity pools: (instance_type, zone, capacity_type) -> remaining.
        # None (absent key) = unlimited; tests/benchmarks inject exhaustion.
        self._capacity_pools: Dict[Tuple[str, str, str], int] = dict(capacity_pools or {})

        # rate limiting (off by default; the scale rig turns it on)
        self._limiters: Dict[str, RateLimiter] = {}
        if rate_limit:
            for api in ("create_fleet", "describe_instances", "terminate_instances", "describe_instance_types"):
                self._limiters[api] = RateLimiter(rate_limit, int(rate_limit * 2), clock)

        # call counters (test observability, like pkg/fake atomic slots)
        self.calls: Dict[str, int] = {}
        # injectable per-API errors: api name -> list of exceptions to raise
        self.inject_errors: Dict[str, List[Exception]] = {}
        # chaos observers (sim/trace.TraceRecorder): callbacks fired on
        # external mutations of the emulated cloud -- kills, interruption
        # sends, capacity-pool edits, price overrides -- so a live or
        # chaos run can be captured as a replayable trace at this seam
        self.chaos_observers: List = []
        # price overrides: instance type -> multiplicative factor applied
        # over the static catalog prices (sim `price` events; the pricing
        # provider picks the change up on its next refresh)
        self._price_factors: Dict[str, float] = {}

    def _notify_chaos(self, kind: str, **detail) -> None:
        for obs in list(self.chaos_observers):
            try:
                obs(kind, detail)
            except Exception:  # noqa: BLE001 -- observers must never fault the cloud
                from karpenter_tpu import metrics

                metrics.HANDLED_ERRORS.inc(site="kwok.chaos_observer")

    # -- plumbing -----------------------------------------------------------
    def _now(self) -> float:
        return self._clock.now() if self._clock else time.time()

    def _enter(self, api: str) -> None:
        with self._lock:
            self.calls[api] = self.calls.get(api, 0) + 1
        lim = self._limiters.get(api)
        if lim and not lim.allow():
            raise RateLimitError(f"{api}: rate limited")
        errs = self.inject_errors.get(api)
        if errs:
            raise errs.pop(0)

    # -- ComputeAPI: catalog ------------------------------------------------
    def describe_zones(self) -> List[ZoneInfo]:
        self._enter("describe_zones")
        return list(self._zones)

    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        self._enter("describe_instance_types")
        return list(self._types)

    def describe_instance_type_offerings(self) -> Dict[str, List[str]]:
        self._enter("describe_instance_type_offerings")
        return {t.name: list(t.zones) for t in self._types}

    def describe_subnets(self) -> List[SubnetInfo]:
        self._enter("describe_subnets")
        return [SubnetInfo(s.id, s.zone, s.zone_id, s.available_ip_count, dict(s.tags)) for s in self._subnets]

    def describe_security_groups(self) -> List[SecurityGroupInfo]:
        self._enter("describe_security_groups")
        return list(self._security_groups)

    def describe_images(self) -> List[ImageInfo]:
        self._enter("describe_images")
        return list(self._images)

    def describe_capacity_reservations(self) -> List[CapacityReservationInfo]:
        self._enter("describe_capacity_reservations")
        return [CapacityReservationInfo(**vars(r)) for r in self._reservations]

    def add_capacity_reservation(self, cr: CapacityReservationInfo) -> None:
        with self._lock:
            self._reservations.append(cr)

    # -- ComputeAPI: fleet --------------------------------------------------
    def set_capacity(self, instance_type: str, zone: str, capacity_type: str, count: int) -> None:
        """Exhaustible capacity pool; emulates ICE when drained."""
        with self._lock:
            self._capacity_pools[(instance_type, zone, capacity_type)] = count
        self._notify_chaos(
            "set_capacity", instance_type=instance_type, zone=zone,
            capacity_type=capacity_type, count=count,
        )

    def _pool_take(self, instance_type: str, zone: str, capacity_type: str) -> bool:
        key = (instance_type, zone, capacity_type)
        with self._lock:
            remaining = self._capacity_pools.get(key)
            if remaining is None:
                return True
            if remaining <= 0:
                return False
            self._capacity_pools[key] = remaining - 1
            return True

    def _reservation_take(self, reservation_id: str) -> bool:
        """Consume one reservation slot (the real cloud decrements reservation
        availability as instances launch into it; Describe reflects it)."""
        with self._lock:
            for r in self._reservations:
                if r.id == reservation_id:
                    if r.available_count <= 0:
                        return False
                    r.available_count -= 1
                    return True
            return False

    def _reservation_release(self, reservation_id: str) -> None:
        with self._lock:
            for r in self._reservations:
                if r.id == reservation_id and r.available_count < r.total_count:
                    r.available_count += 1

    def set_price_factor(self, instance_type: str, factor: float) -> None:
        """Multiplicative price override over the static catalog tables
        (sim `price` events: spot-market swings, list-price changes). Both
        the pricing APIs and the fleet's lowest-price ranking honor it."""
        with self._lock:
            if factor == 1.0:
                self._price_factors.pop(instance_type, None)
            else:
                self._price_factors[instance_type] = float(factor)
        self._notify_chaos(
            "set_price_factor", instance_type=instance_type, factor=factor,
        )

    def _price_factor(self, instance_type: str) -> float:
        return self._price_factors.get(instance_type, 1.0)

    def _score(self, instance_type: str, capacity_type: str, zone: str) -> float:
        """Lowest-price strategy (kwok/strategy/strategy.go:28-60)."""
        info = self._types_by_name.get(instance_type)
        if info is None:
            return float("inf")
        if capacity_type == wk.CAPACITY_TYPE_SPOT:
            return gen_catalog.spot_price(info, zone) * self._price_factor(instance_type)
        return gen_catalog.on_demand_price(info) * self._price_factor(instance_type)

    def create_fleet(self, request: FleetRequest) -> FleetResult:
        self._enter("create_fleet")
        lt = self._launch_templates.get(request.launch_template_name)
        if lt is None:
            raise KeyError(f"launch template {request.launch_template_name} not found")
        subnets_by_id = {s.id: s for s in self._subnets}
        ranked = sorted(
            request.overrides,
            key=lambda o: (o.priority, self._score(o.instance_type, request.capacity_type, o.zone)),
        )
        instances: List[CloudInstance] = []
        errors: List[FleetError] = []
        exhausted = set()
        for slot in range(request.target_capacity):
            token = (
                request.client_tokens[slot]
                if slot < len(request.client_tokens) else None
            )
            if token:
                with self._lock:
                    existing = self._instances.get(self._fleet_tokens.get(token, ""))
                if existing is not None and existing.state not in ("terminated",):
                    # idempotent replay: this slot's token already backs a
                    # live instance (a crashed operator's journal replaying
                    # its open launch intent) -- return it, launch nothing
                    with self._lock:
                        self.idempotent_hits += 1
                    instances.append(existing)
                    continue
            placed = False
            for o in ranked:
                key = (o.instance_type, o.zone)
                if key in exhausted:
                    continue
                subnet = subnets_by_id.get(o.subnet_id)
                if subnet is None or subnet.available_ip_count <= 0:
                    continue
                if not self._pool_take(o.instance_type, o.zone, request.capacity_type):
                    exhausted.add(key)
                    errors.append(
                        FleetError(
                            code=ICE_CODE,
                            message=f"no {request.capacity_type} capacity for {o.instance_type} in {o.zone}",
                            instance_type=o.instance_type,
                            zone=o.zone,
                            capacity_type=request.capacity_type,
                        )
                    )
                    continue
                if o.capacity_reservation_id and not self._reservation_take(o.capacity_reservation_id):
                    exhausted.add(key)
                    errors.append(
                        FleetError(
                            code="ReservationCapacityExceeded",
                            message=f"reservation {o.capacity_reservation_id} exhausted",
                            instance_type=o.instance_type,
                            zone=o.zone,
                            capacity_type=request.capacity_type,
                        )
                    )
                    continue
                iid = f"i-{next(self._id_seq):08x}"
                tags = dict(request.tags)
                if token:
                    tags[INTENT_TOKEN_TAG] = token
                inst = CloudInstance(
                    id=iid,
                    instance_type=o.instance_type,
                    zone=o.zone,
                    subnet_id=o.subnet_id,
                    capacity_type=request.capacity_type,
                    image_id=o.image_id or lt.image_id,
                    state="running",
                    launch_time=self._now(),
                    tags=tags,
                    capacity_reservation_id=o.capacity_reservation_id,
                    nic_count=lt.nic_count,
                    security_group_ids=list(lt.security_group_ids),
                )
                with self._lock:
                    self._instances[iid] = inst
                    subnet.available_ip_count -= 1
                    if token:
                        self._fleet_tokens[token] = iid
                instances.append(inst)
                placed = True
                break
            if not placed:
                if not errors:
                    errors.append(FleetError(code=ICE_CODE, message="no capacity in any override"))
                break
        return FleetResult(instances=instances, errors=errors)

    def describe_instances(self, ids: Sequence[str] = (), tag_filter: Optional[Dict[str, str]] = None) -> List[CloudInstance]:
        self._enter("describe_instances")
        with self._lock:
            out = []
            for inst in self._instances.values():
                if ids and inst.id not in ids:
                    continue
                if tag_filter and not all(
                    (inst.tags.get(k) == v or (v == "*" and k in inst.tags)) for k, v in tag_filter.items()
                ):
                    continue
                out.append(inst)
            return out

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        self._enter("terminate_instances")
        done = []
        released = []
        with self._lock:
            for iid in ids:
                inst = self._instances.get(iid)
                if inst and inst.state not in ("terminated",):
                    inst.state = "terminated"
                    done.append(iid)
                    if inst.capacity_reservation_id:
                        released.append(inst.capacity_reservation_id)
        for rid in released:
            self._reservation_release(rid)
        return done

    def create_tags(self, resource_id: str, tags: Dict[str, str]) -> None:
        self._enter("create_tags")
        with self._lock:
            inst = self._instances.get(resource_id)
            if inst is None:
                raise KeyError(f"resource {resource_id} not found")
            inst.tags.update(tags)

    # -- ComputeAPI: launch templates ---------------------------------------
    def create_launch_template(self, lt: LaunchTemplateInfo) -> LaunchTemplateInfo:
        self._enter("create_launch_template")
        with self._lock:
            lt.created_at = self._now()
            if not lt.id:
                lt.id = f"lt-{next(self._id_seq):08x}"
            self._launch_templates[lt.name] = lt
        return lt

    def describe_launch_templates(self, names: Sequence[str] = ()) -> List[LaunchTemplateInfo]:
        self._enter("describe_launch_templates")
        with self._lock:
            if not names:
                return list(self._launch_templates.values())
            return [self._launch_templates[n] for n in names if n in self._launch_templates]

    def delete_launch_template(self, name: str) -> None:
        self._enter("delete_launch_template")
        with self._lock:
            self._launch_templates.pop(name, None)

    def spot_price_history(self) -> Dict[tuple, float]:
        self._enter("spot_price_history")
        out = {}
        for t in self._types:
            if "spot" in t.supported_usage_classes:
                for z in t.zones:
                    out[(t.name, z)] = gen_catalog.spot_price(t, z) * self._price_factor(t.name)
        return out

    # -- PricingAPI ---------------------------------------------------------
    def on_demand_prices(self) -> Dict[str, float]:
        self._enter("on_demand_prices")
        return {
            t.name: gen_catalog.on_demand_price(t) * self._price_factor(t.name)
            for t in self._types
        }

    # -- QueueAPI -----------------------------------------------------------
    def queue_url(self) -> str:
        return "mem://interruption-queue"

    def send(self, body: str) -> None:
        with self._lock:
            mid = f"msg-{next(self._id_seq):08x}"
            self._queue.append(QueueMessage(id=mid, receipt=mid, body=body))
        if self.chaos_observers:
            # capture seam: an interruption message entering the queue is
            # an external event worth a trace line (best-effort: only the
            # EventBridge detail.instance-id shape is replayable)
            try:
                iid = json.loads(body).get("detail", {}).get("instance-id")
            except (ValueError, AttributeError, TypeError, KeyError):
                # a malformed chaos payload carries no instance id; the
                # narrow net keeps real faults (and crashes) propagating
                iid = None
            if iid:
                self._notify_chaos("interruption", instance_id=iid)

    def receive(self, max_messages: int = 10) -> List[QueueMessage]:
        self._enter("receive")
        with self._lock:
            batch = self._queue[:max_messages]
            self._queue = self._queue[max_messages:]
            for m in batch:
                self._inflight[m.receipt] = m
            return batch

    def delete(self, receipt: str) -> None:
        self._enter("queue_delete")
        with self._lock:
            self._inflight.pop(receipt, None)

    # -- ParamStoreAPI ------------------------------------------------------
    def get_parameter(self, name: str) -> Optional[str]:
        self._enter("get_parameter")
        return self._params.get(name)

    # -- IdentityAPI --------------------------------------------------------
    def create_instance_profile(self, name: str, tags: Dict[str, str]) -> None:
        self._enter("create_instance_profile")
        with self._lock:
            if name in self._instance_profiles:
                raise KeyError(f"instance profile {name} already exists")
            self._instance_profiles[name] = {"name": name, "tags": dict(tags), "roles": []}

    def get_instance_profile(self, name: str) -> Optional[Dict]:
        self._enter("get_instance_profile")
        return self._instance_profiles.get(name)

    def delete_instance_profile(self, name: str) -> None:
        self._enter("delete_instance_profile")
        with self._lock:
            self._instance_profiles.pop(name, None)

    def add_role(self, profile_name: str, role: str) -> None:
        self._enter("add_role")
        prof = self._instance_profiles.get(profile_name)
        if prof is None:
            raise KeyError(f"instance profile {profile_name} not found")
        prof["roles"] = [role]

    # -- ClusterAPI ---------------------------------------------------------
    def cluster_endpoint(self) -> str:
        return "https://cluster.local:6443"

    def cluster_version(self) -> str:
        return "1.32"

    def cluster_ca_bundle(self) -> str:
        return "ca-bundle"

    # -- fault injection / chaos (rig features) -----------------------------
    def kill_instance(self, instance_id: str) -> bool:
        """Abruptly terminate (repair-path exercise; ec2.go:253-281)."""
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None or inst.state == "terminated":
                return False
            inst.state = "terminated"
        self._notify_chaos("kill_instance", instance_id=instance_id)
        return True

    def degrade_instance(self, instance_id: str, condition: str = "Ready") -> bool:
        """Leave the instance RUNNING but unhealthy: its Node reports
        `condition`=False until replaced -- the auto-repair path (dead
        instances take the GC path instead; the reference kwok kill thread
        exercises both)."""
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None or inst.state == "terminated":
                return False
            inst.impaired_condition = condition
            return True

    # -- checkpoint/restore (ec2.go:118-251) --------------------------------
    def checkpoint(self) -> str:
        with self._lock:
            doc = {
                "instances": [vars(i) for i in self._instances.values()],
                "launch_templates": [vars(lt) for lt in self._launch_templates.values()],
                "capacity_pools": [[list(k), v] for k, v in self._capacity_pools.items()],
                "subnet_ips": {s.id: s.available_ip_count for s in self._subnets},
                "id_seq": next(self._id_seq),
                "fleet_tokens": dict(self._fleet_tokens),
            }
        return json.dumps(doc)

    def restore(self, blob: str) -> None:
        doc = json.loads(blob)
        with self._lock:
            self._instances = {d["id"]: CloudInstance(**d) for d in doc["instances"]}
            self._launch_templates = {d["name"]: LaunchTemplateInfo(**d) for d in doc["launch_templates"]}
            self._capacity_pools = {tuple(k): v for k, v in doc["capacity_pools"]}
            for s in self._subnets:
                if s.id in doc["subnet_ips"]:
                    s.available_ip_count = doc["subnet_ips"][s.id]
            self._id_seq = itertools.count(doc["id_seq"])
            self._fleet_tokens = dict(doc.get("fleet_tokens", {}))
