from karpenter_tpu.kwok.cloud import FakeCloud, RateLimiter

__all__ = ["FakeCloud", "RateLimiter"]
