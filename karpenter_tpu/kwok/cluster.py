"""In-memory cluster: the kube-apiserver stand-in.

The reference's coordination bus is the Kubernetes API server (CRDs, watches,
field indexers -- SURVEY.md section 2.4). This module provides the same
contract for a standalone process: a thread-safe typed object store with
resource-version optimistic concurrency, finalizer-aware deletion, event
listeners (watch analogue), and the pod/node relational queries the
scheduler and disruption controllers need (the role of the core's cluster
state, state.NewCluster at cmd/controller/main.go:43).

Everything is step-driven and clock-injected: no background goroutine
analogues, so tests and the benchmark rig are deterministic.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Type

from karpenter_tpu.apis import (
    DaemonSet, NodeClaim, NodePool, Pod, Node, PersistentVolumeClaim,
    PodDisruptionBudget, StorageClass, TPUNodeClass,
)
from karpenter_tpu.apis.storage import CSINode
from karpenter_tpu.apis.objects import APIObject, Lease, ProvisioningIntent
from karpenter_tpu.cache.ttl import Clock
from karpenter_tpu.scheduling import Resources


class Conflict(Exception):
    """Optimistic-concurrency failure (stale resourceVersion)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


EventHandler = Callable[[str, APIObject], None]  # (event_type, object)



class RelationalQueries:
    """Read-only pod/node/claim relations derived purely from list() --
    shared verbatim by the in-memory Cluster and the apiserver-backed
    KubeCluster so the two buses can never drift on these semantics."""

    def pending_pods(self) -> List[Pod]:
        return [p for p in self.list(Pod) if p.schedulable()]

    def pods_on_node(self, node_name: str) -> List[Pod]:
        return [p for p in self.list(Pod) if p.node_name == node_name]

    def nodeclaim_for_node(self, node: Node) -> Optional[NodeClaim]:
        for nc in self.list(NodeClaim):
            if nc.provider_id and nc.provider_id == node.provider_id:
                return nc
        return None

    def node_for_nodeclaim(self, claim: NodeClaim) -> Optional[Node]:
        for n in self.list(Node):
            if n.provider_id and n.provider_id == claim.provider_id:
                return n
        return None

    def node_usage(self, node_name: str, vol_index=None) -> Resources:
        """One node's usage; delegates to node_usage_map so exactly ONE
        copy of the accounting formula exists (a drifted duplicate --
        usage omitting the PODS axis -- was a round-5 bug)."""
        return self.node_usage_map([node_name], vol_index)[node_name]

    def node_usage_map(self, node_names, vol_index=None) -> Dict[str, Resources]:
        """Usage for MANY nodes in ONE pod pass (the per-node form is
        O(all pods) per call on stores without a pod index -- kube's
        TTL-cached list -- which made per-tick snapshots O(nodes x pods)
        at fleet scale, round 5). THE accounting formula lives here:
        each bound pod charges its requests plus ONE slot on the pods
        axis (the solver, oracle, and binder all charge PODS:1 per
        placement), and claim-carrying pods charge their resolved volume
        attachments (apis/storage; hot callers pass a prebuilt index)."""
        from karpenter_tpu.apis.storage import PersistentVolumeClaim, pod_volume_requests, VolumeIndex
        from karpenter_tpu.scheduling import resources as res

        out: Dict[str, Resources] = {n: Resources() for n in node_names}
        one_pod = Resources.from_base_units({res.PODS: 1})
        for p in self.list(Pod):
            total = out.get(p.node_name)
            if total is None:
                continue
            total = total + p.requests + one_pod
            if p.volume_claims:
                if vol_index is None:
                    vol_index = VolumeIndex(self.list(PersistentVolumeClaim))
                total = total + pod_volume_requests(p, vol_index)
            out[p.node_name] = total
        return out

    def nodepool_usage(self, nodepool_name: str) -> Resources:
        from karpenter_tpu.apis import labels as wk

        total = Resources()
        for nc in self.list(NodeClaim):
            if nc.metadata.labels.get(wk.NODEPOOL_LABEL) == nodepool_name and not nc.deleting:
                total = total + nc.capacity
        return total



class Cluster(RelationalQueries):
    KINDS: Tuple[Type[APIObject], ...] = (
        Pod, Node, NodeClaim, NodePool, TPUNodeClass, Lease,
        ProvisioningIntent,
        PodDisruptionBudget, DaemonSet, PersistentVolumeClaim, StorageClass,
        CSINode,
    )

    POD_NODE_INDEX = "spec.nodeName"
    NODE_PROVIDER_INDEX = "spec.providerID"
    CLAIM_PROVIDER_INDEX = "status.providerID"

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._lock = threading.RLock()
        self._store: Dict[str, Dict[str, APIObject]] = {k.KIND: {} for k in self.KINDS}
        self._version = 0
        self._handlers: List[EventHandler] = []
        # field indexers (reference: mgr.GetFieldIndexer().IndexField on
        # NodeClaim status fields, pkg/operator/operator.go:284-305):
        # (kind, index name) -> key fn; per index a forward map key ->
        # {object name: object} and a reverse map object name -> key
        self._indexers: Dict[Tuple[str, str], Callable[[APIObject], Optional[str]]] = {}
        self._indexes: Dict[Tuple[str, str], Tuple[Dict[str, Dict[str, APIObject]], Dict[str, str]]] = {}
        # built-in pod-by-node index: pods_on_node was an O(all pods) scan
        # per call, quadratic in the 50k full-loop E2E (round 5). Writes
        # go through create/update/delete (bind_pod/unbind_pods do), which
        # is the informer contract by_index already documents.
        self.add_field_index(Pod, self.POD_NODE_INDEX, lambda p: p.node_name or None)
        # providerID indexes: node<->claim correlation ran as linear scans
        # per call -- O(claims x nodes) per controller tick at fleet scale
        self.add_field_index(Node, self.NODE_PROVIDER_INDEX,
                             lambda n: n.provider_id or None)
        self.add_field_index(NodeClaim, self.CLAIM_PROVIDER_INDEX,
                             lambda c: c.provider_id or None)

    def pods_on_node(self, node_name: str) -> List[Pod]:  # type: ignore[override]
        return self.by_index(Pod, self.POD_NODE_INDEX, node_name)

    def nodeclaim_for_node(self, node: Node) -> Optional[NodeClaim]:  # type: ignore[override]
        if not node.provider_id:
            return None
        hits = self.by_index(NodeClaim, self.CLAIM_PROVIDER_INDEX, node.provider_id)
        return hits[0] if hits else None

    def node_for_nodeclaim(self, claim: NodeClaim) -> Optional[Node]:  # type: ignore[override]
        if not claim.provider_id:
            return None
        hits = self.by_index(Node, self.NODE_PROVIDER_INDEX, claim.provider_id)
        return hits[0] if hits else None

    # -- watch --------------------------------------------------------------
    def on_event(self, handler: EventHandler) -> None:
        self._handlers.append(handler)

    # -- field indexes ------------------------------------------------------
    def add_field_index(
        self, kind: Type[APIObject], name: str, key_fn: Callable[[APIObject], Optional[str]]
    ) -> None:
        """Register an O(1) lookup over one derived key, maintained on
        every create/update/delete -- the in-memory analogue of
        controller-runtime's field indexer. key_fn returns None for
        objects that should not be indexed (e.g. an empty providerID
        before launch)."""
        with self._lock:
            self._indexers[(kind.KIND, name)] = key_fn
            fwd: Dict[str, Dict[str, APIObject]] = {}
            rev: Dict[str, str] = {}
            for obj in self._store[kind.KIND].values():
                key = key_fn(obj)
                if key:
                    fwd.setdefault(key, {})[obj.metadata.name] = obj
                    rev[obj.metadata.name] = key
            self._indexes[(kind.KIND, name)] = (fwd, rev)

    def by_index(self, kind: Type[APIObject], name: str, key: str) -> List[APIObject]:
        """Objects whose indexed key equals `key`. Hits are re-verified
        against key_fn so an object mutated WITHOUT a cluster.update()
        call is filtered rather than returned stale (informer caches have
        the same contract: writes must go through the store)."""
        with self._lock:
            entry = self._indexes.get((kind.KIND, name))
            if entry is None:
                raise KeyError(f"no field index {name!r} for {kind.KIND}")
            key_fn = self._indexers[(kind.KIND, name)]
            return [o for o in entry[0].get(key, {}).values() if key_fn(o) == key]

    def has_index(self, kind: Type[APIObject], name: str) -> bool:
        with self._lock:
            return (kind.KIND, name) in self._indexes

    def _index_touch(self, obj: APIObject, removed: bool = False) -> None:
        """Under self._lock: re-key `obj` in every index on its kind."""
        kind = type(obj).KIND
        oname = obj.metadata.name
        for (ikind, iname), key_fn in self._indexers.items():
            if ikind != kind:
                continue
            fwd, rev = self._indexes[(ikind, iname)]
            old = rev.pop(oname, None)
            if old is not None:
                bucket = fwd.get(old)
                if bucket is not None:
                    bucket.pop(oname, None)
                    if not bucket:
                        fwd.pop(old, None)
            if not removed:
                key = key_fn(obj)
                if key:
                    fwd.setdefault(key, {})[oname] = obj
                    rev[oname] = key

    def _emit(self, event: str, obj: APIObject) -> None:
        for h in self._handlers:
            h(event, obj)

    # -- CRUD ---------------------------------------------------------------
    def create(self, obj: APIObject) -> APIObject:
        # admission: the store is the apiserver stand-in, so the CRD
        # validation rules run here (apis/validation.py; reference: CEL
        # rules compiled into pkg/apis/crds/*.yaml, enforced at admission)
        from karpenter_tpu.apis.validation import admit

        admit(obj)
        with self._lock:
            kind = type(obj).KIND
            if obj.metadata.name in self._store[kind]:
                raise AlreadyExists(f"{kind}/{obj.metadata.name}")
            self._version += 1
            obj.metadata.resource_version = self._version
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self.clock.now()
            self._store[kind][obj.metadata.name] = obj
            self._index_touch(obj)
        self._emit("ADDED", obj)
        return obj

    def get(self, kind: Type[APIObject], name: str) -> APIObject:
        with self._lock:
            obj = self._store[kind.KIND].get(name)
            if obj is None:
                raise NotFound(f"{kind.KIND}/{name}")
            return obj

    def try_get(self, kind: Type[APIObject], name: str) -> Optional[APIObject]:
        with self._lock:
            return self._store[kind.KIND].get(name)

    def list(self, kind: Type[APIObject], predicate: Optional[Callable[[APIObject], bool]] = None) -> List[APIObject]:
        with self._lock:
            items = list(self._store[kind.KIND].values())
        if predicate is not None:
            items = [o for o in items if predicate(o)]
        return items

    def update(self, obj: APIObject, expect_version: Optional[int] = None) -> APIObject:
        from karpenter_tpu.apis.validation import admit

        admit(obj)
        with self._lock:
            kind = type(obj).KIND
            current = self._store[kind].get(obj.metadata.name)
            if current is None:
                raise NotFound(f"{kind}/{obj.metadata.name}")
            if expect_version is not None and current.metadata.resource_version != expect_version:
                raise Conflict(f"{kind}/{obj.metadata.name}: version {expect_version} is stale")
            self._version += 1
            obj.metadata.resource_version = self._version
            self._store[kind][obj.metadata.name] = obj
            self._index_touch(obj)
        self._emit("MODIFIED", obj)
        return obj

    def delete(self, kind: Type[APIObject], name: str) -> Optional[APIObject]:
        """Finalizer-aware: with finalizers set, marks deleting and returns
        the object; actual removal happens once finalizers clear."""
        with self._lock:
            obj = self._store[kind.KIND].get(name)
            if obj is None:
                return None
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self.clock.now()
                    self._version += 1
                    obj.metadata.resource_version = self._version
                result = obj
            else:
                del self._store[kind.KIND][name]
                self._index_touch(obj, removed=True)
                result = None
        if result is not None:
            self._emit("DELETING", obj)
        else:
            self._emit("DELETED", obj)
        return result

    def remove_finalizer(self, obj: APIObject, finalizer: str) -> None:
        with self._lock:
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                self._store[type(obj).KIND].pop(obj.metadata.name, None)
                self._index_touch(obj, removed=True)
                removed = True
            else:
                removed = False
        if removed:
            self._emit("DELETED", obj)

    # -- relational writes (reads shared via RelationalQueries) -------------
    def bind_pod(self, pod: Pod, node: Node) -> None:
        pod.node_name = node.metadata.name
        pod.phase = "Running"
        self.update(pod)

    def unbind_pods(self, node_name: str) -> List[Pod]:
        """Node went away: owned pods return to Pending (controller
        re-creation abstracted to an in-place reset)."""
        out = []
        for p in self.pods_on_node(node_name):
            p.node_name = ""
            p.phase = "Pending"
            self.update(p)
            out.append(p)
        return out
