"""karpenter-tpu: a TPU-native Kubernetes node-provisioning framework.

A ground-up rebuild of the capabilities of the Karpenter AWS provider
(reference: ellistarn/karpenter-provider-aws) plus the scheduling core it
plugs into (sigs.k8s.io/karpenter), re-architected TPU-first:

- The control plane (reconcilers, providers, caches, cloud API emulation)
  is host-side Python, mirroring the reference's Go reconciler structure
  (reference: cmd/controller/main.go:30-84, pkg/operator/operator.go:96-212).
- The decision plane -- the FFD bin-packing provisioning loop and the
  consolidation candidate search, the two hot loops identified in
  SURVEY.md section 3 -- is a batched JAX solver: pods x instance-type
  fit/cost tensors evaluated on TPU, with constraint algebra lowered to
  boolean masks and the sequential FFD loop reformulated as a
  lax.scan over *pod equivalence classes* (not individual pods).
- Scale-out: the solve shards over a jax.sharding.Mesh (pods axis = data
  parallel, catalog axis = tensor parallel) with XLA collectives.
"""

__version__ = "0.1.0"
