"""Event recorder.

The reference emits k8s Events through an events.Recorder for every notable
lifecycle action (pkg/cloudprovider/events/events.go,
pkg/controllers/interruption/events/events.go). This in-memory recorder
keeps the same shape: typed events attached to objects, deduplicated within
a window, queryable by tests.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.cache.ttl import Clock

NORMAL = "Normal"
WARNING = "Warning"


@dataclass
class Event:
    kind: str            # object kind
    name: str            # object name
    type: str            # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0
    count: int = 1


class Recorder:
    # retained-event cap: the recorder is an in-memory ring, not a durable
    # sink (the reference's Events go to the apiserver with its own GC);
    # pruning drops the OLDEST half so recent history stays queryable
    MAX_EVENTS = 4096

    def __init__(self, clock: Optional[Clock] = None, dedupe_window: float = 60.0):
        self.clock = clock or Clock()
        self.dedupe_window = dedupe_window
        self._lock = threading.Lock()
        self.events: List[Event] = []
        # dedupe index keyed by identity, not a tail scan: a tick that
        # publishes >window-size distinct events must still coalesce each
        # of them with its own previous occurrence next tick
        self._recent: dict = {}

    def publish(self, obj, reason: str, message: str = "", type: str = NORMAL) -> None:
        event_type = type
        kind = getattr(obj, "KIND", "Object")
        name = getattr(obj, "name", str(obj))
        now = self.clock.now()
        key = (kind, name, reason, message)
        with self._lock:
            e = self._recent.get(key)
            if e is not None and now - e.timestamp < self.dedupe_window:
                # identical events coalesce; a CHANGED message under the
                # same reason (e.g. an unschedulable pod's cause moving
                # from a missing claim to no-capacity) keys differently
                # and records fresh -- suppressing it would hide the new
                # cause for the whole window
                e.count += 1
                return
            e = Event(kind=kind, name=name, type=event_type, reason=reason, message=message, timestamp=now)
            self.events.append(e)
            self._recent[key] = e
            if len(self.events) > self.MAX_EVENTS:
                self.events = self.events[self.MAX_EVENTS // 2:]
                kept = set(map(id, self.events))
                self._recent = {k: v for k, v in self._recent.items() if id(v) in kept}

    def for_object(self, obj) -> List[Event]:
        name = getattr(obj, "name", str(obj))
        return [e for e in self.events if e.name == name]

    def with_reason(self, reason: str) -> List[Event]:
        return [e for e in self.events if e.reason == reason]
