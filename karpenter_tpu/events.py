"""Event recorder.

The reference emits k8s Events through an events.Recorder for every notable
lifecycle action (pkg/cloudprovider/events/events.go,
pkg/controllers/interruption/events/events.go). This in-memory recorder
keeps the same shape: typed events attached to objects, deduplicated within
a window, queryable by tests.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.cache.ttl import Clock

NORMAL = "Normal"
WARNING = "Warning"


@dataclass
class Event:
    kind: str            # object kind
    name: str            # object name
    type: str            # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0
    count: int = 1


class Recorder:
    def __init__(self, clock: Optional[Clock] = None, dedupe_window: float = 60.0):
        self.clock = clock or Clock()
        self.dedupe_window = dedupe_window
        self._lock = threading.Lock()
        self.events: List[Event] = []

    def publish(self, obj, reason: str, message: str = "", type: str = NORMAL) -> None:
        event_type = type
        kind = getattr(obj, "KIND", "Object")
        name = getattr(obj, "name", str(obj))
        now = self.clock.now()
        with self._lock:
            for e in reversed(self.events[-50:]):
                if (
                    e.kind == kind and e.name == name and e.reason == reason
                    and e.message == message
                    and now - e.timestamp < self.dedupe_window
                ):
                    # identical events coalesce; a CHANGED message under
                    # the same reason (e.g. an unschedulable pod's cause
                    # moving from a missing claim to no-capacity) records
                    # fresh -- suppressing it would hide the new cause
                    # for the whole window
                    e.count += 1
                    return
            self.events.append(
                Event(kind=kind, name=name, type=event_type, reason=reason, message=message, timestamp=now)
            )

    def for_object(self, obj) -> List[Event]:
        name = getattr(obj, "name", str(obj))
        return [e for e in self.events if e.name == name]

    def with_reason(self, reason: str) -> List[Event]:
        return [e for e in self.events if e.reason == reason]
