"""Typed object <-> Kubernetes manifest conversion.

One converter per kind the controllers touch. Field names mirror the shipped
CRD schemas exactly (hack/crd_gen.py -- the reference's controller-gen
equivalents) and core/v1 for Pod/Node/PDB/DaemonSet. Conversions are scoped
to the fields the scheduling and reconciliation planes read; unknown fields
on incoming manifests are ignored (a real apiserver owns schema pruning).

Quantities serialize to base-unit k8s strings (cpu millicores as "1500m",
bytes as plain integers), durations to "<seconds>s" (parsing accepts any
metav1.Duration form), timestamps to RFC3339.
"""
from __future__ import annotations

import calendar
import re
import time
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.apis import (
    DaemonSet,
    Node,
    NodeClaim,
    NodePool,
    Pod,
    PodDisruptionBudget,
    TPUNodeClass,
)
from karpenter_tpu.apis.nodeclass import ImageSelectorTerm, SelectorTerm
from karpenter_tpu.apis.nodepool import Budget, Disruption, NodeClaimTemplate, NodeClassRef
from karpenter_tpu.apis.objects import APIObject, ObjectMeta
from karpenter_tpu.apis.pod import PodAffinityTerm, TopologySpreadConstraint
from karpenter_tpu.scheduling import Operator, Requirement, Requirements, Resources, Taint, Toleration
from karpenter_tpu.scheduling import resources as res

GROUP_CORE = "karpenter.sh"
GROUP_PROVIDER = "karpenter.tpu"
VERSION = "v1"

_DURATION_RE = re.compile(r"([0-9]+(?:\.[0-9]+)?)(ns|us|ms|s|m|h|d)")
_DURATION_UNITS = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


# -- scalar helpers ----------------------------------------------------------

def parse_duration(s: Optional[str]) -> Optional[float]:
    if s is None or s == "" or s == "Never":
        return None
    total = 0.0
    matched = False
    for m in _DURATION_RE.finditer(s):
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        matched = True
    if not matched:
        raise ValueError(f"invalid duration {s!r}")
    return total


def format_duration(seconds: Optional[float]) -> Optional[str]:
    if seconds is None:
        return None
    # decimal, never exponent notation: %g would emit "2.592e+06s" for a
    # 30-day expireAfter, which no duration parser accepts; int() would
    # silently turn a 500ms consolidation window into 0s
    if seconds == int(seconds):
        return f"{int(seconds)}s"
    return f"{seconds:.9f}".rstrip("0").rstrip(".") + "s"


def format_time(epoch: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def parse_time(s: Optional[str]) -> float:
    if not s:
        return 0.0
    return float(calendar.timegm(time.strptime(s[:19], "%Y-%m-%dT%H:%M:%S")))


def quantity_str(axis: str, value: float) -> str:
    if axis == res.CPU:
        return f"{int(value)}m"  # base unit is millicores
    return str(int(value))


def resources_to_map(r: Resources) -> Dict[str, str]:
    return {axis: quantity_str(axis, v) for axis, v in r.items() if v}


def resources_from_map(m: Optional[Dict[str, str]]) -> Resources:
    return Resources(dict(m or {}))


# -- requirements ------------------------------------------------------------

def requirement_to_manifest(r: Requirement) -> dict:
    out: dict = {"key": r.key}
    if r.greater_than is not None:
        out["operator"] = "Gt"
        out["values"] = [str(int(r.greater_than))]
    elif r.less_than is not None:
        out["operator"] = "Lt"
        out["values"] = [str(int(r.less_than))]
    elif r.complement and not r.values:
        out["operator"] = "Exists"
    elif r.complement:
        out["operator"] = "NotIn"
        out["values"] = sorted(r.values)
    elif r.values:
        out["operator"] = "In"
        out["values"] = sorted(r.values)
    else:
        out["operator"] = "DoesNotExist"
    if r.min_values is not None:
        out["minValues"] = int(r.min_values)
    return out


def requirement_from_manifest(m: dict) -> Requirement:
    return Requirement(
        m["key"], Operator(m["operator"]), list(m.get("values", ())),
        min_values=m.get("minValues"),
    )


def taint_to_manifest(t: Taint) -> dict:
    out = {"key": t.key, "effect": t.effect}
    if t.value:
        out["value"] = t.value
    return out


def taint_from_manifest(m: dict) -> Taint:
    return Taint(key=m["key"], effect=m.get("effect", "NoSchedule"), value=m.get("value", ""))


def toleration_to_manifest(t: Toleration) -> dict:
    out: dict = {}
    if t.key:
        out["key"] = t.key
    out["operator"] = t.operator
    if t.value:
        out["value"] = t.value
    if t.effect:
        out["effect"] = t.effect
    return out


def toleration_from_manifest(m: dict) -> Toleration:
    return Toleration(
        key=m.get("key", ""), operator=m.get("operator", "Equal"),
        value=m.get("value", ""), effect=m.get("effect", ""),
    )


# -- metadata ----------------------------------------------------------------

def meta_to_manifest(meta: ObjectMeta) -> dict:
    out: dict = {"name": meta.name}
    if meta.namespace:
        out["namespace"] = meta.namespace
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.finalizers:
        out["finalizers"] = list(meta.finalizers)
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.uid:
        out["uid"] = meta.uid
    if meta.creation_timestamp:
        out["creationTimestamp"] = format_time(meta.creation_timestamp)
    return out


def meta_from_manifest(obj: APIObject, m: dict) -> None:
    meta = m.get("metadata", {})
    obj.metadata.name = meta.get("name", obj.metadata.name)
    obj.metadata.namespace = meta.get("namespace", "")
    obj.metadata.labels = dict(meta.get("labels", {}))
    obj.metadata.annotations = dict(meta.get("annotations", {}))
    obj.metadata.finalizers = list(meta.get("finalizers", ()))
    obj.metadata.uid = meta.get("uid", obj.metadata.uid)
    rv = meta.get("resourceVersion")
    if rv is not None:
        try:
            obj.metadata.resource_version = int(rv)
        except ValueError:
            # apiserver resourceVersions are opaque strings; keep them
            # comparable by stashing the raw value separately
            obj.metadata.resource_version = 0
    obj._raw_resource_version = meta.get("resourceVersion")  # type: ignore[attr-defined]
    # only REAL owner references (carrying a uid) count: the synthetic
    # entry pod_to_manifest emits to persist owner_kind has uid "" and
    # must not make a bare rig pod look controller-managed
    obj.metadata.owner_references = [
        o["uid"] for o in meta.get("ownerReferences", ()) if o.get("uid")
    ]
    obj.metadata.creation_timestamp = parse_time(meta.get("creationTimestamp"))
    if meta.get("deletionTimestamp"):
        obj.metadata.deletion_timestamp = parse_time(meta.get("deletionTimestamp"))


def conditions_to_manifest(obj: APIObject) -> List[dict]:
    out = []
    for c in obj.status_conditions.all():
        out.append(
            {
                "type": c.type, "status": c.status, "reason": c.reason or "Unknown",
                "message": c.message, "lastTransitionTime": format_time(c.last_transition_time),
            }
        )
    return out


def conditions_from_manifest(obj: APIObject, conds: List[dict]) -> None:
    for c in conds or ():
        if c.get("status") == "True":
            obj.status_conditions.set_true(c["type"], c.get("reason", ""), c.get("message", ""))
        elif c.get("status") == "False":
            obj.status_conditions.set_false(c["type"], c.get("reason", ""), c.get("message", ""))
        else:
            obj.status_conditions.set_unknown(c["type"], c.get("reason", ""), c.get("message", ""))
        # keep the WIRE transition time: set_* stamps now(), and a
        # read-modify-write cycle re-stamping every condition would
        # advance apiserver lastTransitionTime on every node touch
        if c.get("lastTransitionTime"):
            cond = obj.status_conditions.get(c["type"])
            if cond is not None:
                cond.last_transition_time = parse_time(c["lastTransitionTime"])


# -- NodePool ----------------------------------------------------------------

def nodepool_to_manifest(p: NodePool) -> dict:
    t = p.template
    tmpl_spec: dict = {
        "nodeClassRef": {
            "group": t.node_class_ref.group, "kind": t.node_class_ref.kind,
            "name": t.node_class_ref.name,
        },
        "requirements": [requirement_to_manifest(r) for r in t.requirements],
    }
    if t.taints:
        tmpl_spec["taints"] = [taint_to_manifest(x) for x in t.taints]
    if t.startup_taints:
        tmpl_spec["startupTaints"] = [taint_to_manifest(x) for x in t.startup_taints]
    tmpl_spec["expireAfter"] = format_duration(t.expire_after) or "Never"
    if t.termination_grace_period is not None:
        tmpl_spec["terminationGracePeriod"] = format_duration(t.termination_grace_period)
    spec: dict = {
        "disruption": {
            "consolidationPolicy": p.disruption.consolidation_policy,
            "consolidateAfter": format_duration(p.disruption.consolidate_after) or "0s",
            "budgets": [
                {
                    k: v
                    for k, v in (
                        ("nodes", b.nodes),
                        ("reasons", b.reasons),
                        ("schedule", b.schedule),
                        ("duration", format_duration(b.duration)),
                    )
                    if v is not None
                }
                for b in p.disruption.budgets
            ],
        },
        "template": {
            "metadata": {"labels": dict(t.labels), "annotations": dict(t.annotations)},
            "spec": tmpl_spec,
        },
    }
    if p.weight:
        # 0 = unset: the CRD bounds weight to 1..100 when present
        spec["weight"] = p.weight
    if p.limits is not None:
        spec["limits"] = resources_to_map(p.limits)
    return {
        "apiVersion": f"{GROUP_CORE}/{VERSION}", "kind": "NodePool",
        "metadata": meta_to_manifest(p.metadata),
        "spec": spec,
        "status": {
            "resources": resources_to_map(p.status_resources),
            "conditions": conditions_to_manifest(p),
        },
    }


def nodepool_from_manifest(m: dict) -> NodePool:
    spec = m.get("spec", {})
    tmpl = spec.get("template", {})
    tmeta, tspec = tmpl.get("metadata", {}), tmpl.get("spec", {})
    ref = tspec.get("nodeClassRef", {})
    template = NodeClaimTemplate(
        labels=dict(tmeta.get("labels", {})),
        annotations=dict(tmeta.get("annotations", {})),
        requirements=[requirement_from_manifest(r) for r in tspec.get("requirements", ())],
        taints=[taint_from_manifest(x) for x in tspec.get("taints", ())],
        startup_taints=[taint_from_manifest(x) for x in tspec.get("startupTaints", ())],
        node_class_ref=NodeClassRef(
            name=ref.get("name", "default"), kind=ref.get("kind", "TPUNodeClass"),
            group=ref.get("group", GROUP_PROVIDER),
        ),
        expire_after=parse_duration(tspec.get("expireAfter")),
        termination_grace_period=parse_duration(tspec.get("terminationGracePeriod")),
    )
    d = spec.get("disruption", {})
    disruption = Disruption(
        consolidation_policy=d.get("consolidationPolicy", "WhenEmptyOrUnderutilized"),
        consolidate_after=parse_duration(d.get("consolidateAfter")) or 0.0,
        budgets=[
            Budget(
                nodes=b.get("nodes", "10%"), reasons=b.get("reasons"),
                schedule=b.get("schedule"), duration=parse_duration(b.get("duration")),
            )
            for b in d.get("budgets", ())
        ]
        or [Budget()],
    )
    pool = NodePool(
        m["metadata"]["name"],
        limits=resources_from_map(spec["limits"]) if "limits" in spec else None,
        weight=int(spec.get("weight", 0)),
        template=template,
        disruption=disruption,
    )
    meta_from_manifest(pool, m)
    status = m.get("status", {})
    pool.status_resources = resources_from_map(status.get("resources"))
    conditions_from_manifest(pool, status.get("conditions"))
    return pool


# -- NodeClaim ---------------------------------------------------------------

def nodeclaim_to_manifest(c: NodeClaim) -> dict:
    spec: dict = {
        "nodeClassRef": {
            "group": c.node_class_ref.group, "kind": c.node_class_ref.kind,
            "name": c.node_class_ref.name,
        },
        "requirements": [requirement_to_manifest(r) for r in c.requirements],
        "resources": {"requests": resources_to_map(c.resources_requested)},
        "expireAfter": format_duration(c.expire_after) or "Never",
    }
    if c.taints:
        spec["taints"] = [taint_to_manifest(x) for x in c.taints]
    if c.startup_taints:
        spec["startupTaints"] = [taint_to_manifest(x) for x in c.startup_taints]
    if c.termination_grace_period is not None:
        spec["terminationGracePeriod"] = format_duration(c.termination_grace_period)
    return {
        "apiVersion": f"{GROUP_CORE}/{VERSION}", "kind": "NodeClaim",
        "metadata": meta_to_manifest(c.metadata),
        "spec": spec,
        "status": {
            "providerID": c.provider_id, "nodeName": c.node_name, "imageID": c.image_id,
            "capacity": resources_to_map(c.capacity),
            "allocatable": resources_to_map(c.allocatable),
            "conditions": conditions_to_manifest(c),
        },
    }


def nodeclaim_from_manifest(m: dict) -> NodeClaim:
    spec = m.get("spec", {})
    ref = spec.get("nodeClassRef", {})
    claim = NodeClaim(
        m["metadata"]["name"],
        requirements=[requirement_from_manifest(r) for r in spec.get("requirements", ())],
        resources_requested=resources_from_map(spec.get("resources", {}).get("requests")),
        node_class_ref=NodeClassRef(
            name=ref.get("name", "default"), kind=ref.get("kind", "TPUNodeClass"),
            group=ref.get("group", GROUP_PROVIDER),
        ),
        taints=[taint_from_manifest(x) for x in spec.get("taints", ())],
        startup_taints=[taint_from_manifest(x) for x in spec.get("startupTaints", ())],
        expire_after=parse_duration(spec.get("expireAfter")),
    )
    claim.termination_grace_period = parse_duration(spec.get("terminationGracePeriod"))
    meta_from_manifest(claim, m)
    status = m.get("status", {})
    claim.provider_id = status.get("providerID", "")
    claim.node_name = status.get("nodeName", "")
    claim.image_id = status.get("imageID", "")
    claim.capacity = resources_from_map(status.get("capacity"))
    claim.allocatable = resources_from_map(status.get("allocatable"))
    conditions_from_manifest(claim, status.get("conditions"))
    return claim


# -- TPUNodeClass ------------------------------------------------------------

def _term_to_manifest(t: SelectorTerm) -> dict:
    out: dict = {}
    if t.tags:
        out["tags"] = dict(t.tags)
    if t.id:
        out["id"] = t.id
    if getattr(t, "name", ""):
        out["name"] = t.name
    if getattr(t, "alias", ""):
        out["alias"] = t.alias
    return out


def _term_from_manifest(m: dict, image: bool = False) -> SelectorTerm:
    if image:
        return ImageSelectorTerm(
            tags=dict(m.get("tags", {})), id=m.get("id", ""),
            name=m.get("name", ""), alias=m.get("alias", ""),
        )
    return SelectorTerm(
        tags=dict(m.get("tags", {})), id=m.get("id", ""), name=m.get("name", "")
    )


def nodeclass_to_manifest(nc: TPUNodeClass) -> dict:
    k = nc.kubelet
    kubelet: dict = {}
    if k.max_pods is not None:
        kubelet["maxPods"] = k.max_pods
    if k.pods_per_core is not None:
        kubelet["podsPerCore"] = k.pods_per_core
    for name, val in (
        ("systemReserved", k.system_reserved), ("kubeReserved", k.kube_reserved),
        ("evictionHard", k.eviction_hard), ("evictionSoft", k.eviction_soft),
        ("evictionSoftGracePeriod", k.eviction_soft_grace_period),
    ):
        if val:
            kubelet[name] = dict(val)
    if k.cluster_dns:
        kubelet["clusterDNS"] = list(k.cluster_dns)
    spec: dict = {
        "imageFamily": nc.image_family,
        "imageSelectorTerms": [_term_to_manifest(t) for t in nc.image_selector_terms],
        "subnetSelectorTerms": [_term_to_manifest(t) for t in nc.subnet_selector_terms],
        "securityGroupSelectorTerms": [_term_to_manifest(t) for t in nc.security_group_selector_terms],
    }
    if nc.capacity_reservation_selector_terms:
        spec["capacityReservationSelectorTerms"] = [
            _term_to_manifest(t) for t in nc.capacity_reservation_selector_terms
        ]
    if nc.role:
        spec["role"] = nc.role
    if nc.instance_profile:
        spec["instanceProfile"] = nc.instance_profile
    if nc.user_data:
        spec["userData"] = nc.user_data
    if nc.tags:
        spec["tags"] = dict(nc.tags)
    if kubelet:
        spec["kubelet"] = kubelet
    if nc.block_device_mappings:
        spec["blockDeviceMappings"] = [
            {"deviceName": b.device_name, "volumeSize": f"{b.volume_size_gib}Gi",
             "volumeType": b.volume_type}
            for b in nc.block_device_mappings
        ]
    if nc.metadata_http_tokens:
        spec["metadataOptions"] = {"httpTokens": nc.metadata_http_tokens}
    if nc.associate_public_ip is not None:
        spec["associatePublicIPAddress"] = nc.associate_public_ip
    status: dict = {"conditions": conditions_to_manifest(nc)}
    if nc.status_subnets:
        status["subnets"] = [
            {"id": s.id, "zone": s.zone, "zoneID": s.zone_id} for s in nc.status_subnets
        ]
    if nc.status_security_groups:
        status["securityGroups"] = [
            {"id": s.id, "name": s.name} for s in nc.status_security_groups
        ]
    if nc.status_images:
        status["images"] = [
            {
                "id": i.id, "name": i.name,
                "requirements": [requirement_to_manifest(r) for r in i.requirements],
            }
            for i in nc.status_images
        ]
    if nc.status_capacity_reservations:
        status["capacityReservations"] = [
            {
                "id": c.id, "instanceType": c.instance_type, "zone": c.zone,
                "ownerID": c.owner_id, "reservationType": c.reservation_type,
                "state": c.state, "availableCount": c.available_count,
                **({"endTime": format_time(c.end_time)} if c.end_time else {}),
            }
            for c in nc.status_capacity_reservations
        ]
    if nc.status_instance_profile:
        status["instanceProfile"] = nc.status_instance_profile
    return {
        "apiVersion": f"{GROUP_PROVIDER}/{VERSION}", "kind": "TPUNodeClass",
        "metadata": meta_to_manifest(nc.metadata),
        "spec": spec,
        "status": status,
    }


def nodeclass_from_manifest(m: dict) -> TPUNodeClass:
    from karpenter_tpu.apis.nodeclass import BlockDeviceMapping

    spec = m.get("spec", {})
    nc = TPUNodeClass(m["metadata"]["name"])
    nc.image_family = spec.get("imageFamily", nc.image_family)
    if "imageSelectorTerms" in spec:
        nc.image_selector_terms = [_term_from_manifest(t, image=True) for t in spec["imageSelectorTerms"]]
    if "subnetSelectorTerms" in spec:
        nc.subnet_selector_terms = [_term_from_manifest(t) for t in spec["subnetSelectorTerms"]]
    if "securityGroupSelectorTerms" in spec:
        nc.security_group_selector_terms = [_term_from_manifest(t) for t in spec["securityGroupSelectorTerms"]]
    if "capacityReservationSelectorTerms" in spec:
        nc.capacity_reservation_selector_terms = [
            _term_from_manifest(t) for t in spec["capacityReservationSelectorTerms"]
        ]
    nc.role = spec.get("role", "")
    nc.instance_profile = spec.get("instanceProfile", "")
    nc.user_data = spec.get("userData", "")
    nc.tags = dict(spec.get("tags", {}))
    k = spec.get("kubelet", {})
    nc.kubelet.max_pods = k.get("maxPods")
    nc.kubelet.pods_per_core = k.get("podsPerCore")
    nc.kubelet.system_reserved = dict(k.get("systemReserved", {}))
    nc.kubelet.kube_reserved = dict(k.get("kubeReserved", {}))
    nc.kubelet.eviction_hard = dict(k.get("evictionHard", {}))
    nc.kubelet.eviction_soft = dict(k.get("evictionSoft", {}))
    nc.kubelet.eviction_soft_grace_period = dict(k.get("evictionSoftGracePeriod", {}))
    nc.kubelet.cluster_dns = list(k.get("clusterDNS", ()))
    if "blockDeviceMappings" in spec:
        nc.block_device_mappings = [
            BlockDeviceMapping(
                device_name=b.get("deviceName", ""),
                volume_size_gib=int(str(b.get("volumeSize", "0Gi")).rstrip("Gi") or 0),
                volume_type=b.get("volumeType", "ssd"),
            )
            for b in spec["blockDeviceMappings"]
        ]
    nc.metadata_http_tokens = spec.get("metadataOptions", {}).get("httpTokens", nc.metadata_http_tokens)
    if "associatePublicIPAddress" in spec:
        nc.associate_public_ip = spec["associatePublicIPAddress"]
    meta_from_manifest(nc, m)
    status = m.get("status", {})
    conditions_from_manifest(nc, status.get("conditions"))
    from karpenter_tpu.apis.nodeclass import (
        CapacityReservationStatus,
        ImageStatus,
        SecurityGroupStatus,
        SubnetStatus,
    )

    nc.status_subnets = [
        SubnetStatus(id=s.get("id", ""), zone=s.get("zone", ""), zone_id=s.get("zoneID", ""))
        for s in status.get("subnets", ())
    ]
    nc.status_security_groups = [
        SecurityGroupStatus(id=s.get("id", ""), name=s.get("name", ""))
        for s in status.get("securityGroups", ())
    ]
    nc.status_images = [
        ImageStatus(
            id=i.get("id", ""), name=i.get("name", ""),
            requirements=[requirement_from_manifest(r) for r in i.get("requirements", ())],
        )
        for i in status.get("images", ())
    ]
    nc.status_capacity_reservations = [
        CapacityReservationStatus(
            id=c.get("id", ""), instance_type=c.get("instanceType", ""),
            zone=c.get("zone", ""), owner_id=c.get("ownerID", ""),
            reservation_type=c.get("reservationType", "default"),
            state=c.get("state", "active"),
            end_time=parse_time(c["endTime"]) if c.get("endTime") else None,
            available_count=int(c.get("availableCount", 0)),
        )
        for c in status.get("capacityReservations", ())
    ]
    nc.status_instance_profile = status.get("instanceProfile", "")
    return nc


# -- Pod ---------------------------------------------------------------------

def pod_to_manifest(p: Pod) -> dict:
    spec: dict = {
        "containers": [
            {
                "name": "main",
                "resources": {"requests": resources_to_map(p.requests)}
                | ({"limits": resources_to_map(p.limits)} if any(v for _, v in p.limits.items()) else {}),
            }
        ],
    }
    if p.node_selector:
        spec["nodeSelector"] = dict(p.node_selector)
    if p.tolerations:
        spec["tolerations"] = [toleration_to_manifest(t) for t in p.tolerations]
    affinity: dict = {}
    if p.node_affinity_terms:
        affinity["nodeAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {"matchExpressions": [requirement_to_manifest(r) for r in term]}
                    for term in p.node_affinity_terms
                ]
            }
        }
    if p.preferred_node_affinity_terms:
        affinity.setdefault("nodeAffinity", {})[
            "preferredDuringSchedulingIgnoredDuringExecution"
        ] = [
            {
                "weight": w,
                "preference": {"matchExpressions": [requirement_to_manifest(r) for r in term]},
            }
            for w, term in p.preferred_node_affinity_terms
        ]

    def aff_term(t: PodAffinityTerm) -> dict:
        return {
            "labelSelector": {"matchLabels": dict(t.label_selector)},
            "topologyKey": t.topology_key,
        }

    pos = [t for t in p.affinity_terms if not t.anti]
    neg = [t for t in p.affinity_terms if t.anti]
    pref_pos = [(w, t) for w, t in p.preferred_affinity_terms if not t.anti]
    pref_neg = [(w, t) for w, t in p.preferred_affinity_terms if t.anti]
    if pos or pref_pos:
        pa: dict = {}
        if pos:
            pa["requiredDuringSchedulingIgnoredDuringExecution"] = [aff_term(t) for t in pos]
        if pref_pos:
            pa["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w, "podAffinityTerm": aff_term(t)} for w, t in pref_pos
            ]
        affinity["podAffinity"] = pa
    if neg or pref_neg:
        paa: dict = {}
        if neg:
            paa["requiredDuringSchedulingIgnoredDuringExecution"] = [aff_term(t) for t in neg]
        if pref_neg:
            paa["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w, "podAffinityTerm": aff_term(t)} for w, t in pref_neg
            ]
        affinity["podAntiAffinity"] = paa
    if affinity:
        spec["affinity"] = affinity
    if p.topology_spread:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": t.max_skew, "topologyKey": t.topology_key,
                "whenUnsatisfiable": t.when_unsatisfiable,
                "labelSelector": {"matchLabels": dict(t.label_selector)},
            }
            for t in p.topology_spread
        ]
    if p.priority:
        spec["priority"] = p.priority
    if p.scheduling_gates:
        spec["schedulingGates"] = [{"name": g} for g in p.scheduling_gates]
    if p.volume_claims:
        spec["volumes"] = [
            {"name": f"vol-{i}", "persistentVolumeClaim": {"claimName": ref}}
            for i, ref in enumerate(p.volume_claims)
        ]
    if p.node_name:
        spec["nodeName"] = p.node_name
    meta = meta_to_manifest(p.metadata)
    if p.owner_kind:
        meta["ownerReferences"] = [
            {"apiVersion": "apps/v1", "kind": p.owner_kind, "name": "owner", "uid": "", "controller": True}
        ]
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": meta,
        "spec": spec,
        "status": {"phase": p.phase},
    }


def pod_from_manifest(m: dict) -> Pod:
    spec = m.get("spec", {})
    requests = Resources()
    limits = Resources()
    for c in spec.get("containers", ()):
        rr = c.get("resources", {})
        requests = requests + resources_from_map(rr.get("requests"))
        limits = limits + resources_from_map(rr.get("limits"))
    aff = spec.get("affinity", {})
    na = aff.get("nodeAffinity", {})
    nat = [
        [requirement_from_manifest(e) for e in term.get("matchExpressions", ())]
        for term in na.get("requiredDuringSchedulingIgnoredDuringExecution", {}).get(
            "nodeSelectorTerms", ()
        )
    ]
    pref_nat = [
        (int(e.get("weight", 1)),
         [requirement_from_manifest(x) for x in e.get("preference", {}).get("matchExpressions", ())])
        for e in na.get("preferredDuringSchedulingIgnoredDuringExecution", ())
    ]

    def read_aff(block: dict, anti: bool) -> Tuple[list, list]:
        req, pref = [], []
        for t in block.get("requiredDuringSchedulingIgnoredDuringExecution", ()):
            req.append(
                PodAffinityTerm(
                    label_selector=dict(t.get("labelSelector", {}).get("matchLabels", {})),
                    topology_key=t.get("topologyKey", "kubernetes.io/hostname"), anti=anti,
                )
            )
        for e in block.get("preferredDuringSchedulingIgnoredDuringExecution", ()):
            t = e.get("podAffinityTerm", {})
            pref.append(
                (
                    int(e.get("weight", 1)),
                    PodAffinityTerm(
                        label_selector=dict(t.get("labelSelector", {}).get("matchLabels", {})),
                        topology_key=t.get("topologyKey", "kubernetes.io/hostname"), anti=anti,
                    ),
                )
            )
        return req, pref

    pos_req, pos_pref = read_aff(aff.get("podAffinity", {}), anti=False)
    neg_req, neg_pref = read_aff(aff.get("podAntiAffinity", {}), anti=True)
    owners = m.get("metadata", {}).get("ownerReferences", ())
    owner_kind = owners[0]["kind"] if owners else ""
    pod = Pod(
        m["metadata"]["name"],
        namespace=m.get("metadata", {}).get("namespace", "default"),
        requests=requests,
        limits=limits,
        node_selector=spec.get("nodeSelector"),
        node_affinity_terms=nat,
        preferred_node_affinity_terms=pref_nat,
        tolerations=[toleration_from_manifest(t) for t in spec.get("tolerations", ())],
        topology_spread=[
            TopologySpreadConstraint(
                max_skew=int(t.get("maxSkew", 1)),
                topology_key=t.get("topologyKey", ""),
                when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=dict(t.get("labelSelector", {}).get("matchLabels", {})),
            )
            for t in spec.get("topologySpreadConstraints", ())
        ],
        affinity_terms=pos_req + neg_req,
        preferred_affinity_terms=pos_pref + neg_pref,
        priority=int(spec.get("priority", 0)),
        labels=m.get("metadata", {}).get("labels"),
        annotations=m.get("metadata", {}).get("annotations"),
        owner_kind=owner_kind,
        scheduling_gates=[g.get("name", "") for g in spec.get("schedulingGates", ())],
        volume_claims=[
            v["persistentVolumeClaim"]["claimName"]
            for v in spec.get("volumes", ())
            if v.get("persistentVolumeClaim", {}).get("claimName")
        ],
    )
    meta_from_manifest(pod, m)
    pod.node_name = spec.get("nodeName", "")
    pod.phase = m.get("status", {}).get("phase", "Pending")
    return pod


# -- Node --------------------------------------------------------------------

def _node_status_map(r: Resources) -> Dict[str, str]:
    """resources_to_map + the attach-budget default: emitting the axis on
    the WRITE side keeps to->from->to round-trips idempotent with the
    read-side defaulting in node_resources_from_map."""
    out = resources_to_map(r)
    if res.ATTACHABLE_VOLUMES not in out and out:
        out[res.ATTACHABLE_VOLUMES] = quantity_str(
            res.ATTACHABLE_VOLUMES, DEFAULT_NODE_ATTACH_LIMIT
        )
    return out


def node_to_manifest(n: Node) -> dict:
    spec: dict = {}
    if n.taints:
        spec["taints"] = [taint_to_manifest(t) for t in n.taints]
    if n.unschedulable:
        spec["unschedulable"] = True
    if n.provider_id:
        spec["providerID"] = n.provider_id
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": meta_to_manifest(n.metadata),
        "spec": spec,
        "status": {
            "capacity": _node_status_map(n.capacity),
            "allocatable": _node_status_map(n.allocatable),
            # the FULL condition set rides the wire: auto-repair reads
            # impairment conditions (Ready/AcceleratedHardwareReady,
            # cloudprovider.repair_policies) off the node, and dropping
            # them here would blind it on a real bus. The kubelet-style
            # Ready condition is synthesized from n.ready only when no
            # explicit Ready condition exists.
            "conditions": conditions_to_manifest(n) + (
                []
                if any(c.type == "Ready" for c in n.status_conditions.all())
                else [{"type": "Ready", "status": "True" if n.ready else "False"}]
            ),
        },
    }


# attach budget assumed for nodes that report NO attachable-volumes-*
# key: modern CSI drivers publish limits on CSINode objects, not in node
# status -- KubeCluster._overlay_csi_limits replaces this default with
# the node's real CSINode driver count when one exists; this constant
# covers nodes with no CSINode (or no driver reporting a count), where
# leaving the axis at 0 would make every claim-carrying pod unfittable.
# 8 is the FLOOR of providers/instancetype/types.volume_attach_limit
# (max(8, slots - nics - 1)), so the assumption only ever under-packs:
# NIC-rich mid-size shapes bottom out at 8, and assuming more than a
# node can actually attach would over-pack volume-backed pods onto it
# (ADVICE round 4).
DEFAULT_NODE_ATTACH_LIMIT = 8.0


def node_resources_from_map(m: Optional[Dict[str, str]]) -> Resources:
    """Node capacity/allocatable maps come from kubelets, whose vocabulary
    is wider than the solver's dense axes: `attachable-volumes-<driver>`
    keys fold onto the attachable-volumes axis (smallest driver limit
    wins, matching how the core takes the binding driver's CSINode
    limit; absent entirely -> DEFAULT_NODE_ATTACH_LIMIT, see above), and
    keys with no axis (hugepages-*, vendor extended resources) are
    dropped rather than poisoning to_vector."""
    out: Dict[str, str] = {}
    attach: Optional[float] = None
    for k, v in (m or {}).items():
        if k.startswith("attachable-volumes-"):
            n = float(res.parse_quantity(v))
            attach = n if attach is None else min(attach, n)
        elif k in res.AXIS_INDEX:
            out[k] = v
    r = Resources(out)
    if attach is None and out and res.ATTACHABLE_VOLUMES not in r.keys():
        attach = DEFAULT_NODE_ATTACH_LIMIT
    if attach is not None and res.ATTACHABLE_VOLUMES not in r.keys():
        r = r + Resources.from_base_units({res.ATTACHABLE_VOLUMES: attach})
    return r


def node_from_manifest(m: dict) -> Node:
    spec = m.get("spec", {})
    status = m.get("status", {})
    n = Node(
        m["metadata"]["name"],
        labels=m.get("metadata", {}).get("labels"),
        capacity=node_resources_from_map(status.get("capacity")),
        allocatable=node_resources_from_map(status.get("allocatable")),
        taints=[taint_from_manifest(t) for t in spec.get("taints", ())],
        provider_id=spec.get("providerID", ""),
    )
    meta_from_manifest(n, m)
    n.unschedulable = bool(spec.get("unschedulable", False))
    # the SYNTHESIZED Ready condition (node_to_manifest emits it with NO
    # reason key when no explicit Ready condition exists) stays out of
    # status_conditions -- n.ready carries it; every real condition
    # (always serialized WITH a reason key) round-trips, including
    # explicit Ready ones the repair policies read
    conditions_from_manifest(
        n,
        [c for c in status.get("conditions", ()) if c.get("type") != "Ready" or "reason" in c],
    )
    n.ready = any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in status.get("conditions", ())
    )
    return n


# -- PodDisruptionBudget -----------------------------------------------------

def pdb_to_manifest(p: PodDisruptionBudget) -> dict:
    spec: dict = {"selector": {"matchLabels": dict(p.selector)}}
    if p.min_available is not None:
        spec["minAvailable"] = p.min_available
    if p.max_unavailable is not None:
        spec["maxUnavailable"] = p.max_unavailable
    return {
        "apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
        "metadata": meta_to_manifest(p.metadata),
        "spec": spec,
    }


def pdb_from_manifest(m: dict) -> PodDisruptionBudget:
    spec = m.get("spec", {})
    p = PodDisruptionBudget(
        m["metadata"]["name"],
        namespace=m.get("metadata", {}).get("namespace", "default"),
        selector=dict(spec.get("selector", {}).get("matchLabels", {})),
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
    )
    meta_from_manifest(p, m)
    return p


# -- DaemonSet ---------------------------------------------------------------

def daemonset_to_manifest(d: DaemonSet) -> dict:
    pod_spec: dict = {
        "containers": [
            {"name": "main", "resources": {"requests": resources_to_map(d.requests)}}
        ]
    }
    if d.node_selector:
        pod_spec["nodeSelector"] = dict(d.node_selector)
    if d.tolerations:
        pod_spec["tolerations"] = [toleration_to_manifest(t) for t in d.tolerations]
    return {
        "apiVersion": "apps/v1", "kind": "DaemonSet",
        "metadata": meta_to_manifest(d.metadata),
        "spec": {"template": {"spec": pod_spec}},
    }


def daemonset_from_manifest(m: dict) -> DaemonSet:
    pod_spec = m.get("spec", {}).get("template", {}).get("spec", {})
    requests = Resources()
    for c in pod_spec.get("containers", ()):
        requests = requests + resources_from_map(c.get("resources", {}).get("requests"))
    d = DaemonSet(
        m["metadata"]["name"],
        namespace=m.get("metadata", {}).get("namespace", "kube-system"),
        requests=requests,
        node_selector=pod_spec.get("nodeSelector"),
        tolerations=[toleration_from_manifest(t) for t in pod_spec.get("tolerations", ())],
    )
    meta_from_manifest(d, m)
    return d


# -- PersistentVolumeClaim / StorageClass ------------------------------------
# The model carries the PV's zone on the claim (apis/storage: bound_zone);
# on the wire -- where topology lives on the PV object this framework does
# not model -- it rides a claim annotation, so round-trips are lossless.

BOUND_ZONE_ANNOTATION = "storage.karpenter.tpu/bound-zone"


def pvc_to_manifest(c) -> dict:
    meta = meta_to_manifest(c.metadata)
    if c.bound_zone is not None:
        meta.setdefault("annotations", {})[BOUND_ZONE_ANNOTATION] = c.bound_zone
    spec: dict = {
        "accessModes": list(c.access_modes),
        "resources": {"requests": {"storage": c.storage_request}},
    }
    if c.storage_class_name:
        spec["storageClassName"] = c.storage_class_name
    if c.volume_name:
        spec["volumeName"] = c.volume_name
    return {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": meta, "spec": spec,
        "status": {"phase": "Bound" if c.bound else "Pending"},
    }


def pvc_from_manifest(m: dict):
    from karpenter_tpu.apis.storage import PersistentVolumeClaim

    spec = m.get("spec", {})
    ann = m.get("metadata", {}).get("annotations", {}) or {}
    c = PersistentVolumeClaim(
        m["metadata"]["name"],
        namespace=m.get("metadata", {}).get("namespace", "default"),
        storage_class_name=spec.get("storageClassName", "") or "",
        bound_zone=ann.get(BOUND_ZONE_ANNOTATION),
        volume_name=spec.get("volumeName", "") or "",
        access_modes=spec.get("accessModes", ("ReadWriteOnce",)),
        storage_request=spec.get("resources", {}).get("requests", {}).get("storage", "1Gi"),
    )
    meta_from_manifest(c, m)
    return c


def csinode_to_manifest(c) -> dict:
    return {
        "apiVersion": "storage.k8s.io/v1", "kind": "CSINode",
        "metadata": meta_to_manifest(c.metadata),
        "spec": {
            "drivers": [
                {"name": d, "nodeID": c.metadata.name}
                | ({"allocatable": {"count": n}} if n is not None else {})
                for d, n in c.drivers
            ]
        },
    }


def csinode_from_manifest(m: dict):
    from karpenter_tpu.apis.storage import CSINode

    c = CSINode(
        m["metadata"]["name"],
        drivers=[
            (d.get("name", ""), d.get("allocatable", {}).get("count"))
            for d in m.get("spec", {}).get("drivers", ())
        ],
    )
    meta_from_manifest(c, m)
    return c


def storageclass_to_manifest(s) -> dict:
    return {
        "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
        "metadata": meta_to_manifest(s.metadata),
        "provisioner": s.provisioner,
        "volumeBindingMode": s.binding_mode,
    }


def storageclass_from_manifest(m: dict):
    from karpenter_tpu.apis.storage import StorageClass

    s = StorageClass(
        m["metadata"]["name"],
        # the Kubernetes API defaults an unset volumeBindingMode to
        # Immediate -- mirroring that here is what makes VolumeIndex
        # treat unbound claims of such classes as blocked
        binding_mode=m.get("volumeBindingMode", "Immediate"),
        provisioner=m.get("provisioner", ""),
    )
    meta_from_manifest(s, m)
    return s


# -- Lease (leader election) -------------------------------------------------

def lease_to_manifest(l) -> dict:
    return {
        "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
        "metadata": meta_to_manifest(l.metadata),
        "spec": {
            "holderIdentity": l.holder,
            "renewTime": format_time(l.renew_deadline) if l.renew_deadline else None,
            # the fencing epoch rides the REAL Lease field for it:
            # leaseTransitions counts holder changes, which is exactly when
            # the epoch bumps (operator/election.py)
            "leaseTransitions": getattr(l, "epoch", 0),
        },
    }


def lease_from_manifest(m: dict):
    from karpenter_tpu.apis.objects import Lease

    spec = m.get("spec", {})
    l = Lease(
        m["metadata"]["name"],
        holder=spec.get("holderIdentity", "") or "",
        renew_deadline=parse_time(spec.get("renewTime")),
        epoch=int(spec.get("leaseTransitions") or 0),
    )
    meta_from_manifest(l, m)
    return l


# -- ProvisioningIntent (crash-consistency journal) ---------------------------

def intent_to_manifest(i) -> dict:
    return {
        "apiVersion": f"{GROUP_PROVIDER}/{VERSION}", "kind": "ProvisioningIntent",
        "metadata": meta_to_manifest(i.metadata),
        "spec": {
            "op": i.op,
            "claimName": i.claim_name,
            "token": i.token,
            "epoch": i.epoch,
            "providerID": i.provider_id or None,
        },
    }


def intent_from_manifest(m: dict):
    from karpenter_tpu.apis.objects import ProvisioningIntent

    spec = m.get("spec", {})
    i = ProvisioningIntent(
        m["metadata"]["name"],
        op=spec.get("op", ProvisioningIntent.OP_LAUNCH),
        claim_name=spec.get("claimName", ""),
        token=spec.get("token", ""),
        epoch=int(spec.get("epoch") or 0),
        provider_id=spec.get("providerID") or "",
    )
    meta_from_manifest(i, m)
    return i


# -- registry ----------------------------------------------------------------

class KindInfo:
    def __init__(self, kind, api_version, plural, namespaced, to_manifest, from_manifest, status_subresource=False):
        self.kind = kind
        self.api_version = api_version
        self.plural = plural
        self.namespaced = namespaced
        self.to_manifest = to_manifest
        self.from_manifest = from_manifest
        self.status_subresource = status_subresource

    def base_path(self, namespace: str = "") -> str:
        if "/" in self.api_version:
            root = f"/apis/{self.api_version}"
        else:
            root = f"/api/{self.api_version}"
        if self.namespaced:
            return f"{root}/namespaces/{namespace or 'default'}/{self.plural}"
        return f"{root}/{self.plural}"

    def list_path(self) -> str:
        """Cluster-wide collection path: LISTs span ALL namespaces (the
        in-memory store is namespace-agnostic; a default-namespace-only
        view would hide workloads and mis-count node usage)."""
        if "/" in self.api_version:
            return f"/apis/{self.api_version}/{self.plural}"
        return f"/api/{self.api_version}/{self.plural}"


REGISTRY: Dict[type, KindInfo] = {
    NodePool: KindInfo(
        NodePool, f"{GROUP_CORE}/{VERSION}", "nodepools", False,
        nodepool_to_manifest, nodepool_from_manifest, status_subresource=True,
    ),
    NodeClaim: KindInfo(
        NodeClaim, f"{GROUP_CORE}/{VERSION}", "nodeclaims", False,
        nodeclaim_to_manifest, nodeclaim_from_manifest, status_subresource=True,
    ),
    TPUNodeClass: KindInfo(
        TPUNodeClass, f"{GROUP_PROVIDER}/{VERSION}", "tpunodeclasses", False,
        nodeclass_to_manifest, nodeclass_from_manifest, status_subresource=True,
    ),
    Pod: KindInfo(Pod, "v1", "pods", True, pod_to_manifest, pod_from_manifest),
    # nodes/status is a real subresource (the kubelet's seam); the kwok
    # lifecycle writes readiness/capacity through it
    Node: KindInfo(
        Node, "v1", "nodes", False, node_to_manifest, node_from_manifest,
        status_subresource=True,
    ),
    PodDisruptionBudget: KindInfo(
        PodDisruptionBudget, "policy/v1", "poddisruptionbudgets", True,
        pdb_to_manifest, pdb_from_manifest,
    ),
    DaemonSet: KindInfo(
        DaemonSet, "apps/v1", "daemonsets", True, daemonset_to_manifest, daemonset_from_manifest
    ),
}

from karpenter_tpu.apis.storage import PersistentVolumeClaim as _PVC  # noqa: E402
from karpenter_tpu.apis.storage import StorageClass as _SC  # noqa: E402

REGISTRY[_PVC] = KindInfo(
    _PVC, "v1", "persistentvolumeclaims", True, pvc_to_manifest, pvc_from_manifest
)
REGISTRY[_SC] = KindInfo(
    _SC, "storage.k8s.io/v1", "storageclasses", False,
    storageclass_to_manifest, storageclass_from_manifest,
)

from karpenter_tpu.apis.storage import CSINode as _CSINode  # noqa: E402

REGISTRY[_CSINode] = KindInfo(
    _CSINode, "storage.k8s.io/v1", "csinodes", False,
    csinode_to_manifest, csinode_from_manifest,
)

from karpenter_tpu.apis.objects import Lease as _Lease  # noqa: E402

REGISTRY[_Lease] = KindInfo(
    _Lease, "coordination.k8s.io/v1", "leases", True, lease_to_manifest, lease_from_manifest
)

from karpenter_tpu.apis.objects import ProvisioningIntent as _Intent  # noqa: E402

REGISTRY[_Intent] = KindInfo(
    _Intent, f"{GROUP_PROVIDER}/{VERSION}", "provisioningintents", False,
    intent_to_manifest, intent_from_manifest,
)
