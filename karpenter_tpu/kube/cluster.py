"""KubeCluster: the `Cluster` surface over a REAL Kubernetes apiserver.

VERDICT round 3, item 3 / missing #2: everything in the framework runs
against the in-memory store; this adapter implements the same surface
(create / get / list / update / delete / finalizers / field indexes /
event handlers / relational pod-node queries) over a live apiserver via
karpenter_tpu.kube.client, so the decision plane is untouched while the
coordination bus becomes the real thing (reference:
`cmd/controller/main.go:30-84` builds everything on controller-runtime's
client the same way).

Semantics mapping:
- optimistic concurrency: metadata.resourceVersion rides the manifest;
  a 409 surfaces as kwok.cluster.Conflict (same type the in-memory store
  raises), so controller retry loops work unchanged.
- admission: the SHIPPED CRD manifests carry the CEL rules
  (apis/crds/*.yaml, generated from the same invariants
  apis/validation.py enforces in-memory) -- a real apiserver runs them at
  admission, so this adapter does NOT re-validate client-side.
- finalizers/deletion: the apiserver owns deletionTimestamp semantics;
  delete() and remove_finalizer() translate directly.
- reads are LIVE (one GET/LIST per call): this seam is about correctness
  against a real bus, not the 100 ms solve path -- the solver never reads
  through it mid-tick. `watch_events()` starts background watches that
  feed on_event handlers for event-driven ticking.
- status updates go through the /status subresource for the CRDs (the
  generated manifests enable it), mirroring the controller-runtime
  status-writer split.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple, Type

from karpenter_tpu.apis import Node, NodeClaim, Pod
from karpenter_tpu.apis.objects import APIObject
from karpenter_tpu.cache.ttl import Clock
from karpenter_tpu.kube import convert
from karpenter_tpu.kube.client import ApiError, Conflict as HttpConflict, KubeClient, NotFound as HttpNotFound
from karpenter_tpu.kwok.cluster import AlreadyExists, Conflict, NotFound, RelationalQueries
from karpenter_tpu.logging import ChangeMonitor, get_logger
from karpenter_tpu.scheduling import Resources

EventHandler = Callable[[str, APIObject], None]


class KubeCluster(RelationalQueries):
    log = get_logger("kube")

    def __init__(
        self, client: KubeClient, clock: Optional[Clock] = None,
        namespace: str = "default", list_cache_ttl: float = 0.25,
    ):
        self.client = client
        self.clock = clock or Clock()
        self.namespace = namespace
        self._handlers: List[EventHandler] = []
        self._indexes: Dict[Tuple[str, str], Callable[[APIObject], Optional[str]]] = {}
        self._watch_threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # short-TTL list snapshot: the binder/provisioner issue relational
        # queries (pods_on_node, node_usage) per candidate node, and each
        # is a list() -- without the snapshot one tick costs O(pods x
        # nodes) full LISTs over HTTPS. Local writes invalidate the kind's
        # snapshot so a reconciler never re-reads stale state it just
        # changed; cross-client writers are seen within ttl (the same
        # freshness window an informer cache gives controller-runtime).
        self._list_cache_ttl = list_cache_ttl
        self._list_cache: Dict[str, Tuple[float, List[dict]]] = {}
        self._list_lock = threading.Lock()
        self._csi_err_monitor = ChangeMonitor()

    # -- plumbing -----------------------------------------------------------
    def _info(self, kind: Type[APIObject]) -> convert.KindInfo:
        info = convert.REGISTRY.get(kind)
        if info is None:
            raise KeyError(f"kind {kind.__name__} has no kube mapping")
        return info

    def _obj_path(self, obj: APIObject) -> str:
        info = self._info(type(obj))
        ns = obj.metadata.namespace or self.namespace
        return f"{info.base_path(ns)}/{obj.metadata.name}"

    # -- event handlers / indexes (Cluster surface) -------------------------
    def on_event(self, handler: EventHandler) -> None:
        self._handlers.append(handler)

    def add_field_index(self, kind: Type[APIObject], name: str, key_fn) -> None:
        # indexes are LIVE list+filter here: the apiserver is the store,
        # and these controllers index small collections (claims by
        # instance id); by_index keeps the call shape identical
        self._indexes[(kind.KIND, name)] = key_fn

    def has_index(self, kind: Type[APIObject], name: str) -> bool:
        return (kind.KIND, name) in self._indexes

    def by_index(self, kind: Type[APIObject], name: str, key: str) -> List[APIObject]:
        fn = self._indexes[(kind.KIND, name)]
        return [o for o in self.list(kind) if fn(o) == key]

    # -- CRUD ---------------------------------------------------------------
    def create(self, obj: APIObject) -> APIObject:
        info = self._info(type(obj))
        manifest = info.to_manifest(obj)
        manifest["metadata"].pop("resourceVersion", None)
        ns = obj.metadata.namespace or self.namespace
        try:
            out = self.client.create(info.base_path(ns), manifest)
        except ApiError as e:
            if e.status == 409 or "AlreadyExists" in e.message:
                raise AlreadyExists(f"{info.kind.KIND}/{obj.metadata.name}") from e
            raise
        fresh = info.from_manifest(out)
        self._sync_meta(obj, fresh)
        self._invalidate(type(obj))
        if info.status_subresource and self._has_status(manifest):
            # a create cannot carry status; push it through the subresource
            try:
                self._put_status(obj)
            except ApiError:
                pass
        return obj

    def get(self, kind: Type[APIObject], name: str) -> APIObject:
        obj = self.try_get(kind, name)
        if obj is None:
            raise NotFound(f"{kind.KIND}/{name}")
        return obj

    def try_get(self, kind: Type[APIObject], name: str, _overlay: bool = True) -> Optional[APIObject]:
        """The Cluster surface is name-keyed (the in-memory store is
        namespace-agnostic): try the configured namespace first, then fall
        back to a cluster-wide scan so objects in other namespaces are
        reachable by name too. `_overlay=False` skips the CSINode join for
        internal callers that only read metadata (field-scoped updates)."""
        info = self._info(kind)
        try:
            out = self.client.get(f"{info.base_path(self.namespace)}/{name}")
            obj = info.from_manifest(out)
            if _overlay and kind is Node:
                self._overlay_csi_one(obj)
            return obj
        except HttpNotFound:
            pass
        if not info.namespaced:
            return None
        for obj in self.list(kind):
            if obj.metadata.name == name:
                return obj
        return None

    def list(self, kind: Type[APIObject], predicate=None) -> List[APIObject]:
        info = self._info(kind)
        now = self.clock.now() if self._list_cache_ttl else 0.0
        manifests = None
        if self._list_cache_ttl:
            with self._list_lock:
                hit = self._list_cache.get(info.kind.KIND)
                if hit is not None and now - hit[0] <= self._list_cache_ttl:
                    manifests = hit[1]
        if manifests is None:
            out = self.client.list(info.list_path())
            manifests = list(out.get("items", ()))
            if self._list_cache_ttl:
                with self._list_lock:
                    self._list_cache[info.kind.KIND] = (now, manifests)
        items = [info.from_manifest(m) for m in manifests]
        if kind is Node:
            self._overlay_csi_limits(items)
        if predicate is not None:
            items = [o for o in items if predicate(o)]
        return items

    def _overlay_csi_one(self, node: APIObject) -> None:
        """Single-node overlay via a targeted GET (CSINode names equal node
        names): a cluster-wide CSINode LIST per node GET would multiply
        through per-pod try_get loops."""
        from karpenter_tpu.apis.storage import CSINode

        info = self._info(CSINode)
        try:
            m = self.client.get(f"{info.base_path()}/{node.metadata.name}")
        except HttpNotFound:
            return
        except ApiError as e:
            if self._csi_err_monitor.has_changed("csinode_get", type(e).__name__):
                self.log.warning(
                    "csinode get failed; using default attach limits",
                    error=str(e)[:200],
                )
            return
        self._apply_csi_limit(node, info.from_manifest(m).attach_limit())

    def _overlay_csi_limits(self, nodes: List[APIObject]) -> None:
        """Real clusters publish attach limits on CSINode objects, not in
        node status: where a CSINode exists for a node, its smallest
        driver allocatable.count REPLACES the conversion-time default on
        the attachable-volumes axis (kept when no CSINode/driver reports
        a count)."""
        from karpenter_tpu.apis.storage import CSINode
        from karpenter_tpu.scheduling import resources as res

        try:
            csinodes = {c.metadata.name: c for c in self.list(CSINode)}
        except HttpNotFound:
            return  # apiserver without the storage API group
        except ApiError as e:
            # RBAC denial / server trouble: fall back to the conversion
            # default, but say so -- silent degradation here surfaces as
            # unexplained over/under-packing (ChangeMonitor dedups)
            if self._csi_err_monitor.has_changed("csinode_list", type(e).__name__):
                self.log.warning(
                    "csinode list failed; using default attach limits",
                    error=str(e)[:200],
                )
            return
        if not csinodes:
            return
        for n in nodes:
            c = csinodes.get(n.metadata.name)
            self._apply_csi_limit(n, c.attach_limit() if c is not None else None)

    @staticmethod
    def _apply_csi_limit(node: APIObject, limit: Optional[int]) -> None:
        from karpenter_tpu.scheduling import resources as res

        if limit is None:
            return
        for attr in ("capacity", "allocatable"):
            r = getattr(node, attr)
            delta = float(limit) - r.get(res.ATTACHABLE_VOLUMES)
            if delta:
                setattr(
                    node, attr,
                    r + Resources.from_base_units({res.ATTACHABLE_VOLUMES: delta}),
                )

    def _invalidate(self, kind: Type[APIObject]) -> None:
        with self._list_lock:
            self._list_cache.pop(kind.KIND, None)

    def update(self, obj: APIObject, expect_version: Optional[int] = None) -> APIObject:
        # pods and nodes carry server/kubelet-owned fields this framework
        # does not model (real container specs, podCIDR, ...): a whole-
        # object PUT would clobber them (or be rejected -- spec.nodeName
        # is immutable). Those kinds go through field-scoped writes.
        if isinstance(obj, Pod):
            return self._update_pod(obj)
        if isinstance(obj, Node):
            return self._update_node(obj)
        from karpenter_tpu.apis.storage import PersistentVolumeClaim as _PVC

        if isinstance(obj, _PVC):
            return self._update_pvc(obj)
        info = self._info(type(obj))
        manifest = info.to_manifest(obj)
        raw_rv = getattr(obj, "_raw_resource_version", None)
        if raw_rv:
            manifest["metadata"]["resourceVersion"] = raw_rv
        try:
            out = self.client.update(self._obj_path(obj), manifest)
        except HttpConflict as e:
            raise Conflict(f"{info.kind.KIND}/{obj.metadata.name}: stale resourceVersion") from e
        fresh = info.from_manifest(out)
        self._sync_meta(obj, fresh)
        self._invalidate(type(obj))
        if info.status_subresource:
            try:
                self._put_status(obj)
            except HttpConflict:
                pass  # next reconcile refreshes and retries, level-triggered
            except HttpNotFound:
                pass  # the update cleared the last finalizer: object is gone
        return obj

    def _update_pvc(self, claim) -> APIObject:
        """PVC spec is immutable server-side (and accessModes/storage are
        PV-controller territory this framework never changes): the only
        field the scheduler owns is the bound-zone annotation, so the
        write is an annotation merge-patch, never a whole-object PUT."""
        from karpenter_tpu.kube.convert import BOUND_ZONE_ANNOTATION

        patch = {
            "metadata": {
                "annotations": {BOUND_ZONE_ANNOTATION: claim.bound_zone}
            }
        }
        try:
            self.client.patch(self._obj_path(claim), patch)
        except HttpConflict as e:
            raise Conflict(f"PersistentVolumeClaim/{claim.metadata.name}") from e
        self._invalidate(type(claim))
        return claim

    def _meta_patch(self, obj: APIObject, server: Optional[APIObject]) -> dict:
        """RFC 7386 merge-patch deletes only keys explicitly set to null:
        removed labels/annotations must be nulled against the SERVER copy
        or they silently survive (e.g. a lapsed reservation-id label)."""

        def with_nulls(new: dict, old: dict) -> dict:
            out: dict = {k: None for k in old if k not in new}
            out.update(new)
            return out

        old_labels = dict(server.metadata.labels) if server else {}
        old_annos = dict(server.metadata.annotations) if server else {}
        return {
            "labels": with_nulls(dict(obj.metadata.labels), old_labels),
            "annotations": with_nulls(dict(obj.metadata.annotations), old_annos),
            "finalizers": list(obj.metadata.finalizers),
        }

    def _update_pod(self, pod: Pod) -> Pod:
        """Pod writes the controllers perform: unbinding (drain) and
        metadata/phase changes. spec.nodeName is immutable, so clearing it
        is EVICTION -- delete, and re-create pending when no controller
        will (mirroring unbind_pods)."""
        server = self.try_get(Pod, pod.metadata.name)
        if server is not None and server.node_name and not pod.node_name:
            self.delete_object(server)
            if not pod.metadata.owner_references:
                self._recreate_bare_pod(pod)
            self._invalidate(Pod)
            return pod
        out = self.client.patch(
            self._obj_path(pod), {"metadata": self._meta_patch(pod, server)}
        )
        # pod status is a SUBRESOURCE: a phase change on the main resource
        # would be silently dropped by a real apiserver
        if server is None or server.phase != pod.phase:
            self.client.patch(
                f"{self._obj_path(pod)}/status", {"status": {"phase": pod.phase}}
            )
        self._sync_meta(pod, self._info(Pod).from_manifest(out))
        self._invalidate(Pod)
        return pod

    def _recreate_bare_pod(self, pod: Pod) -> None:
        """Re-create an evicted OWNERLESS pod as pending (nothing else
        will); shared by the eviction-style update and unbind_pods."""
        info = self._info(Pod)
        manifest = info.to_manifest(pod)
        manifest["metadata"].pop("resourceVersion", None)
        manifest["metadata"].pop("uid", None)
        manifest["spec"].pop("nodeName", None)
        manifest["status"] = {"phase": "Pending"}
        ns = pod.metadata.namespace or self.namespace
        try:
            self.client.create(info.base_path(ns), manifest)
        except ApiError as e:
            self.log.warning(
                "bare pod re-create deferred",
                pod=pod.metadata.name, error=str(e)[:120],
            )

    def _update_node(self, node: Node) -> Node:
        """Node writes the controllers perform: cordon (unschedulable),
        taints, labels -- field-scoped so kubelet-owned spec/status fields
        survive; readiness/capacity go through nodes/status."""
        info = self._info(Node)
        server = self.try_get(Node, node.metadata.name, _overlay=False)
        patch = {
            "metadata": self._meta_patch(node, server),
            "spec": {
                "unschedulable": bool(node.unschedulable),
                "taints": [
                    {"key": t.key, "effect": t.effect, **({"value": t.value} if t.value else {})}
                    for t in node.taints
                ],
            },
        }
        out = self.client.patch(self._obj_path(node), patch)
        self._sync_meta(node, info.from_manifest(out))
        self._invalidate(Node)
        try:
            self._put_status(node)
        except (HttpConflict, HttpNotFound):
            pass
        return node

    def delete(self, kind: Type[APIObject], name: str) -> Optional[APIObject]:
        """Name-keyed delete (the in-memory surface is name-unique). The
        configured namespace is tried first; outside it the target must be
        UNAMBIGUOUS -- with several same-named objects across namespaces
        nothing is deleted (deleting 'the first one found' would destroy
        an unrelated workload). Callers holding the object use its exact
        path (delete_object)."""
        info = self._info(kind)
        try:
            self.client.delete(f"{info.base_path(self.namespace)}/{name}")
            self._invalidate(kind)
            return self.try_get(kind, name)
        except HttpNotFound:
            pass
        if not info.namespaced:
            return None
        matches = [o for o in self.list(kind) if o.metadata.name == name]
        if not matches:
            return None
        if len(matches) > 1:
            self.log.warning(
                "name-keyed delete is ambiguous across namespaces; refusing",
                kind=kind.KIND, name=name,
                namespaces=[m.metadata.namespace for m in matches],
            )
            return None
        return self.delete_object(matches[0])

    def delete_object(self, obj: APIObject) -> Optional[APIObject]:
        """Namespace-exact delete for callers holding the object."""
        try:
            self.client.delete(self._obj_path(obj))
        except HttpNotFound:
            return None
        self._invalidate(type(obj))
        return self.try_get(type(obj), obj.metadata.name)

    def remove_finalizer(self, obj: APIObject, finalizer: str) -> None:
        if finalizer in obj.metadata.finalizers:
            obj.metadata.finalizers.remove(finalizer)
        self.update(obj)

    # -- status subresource --------------------------------------------------
    def _put_status(self, obj: APIObject) -> None:
        info = self._info(type(obj))
        manifest = info.to_manifest(obj)
        if isinstance(obj, Node):
            # the attachable-volumes axis is DERIVED at read time (CSINode
            # overlay, else the conversion default) -- writing it back
            # would persist a point-in-time overlay into node status and
            # pin it past CSINode changes
            from karpenter_tpu.scheduling import resources as res

            for m in (manifest.get("status", {}).get("capacity", {}),
                      manifest.get("status", {}).get("allocatable", {})):
                m.pop(res.ATTACHABLE_VOLUMES, None)
        raw_rv = getattr(obj, "_raw_resource_version", None)
        if raw_rv:
            manifest["metadata"]["resourceVersion"] = raw_rv
        out = self.client.patch_status(self._obj_path(obj), manifest)
        self._sync_meta(obj, info.from_manifest(out))
        self._invalidate(type(obj))

    @staticmethod
    def _has_status(manifest: dict) -> bool:
        s = manifest.get("status")
        return bool(s and any(v for v in s.values()))

    @staticmethod
    def _sync_meta(obj: APIObject, fresh: APIObject) -> None:
        obj.metadata.resource_version = fresh.metadata.resource_version
        obj.metadata.uid = fresh.metadata.uid
        obj.metadata.creation_timestamp = (
            fresh.metadata.creation_timestamp or obj.metadata.creation_timestamp
        )
        obj.metadata.deletion_timestamp = fresh.metadata.deletion_timestamp
        obj._raw_resource_version = getattr(fresh, "_raw_resource_version", None)  # type: ignore[attr-defined]

    # -- watches ------------------------------------------------------------
    def watch_events(self, kinds: Optional[List[Type[APIObject]]] = None) -> None:
        """Start one background watch per kind, dispatching on_event
        handlers ('ADDED'/'MODIFIED'/'DELETED', converted object). Loops
        with resume-from-last-resourceVersion; a dropped watch relists."""
        for kind in kinds or list(convert.REGISTRY):
            t = threading.Thread(
                target=self._watch_loop, args=(kind,), daemon=True,
                name=f"kube-watch-{kind.__name__}",
            )
            t.start()
            self._watch_threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _watch_loop(self, kind: Type[APIObject]) -> None:
        info = self._info(kind)
        path = info.list_path()
        rv: Optional[str] = None
        while not self._stop.is_set():
            try:
                if rv is None:
                    out = self.client.list(path)
                    rv = out.get("metadata", {}).get("resourceVersion")
                for ev_type, manifest in self.client.watch(path, resource_version=rv):
                    if self._stop.is_set():
                        return
                    if ev_type == "ERROR":
                        # a real apiserver reports resourceVersion expiry
                        # as an HTTP-200 ERROR event carrying a Status
                        # with code 410 -- relist from scratch, never
                        # busy-loop on the stale RV
                        if manifest.get("code") == 410:
                            rv = None
                        else:
                            # unknown in-band error: back off instead of
                            # re-opening the watch in a tight loop
                            self._stop.wait(1.0)
                        break
                    mrv = manifest.get("metadata", {}).get("resourceVersion")
                    if mrv:
                        rv = mrv
                    if ev_type == "BOOKMARK":
                        continue
                    if ev_type in ("ADDED", "MODIFIED", "DELETED"):
                        obj = info.from_manifest(manifest)
                        for h in list(self._handlers):
                            try:
                                h(ev_type, obj)
                            except Exception:  # noqa: BLE001
                                self.log.warning("event handler failed", kind=kind.__name__)
            except ApiError as e:
                if e.status == 410:  # resourceVersion expired: relist
                    rv = None
                    continue
                self._stop.wait(2.0)
            except (OSError, ConnectionError):
                self._stop.wait(2.0)

    # -- relational queries (Cluster surface) --------------------------------
    def bind_pod(self, pod: Pod, node: Node) -> None:
        # the real apiserver path: pods/{name}/binding (the kube-scheduler
        # verb); spec.nodeName is immutable through plain updates
        info = self._info(Pod)
        ns = pod.metadata.namespace or self.namespace
        self.client.create(
            f"{info.base_path(ns)}/{pod.metadata.name}/binding",
            {
                "apiVersion": "v1", "kind": "Binding",
                "metadata": {"name": pod.metadata.name},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node.metadata.name},
            },
        )
        pod.node_name = node.metadata.name
        pod.phase = "Running"
        self._invalidate(Pod)

    def unbind_pods(self, node_name: str) -> List[Pod]:
        """Node went away: the in-memory contract returns the pods to
        Pending (kwok/cluster.py abstracts controller re-creation to an
        in-place reset, and GC/lifecycle callers rely on the pods
        reappearing as pending). spec.nodeName is immutable on a real
        apiserver, so: pods WITH a controller (ownerReferences) are
        deleted and the controller re-creates them; bare pods are deleted
        and RE-CREATED here, pending, preserving their spec -- deleting
        them outright would destroy the workload."""
        out = []
        for p in self.pods_on_node(node_name):
            try:
                self.delete_object(p)
            except ApiError:
                continue
            p.node_name = ""
            p.phase = "Pending"
            if not p.metadata.owner_references:
                # no REAL owner (uid-carrying ownerReference): nothing
                # will re-create this pod, so we do
                self._recreate_bare_pod(p)
            out.append(p)
        self._invalidate(Pod)
        return out

