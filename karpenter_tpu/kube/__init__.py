from karpenter_tpu.kube.client import KubeClient, KubeConfig, ApiError, Conflict, NotFound
from karpenter_tpu.kube.cluster import KubeCluster

__all__ = ["KubeClient", "KubeConfig", "KubeCluster", "ApiError", "Conflict", "NotFound"]
