"""A dependency-free Kubernetes API client (stdlib HTTP + JSON).

The reference's coordination bus IS the kube apiserver
(`/root/reference/pkg/operator/operator.go:284-305`,
`cmd/controller/main.go:30-84` build everything on controller-runtime's
client); this module is the TPU build's equivalent seam, written against
the apiserver's REST surface directly because the image ships no
`kubernetes` package. Scope: exactly what the controllers need -- CRUD +
list with selectors + watch streams + subresource status updates, with
bearer-token / client-cert auth and CA verification.

Auth resolution:
- `KubeConfig.in_cluster()`: the pod serviceaccount mount
  (/var/run/secrets/kubernetes.io/serviceaccount).
- `KubeConfig.from_kubeconfig(path)`: standard kubeconfig (current-context;
  token, client cert/key, or insecure-skip-tls-verify).
- explicit `KubeConfig(server=..., token=...)`.
"""
from __future__ import annotations

import base64
import http.client
import json
import os
import ssl
import tempfile
import urllib.parse
from typing import Dict, Iterator, Optional, Tuple

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status
        self.message = message


class NotFound(ApiError):
    pass


class Conflict(ApiError):
    """409: resourceVersion conflict (the optimistic-concurrency signal the
    in-memory store raises as its own Conflict)."""


class KubeConfig:
    def __init__(
        self,
        server: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        client_cert_file: Optional[str] = None,
        client_key_file: Optional[str] = None,
        verify: bool = True,
    ):
        self.server = server.rstrip("/")
        self.token = token
        self.ca_file = ca_file
        self.client_cert_file = client_cert_file
        self.client_key_file = client_key_file
        self.verify = verify

    @staticmethod
    def in_cluster() -> "KubeConfig":
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not running in a cluster (no KUBERNETES_SERVICE_HOST)")
        with open(os.path.join(SA_DIR, "token")) as f:
            token = f.read().strip()
        return KubeConfig(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SA_DIR, "ca.crt"),
        )

    @staticmethod
    def from_kubeconfig(path: Optional[str] = None, context: Optional[str] = None) -> "KubeConfig":
        import yaml

        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context")
        ctx = next(c["context"] for c in cfg["contexts"] if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in cfg["users"] if u["name"] == ctx["user"])

        def materialize(data_key: str, file_key: str) -> Optional[str]:
            """Inline base64 data -> temp file; else the referenced path."""
            source = user if data_key.startswith("client") else cluster
            data = source.get(f"{data_key}-data")
            if data:
                fd, p = tempfile.mkstemp(prefix="kubeconfig-", suffix=".pem")
                with os.fdopen(fd, "wb") as f:
                    f.write(base64.b64decode(data))
                return p
            return source.get(file_key)

        return KubeConfig(
            server=cluster["server"],
            token=user.get("token"),
            ca_file=materialize("certificate-authority", "certificate-authority"),
            client_cert_file=materialize("client-certificate", "client-certificate"),
            client_key_file=materialize("client-key", "client-key"),
            verify=not cluster.get("insecure-skip-tls-verify", False),
        )


class KubeClient:
    """Thin REST client. One connection per call path (watch holds its own
    connection open); no retries here -- controllers are level-triggered
    and re-reconcile, the reference's posture."""

    def __init__(self, config: KubeConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        u = urllib.parse.urlparse(config.server)
        self._https = u.scheme == "https"
        self._host = u.hostname
        self._port = u.port or (443 if self._https else 80)
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self._https:
            ctx = ssl.create_default_context(cafile=config.ca_file)
            if not config.verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if config.client_cert_file:
                ctx.load_cert_chain(config.client_cert_file, config.client_key_file)
            self._ssl_ctx = ctx

    # -- plumbing -----------------------------------------------------------
    def _connect(self, timeout: Optional[float] = None) -> http.client.HTTPConnection:
        t = self.timeout if timeout is None else timeout
        if self._https:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=t, context=self._ssl_ctx
            )
        return http.client.HTTPConnection(self._host, self._port, timeout=t)

    def _headers(self) -> Dict[str, str]:
        h = {"Accept": "application/json", "Content-Type": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        return h

    def request(
        self, method: str, path: str, body: Optional[dict] = None,
        params: Optional[Dict[str, str]] = None,
        content_type: Optional[str] = None,
    ) -> dict:
        if params:
            path = f"{path}?{urllib.parse.urlencode(params)}"
        conn = self._connect()
        try:
            headers = self._headers()
            if content_type:
                headers["Content-Type"] = content_type
            conn.request(
                method, path,
                body=json.dumps(body) if body is not None else None,
                headers=headers,
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status == 404:
                raise NotFound(404, raw.decode(errors="replace")[:500])
            if resp.status == 409:
                raise Conflict(409, raw.decode(errors="replace")[:500])
            if resp.status >= 400:
                raise ApiError(resp.status, raw.decode(errors="replace")[:500])
            return json.loads(raw) if raw else {}
        finally:
            conn.close()

    # -- verbs --------------------------------------------------------------
    def get(self, path: str) -> dict:
        return self.request("GET", path)

    def list(self, path: str, params: Optional[Dict[str, str]] = None) -> dict:
        return self.request("GET", path, params=params)

    def create(self, path: str, manifest: dict) -> dict:
        return self.request("POST", path, body=manifest)

    def update(self, path: str, manifest: dict) -> dict:
        return self.request("PUT", path, body=manifest)

    def patch(self, path: str, patch: dict) -> dict:
        """application/merge-patch+json: update only the named fields --
        the write verb for kinds whose objects carry server/kubelet-owned
        fields a whole-object PUT would clobber (pods, nodes)."""
        return self.request(
            "PATCH", path, body=patch, content_type="application/merge-patch+json"
        )

    def patch_status(self, path: str, manifest: dict) -> dict:
        return self.request("PUT", f"{path}/status", body=manifest)

    def delete(self, path: str) -> dict:
        return self.request("DELETE", path)

    def server_version(self) -> dict:
        return self.request("GET", "/version")

    def watch(
        self, path: str, resource_version: Optional[str] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[Tuple[str, dict]]:
        """Stream (event_type, object) from a watch. The connection is held
        open; the apiserver chunk-streams one JSON object per line. Ends
        when the server closes (timeoutSeconds) -- callers loop, resuming
        from the last seen resourceVersion (bookmarks requested)."""
        params = {
            "watch": "true",
            "timeoutSeconds": str(timeout_seconds),
            "allowWatchBookmarks": "true",
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        qpath = f"{path}?{urllib.parse.urlencode(params)}"
        conn = self._connect(timeout=timeout_seconds + 15)
        try:
            conn.request("GET", qpath, headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                raw = resp.read()
                raise ApiError(resp.status, raw.decode(errors="replace")[:500])
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    yield ev.get("type", ""), ev.get("object", {})
        finally:
            conn.close()
