from karpenter_tpu.scheduling.resources import Resources, parse_quantity, format_quantity
from karpenter_tpu.scheduling.requirements import (
    Requirement,
    Requirements,
    Operator,
)
from karpenter_tpu.scheduling.taints import Taint, Toleration, tolerates, tolerates_all

__all__ = [
    "Resources",
    "parse_quantity",
    "format_quantity",
    "Requirement",
    "Requirements",
    "Operator",
    "Taint",
    "Toleration",
    "tolerates",
    "tolerates_all",
]
