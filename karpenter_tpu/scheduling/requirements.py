"""Label-requirement constraint algebra.

The reference leans on the core module's `scheduling.Requirements` everywhere
(e.g. pkg/providers/instancetype/types.go:158-292 builds ~30 requirements per
instance type; pkg/providers/instance/instance.go:244-249 filters candidate
types via `.Compatible`). That algebra -- node-selector operators over label
sets, with intersection and compatibility -- is rebuilt here from its observed
semantics, as the host-side half of a dual representation:

- here: exact set algebra on small string sets (control plane, explainable)
- solver/encode.py: the same constraints lowered to boolean masks over the
  catalog's label columns (decision plane, vectorized)

Operator semantics follow k8s NodeSelectorOperator: In, NotIn, Exists,
DoesNotExist, Gt, Lt.
"""
from __future__ import annotations

import enum
from typing import Dict, Iterable, Iterator, List, Mapping, Optional, Sequence, Set, Tuple


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


class Requirement:
    """One constraint on one label key.

    Internally normalized to one of three forms:
      - complement=False: allowed values = `values` (In / numeric windows)
      - complement=True:  allowed values = everything except `values`
        (Exists == complement of {}; NotIn; DoesNotExist == empty In)
      - additionally a numeric window [gt, lt] (exclusive bounds) that
        composes with the set form, mirroring how the core treats Gt/Lt.
    """

    __slots__ = ("key", "complement", "values", "greater_than", "less_than", "min_values")

    def __init__(
        self,
        key: str,
        operator: Operator | str,
        values: Sequence[str] = (),
        min_values: Optional[int] = None,
    ):
        operator = Operator(operator)
        self.key = key
        self.greater_than: Optional[float] = None
        self.less_than: Optional[float] = None
        self.min_values = min_values
        if operator == Operator.IN:
            self.complement = False
            self.values: Set[str] = set(values)
        elif operator == Operator.NOT_IN:
            self.complement = True
            self.values = set(values)
        elif operator == Operator.EXISTS:
            self.complement = True
            self.values = set()
        elif operator == Operator.DOES_NOT_EXIST:
            self.complement = False
            self.values = set()
        elif operator == Operator.GT:
            self.complement = True
            self.values = set()
            self.greater_than = float(values[0])
        elif operator == Operator.LT:
            self.complement = True
            self.values = set()
            self.less_than = float(values[0])
        else:  # pragma: no cover
            raise ValueError(f"unsupported operator {operator}")

    # -- predicates ---------------------------------------------------------
    def matches(self, value: Optional[str]) -> bool:
        """Does a concrete label value satisfy this requirement?
        `None` means the label is absent."""
        if value is None:
            # Absent label: only DoesNotExist (empty In == no allowed values?
            # no -- empty-In means unsatisfiable-for-present) matches.
            return self.complement is False and not self.values and self._window_open()
        if self.complement:
            if value in self.values:
                return False
        else:
            if value not in self.values:
                return False
        return self._in_window(value)

    def _window_open(self) -> bool:
        return self.greater_than is None and self.less_than is None

    def _in_window(self, value: str) -> bool:
        if self.greater_than is None and self.less_than is None:
            return True
        try:
            num = float(value)
        except ValueError:
            return False
        if self.greater_than is not None and not num > self.greater_than:
            return False
        if self.less_than is not None and not num < self.less_than:
            return False
        return True

    def is_does_not_exist(self) -> bool:
        return not self.complement and not self.values and self._window_open()

    # -- algebra ------------------------------------------------------------
    def intersect(self, other: "Requirement") -> "Requirement":
        """Tightest requirement satisfied only by values allowed by both."""
        assert self.key == other.key
        if self.complement and other.complement:
            out = Requirement(self.key, Operator.NOT_IN, sorted(self.values | other.values))
        elif self.complement and not other.complement:
            out = Requirement(self.key, Operator.IN, sorted(other.values - self.values))
        elif not self.complement and other.complement:
            out = Requirement(self.key, Operator.IN, sorted(self.values - other.values))
        else:
            out = Requirement(self.key, Operator.IN, sorted(self.values & other.values))
        gts = [g for g in (self.greater_than, other.greater_than) if g is not None]
        lts = [l for l in (self.less_than, other.less_than) if l is not None]
        out.greater_than = max(gts) if gts else None
        out.less_than = min(lts) if lts else None
        if not out.complement:
            out.values = {v for v in out.values if out._in_window(v)}
            out.greater_than = out.less_than = None
        out.min_values = max(filter(None, (self.min_values, other.min_values)), default=None)
        return out

    def intersects(self, other: "Requirement") -> bool:
        """Could any value satisfy both requirements?"""
        merged = self.intersect(other)
        if merged.complement:
            # complement sets always admit *some* value unless the numeric
            # window is empty
            if merged.greater_than is not None and merged.less_than is not None:
                return merged.less_than - merged.greater_than > 1
            return True
        return bool(merged.values)

    def allows(self, other: "Requirement") -> bool:
        """Is every value admitted by `other` also admitted by self?
        (i.e. other is at least as tight). Conservative on complements."""
        if not other.complement:
            return all(self.matches(v) for v in other.values)
        # `other` admits an open-ended set; only an Exists self safely covers it.
        return self.complement and not self.values and self._window_open()

    def copy(self) -> "Requirement":
        op = Operator.NOT_IN if self.complement else Operator.IN
        out = Requirement(self.key, op, sorted(self.values))
        out.greater_than = self.greater_than
        out.less_than = self.less_than
        out.min_values = self.min_values
        return out

    def __repr__(self) -> str:
        if self.complement:
            if not self.values and self._window_open():
                core = f"{self.key} Exists"
            else:
                core = f"{self.key} NotIn {sorted(self.values)}"
        else:
            core = f"{self.key} In {sorted(self.values)}"
        win = ""
        if self.greater_than is not None:
            win += f" >{self.greater_than:g}"
        if self.less_than is not None:
            win += f" <{self.less_than:g}"
        return f"Requirement({core}{win})"

    def __eq__(self, other) -> bool:
        if not isinstance(other, Requirement):
            return NotImplemented
        return (
            self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
        )

    def __hash__(self):
        return hash((self.key, self.complement, frozenset(self.values), self.greater_than, self.less_than))


class Requirements:
    """A conjunction of Requirements keyed by label.

    Mirrors the observed call surface of the core's scheduling.Requirements:
    NewRequirements/NewLabelRequirements, Add (tightening merge), Compatible,
    Intersects, Has/Get, Keys, Labels.
    """

    def __init__(self, reqs: Iterable[Requirement] = ()):
        self._m: Dict[str, Requirement] = {}
        for r in reqs:
            self.add(r)

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_labels(cls, labels: Mapping[str, str]) -> "Requirements":
        return cls(Requirement(k, Operator.IN, [v]) for k, v in labels.items())

    @classmethod
    def from_node_selector(cls, selector: Mapping[str, str]) -> "Requirements":
        return cls.from_labels(selector)

    @classmethod
    def from_node_selector_terms(cls, terms: Sequence[Mapping]) -> List["Requirements"]:
        """nodeAffinity requiredDuringScheduling terms: OR of ANDs.
        Returns one Requirements per term; callers try each (the core treats
        terms as alternatives)."""
        out = []
        for term in terms:
            reqs = []
            for expr in term.get("matchExpressions", []):
                reqs.append(Requirement(expr["key"], expr["operator"], expr.get("values", [])))
            out.append(cls(reqs))
        return out

    # -- mutation -----------------------------------------------------------
    def add(self, *reqs: Requirement) -> "Requirements":
        for r in reqs:
            if r.key in self._m:
                self._m[r.key] = self._m[r.key].intersect(r)
            else:
                self._m[r.key] = r.copy()
        return self

    def union(self, other: "Requirements") -> "Requirements":
        out = Requirements(self._m.values())
        out.add(*other._m.values())
        return out

    # -- access -------------------------------------------------------------
    def has(self, key: str) -> bool:
        return key in self._m

    def get(self, key: str) -> Optional[Requirement]:
        return self._m.get(key)

    def keys(self) -> Set[str]:
        return set(self._m.keys())

    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._m.values())

    def __len__(self) -> int:
        return len(self._m)

    def labels(self) -> Dict[str, str]:
        """Project requirements that pin a single value into a label map
        (how NodeClaim requirements become node labels in the reference)."""
        out = {}
        for k, r in self._m.items():
            if not r.complement and len(r.values) == 1:
                out[k] = next(iter(r.values))
        return out

    # -- algebra ------------------------------------------------------------
    def compatible(self, other: "Requirements", allow_undefined: Optional[Set[str]] = None) -> bool:
        """Can a single entity satisfy both requirement sets?

        For every key present in `other`, self must either intersect on that
        key or (if self lacks the key) the key must be in `allow_undefined`
        (mirrors the core's allowUndefinedWellKnownLabels compatibility used
        when matching pods against not-yet-labeled in-flight nodes).
        """
        for key, theirs in other._m.items():
            mine = self._m.get(key)
            if mine is None:
                if allow_undefined is not None and key not in allow_undefined:
                    return False
                if theirs.is_does_not_exist():
                    continue
                continue
            if theirs.is_does_not_exist():
                # other forbids the label; self defines it -> incompatible
                return False
            if not mine.intersects(theirs):
                return False
        return True

    def intersects(self, other: "Requirements") -> bool:
        return self.compatible(other) and other.compatible(self)

    def matches_labels(self, labels: Mapping[str, str]) -> bool:
        """Do concrete node labels satisfy every requirement?"""
        return all(r.matches(labels.get(k)) for k, r in self._m.items())

    def copy(self) -> "Requirements":
        return Requirements(r.copy() for r in self._m.values())

    def __repr__(self) -> str:
        return "Requirements(" + ", ".join(repr(r) for r in self._m.values()) + ")"

    def stable_hash(self) -> str:
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        for k in sorted(self._m):
            r = self._m[k]
            h.update(
                f"{k}|{r.complement}|{sorted(r.values)}|{r.greater_than}|{r.less_than};".encode()
            )
        return h.hexdigest()


def min_values_shortfall(reqs: "Requirements", instance_types) -> Optional[str]:
    """The first requirement key whose minValues flexibility is NOT met by
    `instance_types` (fewer distinct label values than required), or None.
    The karpenter v1 minValues contract: a NodeClaim must keep at least N
    distinct values of the key among its candidate types, guaranteeing
    launch flexibility."""
    for r in reqs:
        if r.min_values is None:
            continue
        distinct = {
            it.requirements.labels().get(r.key)
            for it in instance_types
            if it.requirements.labels().get(r.key) is not None
        }
        if len(distinct) < r.min_values:
            return r.key
    return None


def truncate_preserving_min_values(
    reqs: "Requirements", types_sorted, cap: int
):
    """Truncate a cheapest-first type list to `cap`, keeping minValues
    satisfied when the full list satisfies it: fill cheapest-first, then
    for each unmet key swap in the cheapest remaining type contributing a
    NEW value, evicting the most expensive chosen type whose removal
    breaks nothing. Mirrors the reference's truncation honoring
    spec.requirements[].minValues."""
    chosen = list(types_sorted[:cap])
    if len(types_sorted) <= cap:
        return chosen
    min_reqs = [r for r in reqs if r.min_values is not None]
    if not min_reqs:
        return chosen
    rest = list(types_sorted[cap:])

    def values_of(pool, key):
        out = {}
        for it in pool:
            v = it.requirements.labels().get(key)
            if v is not None:
                out.setdefault(v, 0)
                out[v] += 1
        return out

    for r in min_reqs:
        have = values_of(chosen, r.key)
        need = r.min_values - len(have)
        if need <= 0:
            continue
        for it in rest:
            if need <= 0:
                break
            v = it.requirements.labels().get(r.key)
            if v is None or v in have:
                continue
            # evict the priciest chosen type that is not the last holder
            # of any minValues-contributing value
            evict_idx = None
            for j in range(len(chosen) - 1, -1, -1):
                cand = chosen[j]
                safe = True
                for r2 in min_reqs:
                    v2 = cand.requirements.labels().get(r2.key)
                    if v2 is not None:
                        holders = values_of(chosen, r2.key)
                        if holders.get(v2, 0) <= 1 and len(holders) <= r2.min_values:
                            safe = False
                            break
                if safe:
                    evict_idx = j
                    break
            if evict_idx is None:
                break
            chosen.pop(evict_idx)
            chosen.append(it)
            have[v] = 1
            need -= 1
    return chosen
