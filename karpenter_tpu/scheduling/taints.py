"""Taints and tolerations.

The reference relies on the core scheduler's taint/toleration matching during
bin-packing and consolidation simulation (startup taints on NodeClaims:
pkg/cloudprovider/cloudprovider.go instanceToNodeClaim path; kwok node
fabrication applies taints when registering fake nodes). Semantics follow
k8s: a pod tolerates a taint if a toleration matches (key, operator Equal/
Exists, value, effect); NoSchedule/NoExecute taints block scheduling unless
tolerated, PreferNoSchedule is soft (treated as non-blocking here, matching
the core scheduler's hard-constraint-only simulation).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

NO_SCHEDULE = "NoSchedule"
NO_EXECUTE = "NoExecute"
PREFER_NO_SCHEDULE = "PreferNoSchedule"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str = NO_SCHEDULE
    value: str = ""

    def blocking(self) -> bool:
        return self.effect in (NO_SCHEDULE, NO_EXECUTE)


@dataclass(frozen=True)
class Toleration:
    key: str = ""                 # empty + Exists tolerates everything
    operator: str = "Equal"       # Equal | Exists
    value: str = ""
    effect: str = ""              # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


def tolerates(tolerations: Sequence[Toleration], taint: Taint) -> bool:
    if not taint.blocking():
        return True
    return any(t.tolerates(taint) for t in tolerations)


def tolerates_all(tolerations: Sequence[Toleration], taints: Sequence[Taint]) -> bool:
    return all(tolerates(tolerations, t) for t in taints)
