"""Resource-quantity vocabulary and vector math.

The reference models resources as k8s `corev1.ResourceList` maps and computes
fit as per-resource comparisons inside the core scheduler's FFD loop
(reference: designs/bin-packing.md:17-43; capacity construction at
pkg/providers/instancetype/types.go:313-331). Here the same vocabulary has a
dual representation:

- `Resources`: a small dict-like value type for host-side (control-plane) code.
- a fixed, ordered axis list `RESOURCE_AXES` so any Resources value can be
  densified into a float32 vector of static length for the TPU solver
  (XLA needs static shapes; a sparse resource map would defeat tiling).

All quantities normalize to base units at parse time: cpu -> millicores,
memory/ephemeral-storage -> bytes, counts -> unit. This avoids carrying k8s
Quantity objects into the hot path.
"""
from __future__ import annotations

import math
import re
from typing import Dict, Iterable, Mapping, Tuple, Union

# Canonical resource names (k8s vocabulary, as used throughout the reference).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
GPU = "gpu.devices.dev/gpu"            # generic GPU-like extended resource
ACCELERATOR = "accelerator.dev/chips"  # generic ML accelerator (TPU-like)
NIC = "network.dev/nic"                # EFA-like high-perf NIC resource
PRIVATE_IPV4 = "private-ipv4"          # per-instance IP budget (subnet math)
# Per-node persistent-volume attach budget. The reference core counts a
# pod's CSI volumes against the node's attach limit during its scheduling
# simulation (karpenter core scheduling volume-usage tracking; the AWS
# analogue is the EBS per-instance attachment ceiling). Here it is ONE
# MORE DENSE AXIS: pods with resolved claims carry their volume count on
# it, instance types carry their attach limit, and the same vector fit
# that bounds cpu/mem/pods bounds attachments -- on the device kernel,
# the oracle, and the binder, with zero special-case code in any of them.
ATTACHABLE_VOLUMES = "attachable-volumes"

# The dense axis order for the solver. Static: changing it is a schema bump.
RESOURCE_AXES: Tuple[str, ...] = (
    CPU,
    MEMORY,
    EPHEMERAL_STORAGE,
    PODS,
    GPU,
    ACCELERATOR,
    NIC,
    PRIVATE_IPV4,
    ATTACHABLE_VOLUMES,
)
AXIS_INDEX: Dict[str, int] = {name: i for i, name in enumerate(RESOURCE_AXES)}
NUM_RESOURCE_AXES = len(RESOURCE_AXES)

_BINARY_SUFFIX = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL_SUFFIX = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(value: Union[str, int, float], resource: str = "") -> float:
    """Parse a k8s-style quantity into base units.

    cpu values normalize to *millicores* ("1" -> 1000.0, "250m" -> 250.0);
    everything else normalizes to its plain unit (memory in bytes).
    """
    is_cpu = resource == CPU
    if isinstance(value, (int, float)):
        # Numeric inputs are already in base units (cpu: millicores) --
        # only strings carry k8s quantity notation.
        return float(value)
    m = _QTY_RE.match(value)
    if not m:
        raise ValueError(f"unparseable quantity {value!r}")
    num = float(m.group(1))
    suffix = m.group(2)
    if suffix == "":
        scale = 1.0
    elif suffix == "m":
        # milli-units: for cpu this IS the base unit; for others scale down.
        return num if is_cpu else num / 1000.0
    elif suffix in _BINARY_SUFFIX:
        scale = float(_BINARY_SUFFIX[suffix])
    elif suffix in _DECIMAL_SUFFIX:
        scale = float(_DECIMAL_SUFFIX[suffix])
    else:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")
    base = num * scale
    return base * 1000.0 if is_cpu else base


def format_quantity(value: float, resource: str = "") -> str:
    """Render a base-unit value back into a compact k8s-style string."""
    if resource == CPU:
        if value == int(value) and int(value) % 1000 == 0:
            return str(int(value) // 1000)
        return f"{int(value)}m" if value == int(value) else f"{value}m"
    if resource in (MEMORY, EPHEMERAL_STORAGE):
        for suffix, scale in (("Ti", 2**40), ("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
            if value >= scale and (value / scale) == int(value / scale):
                return f"{int(value / scale)}{suffix}"
        return str(int(value))
    if value == int(value):
        return str(int(value))
    return str(value)


class Resources:
    """An immutable-ish resource vector with dict semantics.

    Values are floats in base units (cpu: millicores, memory: bytes).
    Arithmetic is element-wise over the union of keys; comparisons used by
    the schedulers are provided as `fits` (self <= other on every axis).
    """

    __slots__ = ("_v", "_sig")

    def __init__(self, values: Mapping[str, Union[str, int, float]] | None = None, **kw):
        self._sig = None
        self._v: Dict[str, float] = {}
        merged: Dict[str, Union[str, int, float]] = dict(values or {})
        merged.update(kw)
        for k, raw in merged.items():
            # Strings go through k8s-quantity parsing (cpu -> millicores).
            # Numeric values are taken as base units verbatim, so host code
            # and the solver's dense encoding agree without guessing.
            val = parse_quantity(raw, k) if isinstance(raw, str) else float(raw)
            if val != 0.0:
                self._v[k] = self._v.get(k, 0.0) + val

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_base_units(cls, values: Mapping[str, float]) -> "Resources":
        r = cls()
        r._v = {k: float(v) for k, v in values.items() if v != 0.0}
        return r

    @classmethod
    def from_vector(cls, vec) -> "Resources":
        """Dense RESOURCE_AXES vector -> Resources, skipping the dict
        round-trip (the decode hot loop builds one per opened group)."""
        r = cls.__new__(cls)
        r._sig = None
        r._v = {k: v for k, v in zip(RESOURCE_AXES, vec) if v != 0.0}
        return r

    def sig(self) -> tuple:
        """Canonical content tuple, memoized. Resources are immutable after
        construction, and pods of one workload template share one Resources
        object (ReplicaSet replicas carry literally identical specs), so the
        sort amortizes across the whole template on the 50k-pod grouping
        path (solver/encode.group_pods)."""
        s = self._sig
        if s is None:
            s = self._sig = tuple(sorted(self._v.items()))
        return s

    # -- dict-ish -----------------------------------------------------------
    def get(self, key: str, default: float = 0.0) -> float:
        return self._v.get(key, default)

    def __getitem__(self, key: str) -> float:
        return self._v.get(key, 0.0)

    def __contains__(self, key: str) -> bool:
        return key in self._v

    def keys(self):
        return self._v.keys()

    def items(self):
        return self._v.items()

    def __iter__(self):
        return iter(self._v)

    def __len__(self):
        return len(self._v)

    def __bool__(self):
        return any(v != 0.0 for v in self._v.values())

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other: "Resources") -> "Resources":
        out = dict(self._v)
        for k, v in other._v.items():
            out[k] = out.get(k, 0.0) + v
        return Resources.from_base_units(out)

    def __sub__(self, other: "Resources") -> "Resources":
        out = dict(self._v)
        for k, v in other._v.items():
            out[k] = out.get(k, 0.0) - v
        return Resources.from_base_units(out)

    def __mul__(self, scalar: float) -> "Resources":
        return Resources.from_base_units({k: v * scalar for k, v in self._v.items()})

    __rmul__ = __mul__

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        keys = set(self._v) | set(other._v)
        return all(math.isclose(self.get(k), other.get(k), rel_tol=1e-9, abs_tol=1e-9) for k in keys)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={format_quantity(v, k)}" for k, v in sorted(self._v.items()))
        return f"Resources({inner})"

    # -- scheduling ---------------------------------------------------------
    def fits(self, capacity: "Resources") -> bool:
        """True iff every requested axis is satisfiable within `capacity`."""
        return all(v <= capacity.get(k) + 1e-9 for k, v in self._v.items())

    def within(self, limits: "Resources") -> bool:
        """True iff every axis NAMED BY `limits` is at or under it. Axes
        absent from limits are UNCONSTRAINED -- NodePool-limits semantics
        (the reference caps only the resources the operator lists,
        `nodepool.spec.limits`). fits() is the wrong shape for that check:
        a cpu-only limit would read every other axis as capacity 0 and
        refuse all capacity (round-5 finding)."""
        return all(self._v.get(k, 0.0) <= v + 1e-9 for k, v in limits._v.items())

    def any_negative(self) -> bool:
        return any(v < -1e-9 for v in self._v.values())

    def nonzero_axes(self) -> Iterable[str]:
        return (k for k, v in self._v.items() if v != 0.0)

    # -- dense encoding for the solver -------------------------------------
    def to_vector(self) -> Tuple[float, ...]:
        """Densify onto RESOURCE_AXES. Unknown extended resources raise --
        the catalog schema must be extended deliberately, not silently."""
        vec = [0.0] * NUM_RESOURCE_AXES
        for k, v in self._v.items():
            if k not in AXIS_INDEX:
                raise KeyError(
                    f"resource {k!r} has no dense axis; add it to RESOURCE_AXES"
                )
            vec[AXIS_INDEX[k]] = v
        return tuple(vec)


def merge_requests(*rs: Resources) -> Resources:
    total = Resources()
    for r in rs:
        total = total + r
    return total
