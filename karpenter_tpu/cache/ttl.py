"""TTL cache with injectable clock.

Equivalent role to the patrickmn/go-cache instances the reference threads
through every provider (constructed in pkg/operator/operator.go:126-186).
Clock injection mirrors the reference's clock.Clock so tests can step time.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple


class Clock:
    """Real clock; tests substitute FakeClock."""

    def now(self) -> float:
        return time.time()


class FakeClock(Clock):
    def __init__(self, start: float = 0.0):
        self._t = start

    def now(self) -> float:
        return self._t

    def step(self, seconds: float) -> None:
        self._t += seconds

    def set(self, t: float) -> None:
        self._t = t


class TTLCache:
    def __init__(self, default_ttl: float, clock: Optional[Clock] = None):
        self._ttl = default_ttl
        self._clock = clock or Clock()
        self._lock = threading.Lock()
        self._d: Dict[Any, Tuple[Any, float]] = {}  # key -> (value, expires_at)

    def set(self, key: Any, value: Any, ttl: Optional[float] = None) -> None:
        exp = self._clock.now() + (self._ttl if ttl is None else ttl)
        with self._lock:
            self._d[key] = (value, exp)

    def get(self, key: Any) -> Tuple[Any, bool]:
        with self._lock:
            entry = self._d.get(key)
            if entry is None:
                return None, False
            value, exp = entry
            if self._clock.now() >= exp:
                del self._d[key]
                return None, False
            return value, True

    def get_or_compute(self, key: Any, fn: Callable[[], Any], ttl: Optional[float] = None) -> Any:
        value, ok = self.get(key)
        if ok:
            return value
        value = fn()
        self.set(key, value, ttl)
        return value

    def delete(self, key: Any) -> None:
        with self._lock:
            self._d.pop(key, None)

    def flush(self) -> None:
        with self._lock:
            self._d.clear()

    def items(self) -> Iterator[Tuple[Any, Any]]:
        now = self._clock.now()
        with self._lock:
            return iter([(k, v) for k, (v, exp) in self._d.items() if now < exp])

    def __len__(self) -> int:
        now = self._clock.now()
        with self._lock:
            return sum(1 for _, exp in self._d.values() if now < exp)
