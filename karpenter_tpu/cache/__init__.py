from karpenter_tpu.cache.ttl import TTLCache
from karpenter_tpu.cache.unavailable_offerings import UnavailableOfferings

# Cache TTL constants (reference: pkg/cache/cache.go -- instance types /
# offerings 5 min, unavailable offerings ICE TTL 3 min, SSM 24h, discovered
# capacity 60 days; values in seconds).
DEFAULT_TTL = 60.0
INSTANCE_TYPES_AND_OFFERINGS_TTL = 5 * 60.0
UNAVAILABLE_OFFERINGS_TTL = 3 * 60.0
SSM_CACHE_TTL = 24 * 3600.0
DISCOVERED_CAPACITY_TTL = 60 * 24 * 3600.0
INSTANCE_PROFILE_TTL = 15 * 60.0
SUBNETS_TTL = 60.0
SECURITY_GROUPS_TTL = 5 * 60.0
INSTANCE_LINK_TTL = 10 * 60.0
VALIDATION_TTL = 10 * 60.0
CAPACITY_RESERVATION_TTL = 60.0

__all__ = [
    "TTLCache",
    "UnavailableOfferings",
    "DEFAULT_TTL",
    "INSTANCE_TYPES_AND_OFFERINGS_TTL",
    "UNAVAILABLE_OFFERINGS_TTL",
    "SSM_CACHE_TTL",
    "DISCOVERED_CAPACITY_TTL",
    "INSTANCE_PROFILE_TTL",
    "SUBNETS_TTL",
    "SECURITY_GROUPS_TTL",
    "INSTANCE_LINK_TTL",
    "VALIDATION_TTL",
    "CAPACITY_RESERVATION_TTL",
]
