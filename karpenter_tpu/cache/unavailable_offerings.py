"""UnavailableOfferings: the insufficient-capacity (ICE) negative cache.

Rebuilt from the reference's pkg/cache/unavailableofferings.go:33-107: three
TTL'd sub-caches -- per (instance-type, zone, capacity-type) offering, per
capacity-type, and per (zone, capacity-type) -- plus a monotonically
increasing SeqNum folded into catalog cache keys so every ICE change
invalidates cached instance-type lists
(reference: pkg/providers/instancetype/offering/offering.go:200-206).
"""
from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.cache.ttl import Clock, TTLCache

DEFAULT_ICE_TTL = 3 * 60.0


class UnavailableOfferings:
    def __init__(self, clock: Optional[Clock] = None, ttl: float = DEFAULT_ICE_TTL):
        self._offerings = TTLCache(ttl, clock)
        self._capacity_types = TTLCache(ttl, clock)
        self._zonal = TTLCache(ttl, clock)
        self._lock = threading.Lock()
        self._seq = 0

    @property
    def seq_num(self) -> int:
        """Monotonic change counter, read under the SAME lock the marks
        bump it under: catalog cache keys fold this in, and a key must
        never pair a seqnum with a cache view from a different moment."""
        with self._lock:
            return self._seq

    # -- marking ------------------------------------------------------------
    # mark-and-bump is ATOMIC (one lock acquisition around both): with the
    # old two-step (unlocked set, then locked bump) a concurrent reader
    # could observe the bumped seqnum paired with the pre-mark cache view
    # -- computing a FRESH catalog key over STALE availability, which the
    # key would then cache until the next unrelated bump.
    def mark_unavailable(self, instance_type: str, zone: str, capacity_type: str, reason: str = "") -> None:
        with self._lock:
            self._offerings.set((instance_type, zone, capacity_type), reason or True)
            self._seq += 1

    def mark_capacity_type_unavailable(self, capacity_type: str) -> None:
        with self._lock:
            self._capacity_types.set(capacity_type, True)
            self._seq += 1

    def mark_az_unavailable(self, zone: str, capacity_type: str) -> None:
        with self._lock:
            self._zonal.set((zone, capacity_type), True)
            self._seq += 1

    # -- queries ------------------------------------------------------------
    def is_unavailable(self, instance_type: str, zone: str, capacity_type: str) -> bool:
        if self._capacity_types.get(capacity_type)[1]:
            return True
        if self._zonal.get((zone, capacity_type))[1]:
            return True
        return self._offerings.get((instance_type, zone, capacity_type))[1]

    def flush(self) -> None:
        with self._lock:
            self._offerings.flush()
            self._capacity_types.flush()
            self._zonal.flush()
            self._seq += 1
