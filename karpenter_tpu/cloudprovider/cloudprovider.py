"""CloudProvider plugin boundary.

Rebuilds the contract of pkg/cloudprovider/cloudprovider.go:56-305 -- the
seam between the scheduling core and the provider stack:

  Create / Delete / Get / List / GetInstanceTypes / IsDrifted /
  RepairPolicies / Name / GetSupportedNodeClasses / DisruptionReasons

Create resolves the claim's nodeclass, lists candidate instance types,
delegates to the instance provider, and reflects the launched instance back
into the NodeClaim (instanceToNodeClaim :377-440). Drift detection compares
static hashes plus resolved cloud state (pkg/cloudprovider/drift.go:43-157).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from karpenter_tpu.apis import NodeClaim, NodePool, TPUNodeClass, labels as wk
from karpenter_tpu.apis.nodeclaim import COND_LAUNCHED
from karpenter_tpu.apis.nodeclass import HASH_ANNOTATION, HASH_VERSION, HASH_VERSION_ANNOTATION
from karpenter_tpu.cloud.types import CloudInstance
from karpenter_tpu.errors import NodeClassNotReadyError, NotFoundError
from karpenter_tpu.kwok.cluster import Cluster
from karpenter_tpu.providers.instance import InstanceProvider
from karpenter_tpu.providers.instancetype.provider import InstanceTypeProvider
from karpenter_tpu.providers.instancetype.types import InstanceType
from karpenter_tpu.scheduling import Resources
from karpenter_tpu.scheduling import resources as res
from karpenter_tpu.utils import parse_instance_id

DRIFTED_STATIC = "NodeClassHashDrifted"
DRIFTED_NODECLASS = "NodeClassDrifted"
DRIFTED_IMAGE = "ImageDrifted"
DRIFTED_SUBNET = "SubnetDrifted"
DRIFTED_SECURITY_GROUP = "SecurityGroupDrifted"


@dataclass
class RepairPolicy:
    """Tolerate an unhealthy node condition for a window, then replace
    (reference: cloudprovider.go:264-305)."""

    condition_type: str
    condition_status: str
    toleration_seconds: float


class CloudProvider:
    NAME = "tpu"

    def __init__(
        self,
        cluster: Cluster,
        instance_types: InstanceTypeProvider,
        instances: InstanceProvider,
    ):
        self.cluster = cluster
        self.instance_types = instance_types
        self.instances = instances

    def launch_window(self, expected: int):
        return self.instances.launch_window(expected)

    def name(self) -> str:
        return self.NAME

    def get_supported_node_classes(self) -> List[type]:
        return [TPUNodeClass]

    def disruption_reasons(self) -> List[str]:
        return ["Underutilized", "Empty", "Drifted", "Expired", "Interrupted"]

    # -- catalog ------------------------------------------------------------
    def _nodeclass_for(self, obj) -> TPUNodeClass:
        ref = getattr(obj, "node_class_ref", None) or obj.template.node_class_ref
        nc = self.cluster.try_get(TPUNodeClass, ref.name)
        if nc is None:
            raise NotFoundError(f"nodeclass {ref.name} not found")
        return nc

    def get_instance_types(self, nodepool: NodePool) -> List[InstanceType]:
        nodeclass = self._nodeclass_for(nodepool)
        return self.instance_types.list(nodeclass)

    # -- lifecycle ----------------------------------------------------------
    def create(self, claim: NodeClaim) -> NodeClaim:
        nodeclass = self._nodeclass_for(claim)
        if not nodeclass.ready():
            raise NodeClassNotReadyError(f"nodeclass {nodeclass.name} is not ready")
        items = self.instance_types.list(nodeclass)
        compatible = [it for it in items if it.requirements.compatible(claim.requirements)]
        inst = self.instances.create(nodeclass, claim, compatible)
        # crash site: the canonical crash-consistency window -- the cloud
        # mutation has happened, the claim status commit has NOT. Without
        # the intent journal this instance leaks until GC's grace window;
        # with it, the restart recovery sweep adopts the instance by its
        # intent token (controllers/recovery.py)
        from karpenter_tpu import failpoints

        failpoints.eval("crash.launch")
        chosen = next((it for it in items if it.name == inst.instance_type), None)
        return self._instance_to_nodeclaim(claim, inst, chosen)

    def adopt(self, claim: NodeClaim, inst: CloudInstance) -> NodeClaim:
        """Reflect an ALREADY-LAUNCHED instance into a claim whose status
        commit was lost to a crash (the recovery sweep's repair path):
        exactly the instanceToNodeClaim reflection create() would have
        done, minus the launch."""
        nodeclass = self._nodeclass_for(claim)
        items = self.instance_types.list(nodeclass)
        chosen = next((it for it in items if it.name == inst.instance_type), None)
        return self._instance_to_nodeclaim(claim, inst, chosen)

    def _instance_to_nodeclaim(
        self, claim: NodeClaim, inst: CloudInstance, itype: Optional[InstanceType]
    ) -> NodeClaim:
        labels = dict(claim.metadata.labels)
        if itype is not None:
            labels.update(itype.requirements.labels())
        labels[wk.INSTANCE_TYPE_LABEL] = inst.instance_type
        labels[wk.ZONE_LABEL] = inst.zone
        labels[wk.CAPACITY_TYPE_LABEL] = inst.capacity_type
        if inst.capacity_reservation_id:
            labels[wk.LABEL_CAPACITY_RESERVATION_ID] = inst.capacity_reservation_id
        claim.metadata.labels = labels
        claim.provider_id = inst.provider_id
        claim.image_id = inst.image_id
        if itype is not None:
            claim.capacity = itype.capacity
            claim.allocatable = itype.allocatable()
        claim.status_conditions.set_true(COND_LAUNCHED, "InstanceLaunched")
        return claim

    def delete(self, claim: NodeClaim) -> None:
        if not claim.provider_id:
            raise NotFoundError(f"nodeclaim {claim.name} has no provider id")
        self.instances.delete(parse_instance_id(claim.provider_id))

    def get(self, provider_id: str) -> CloudInstance:
        return self.instances.get(parse_instance_id(provider_id))

    def list_instances(self) -> List[CloudInstance]:
        return [i for i in self.instances.list() if i.state not in ("terminated",)]

    # -- drift --------------------------------------------------------------
    def is_drifted(self, claim: NodeClaim) -> Optional[str]:
        nodeclass = self._nodeclass_for(claim)
        # static hash drift (annotation stamped at claim creation)
        claimed_hash = claim.metadata.annotations.get(HASH_ANNOTATION)
        if (
            claimed_hash is not None
            and claim.metadata.annotations.get(HASH_VERSION_ANNOTATION) == HASH_VERSION
            and claimed_hash != nodeclass.static_hash()
        ):
            return DRIFTED_STATIC
        # image drift: the claim's image no longer in resolved status
        if claim.image_id and nodeclass.status_images:
            if claim.image_id not in {i.id for i in nodeclass.status_images}:
                return DRIFTED_IMAGE
        # cloud-state drift: instance's subnet / security groups no longer
        # covered by the nodeclass's resolved status (drift.go:43-157)
        if claim.provider_id and (nodeclass.status_subnets or nodeclass.status_security_groups):
            try:
                inst = self.get(claim.provider_id)
            except NotFoundError:
                return None
            if nodeclass.status_subnets and inst.subnet_id not in {s.id for s in nodeclass.status_subnets}:
                return DRIFTED_SUBNET
            if nodeclass.status_security_groups and inst.security_group_ids:
                if set(inst.security_group_ids) != {g.id for g in nodeclass.status_security_groups}:
                    return DRIFTED_SECURITY_GROUP
        return None

    # -- repair -------------------------------------------------------------
    def repair_policies(self) -> List[RepairPolicy]:
        return [
            RepairPolicy("Ready", "False", 30 * 60.0),
            RepairPolicy("Ready", "Unknown", 30 * 60.0),
            RepairPolicy("AcceleratedHardwareReady", "False", 10 * 60.0),
        ]
