from karpenter_tpu.cloudprovider.cloudprovider import CloudProvider, RepairPolicy

__all__ = ["CloudProvider", "RepairPolicy"]
