"""Overload control: tick deadline budgets, shedding, brownout, watchdog.

Every robustness layer before this one defends against component FAILURE
(the breaker, the crash journal, the chaos soaks); none defends against
sustained OVERLOAD: an arrival storm past solver capacity just grows the
pending set and stretches ticks unboundedly. This module gives the
operator tick a degraded-but-predictable mode instead, four pieces:

- ``TickBudget`` -- a per-tick deadline (``Options.tick_deadline`` /
  ``--tick-deadline``) decomposed hierarchically into stage budgets on
  the PR-2 trace span boundaries (snapshot/encode/wire/device/decode/
  bind), threaded through the sweep as a thread-local so deep layers
  (the solver wire's read timeout, the provisioner's admission sizing)
  can shed work EARLY instead of timing out late;
- bounded admission with priority-aware shedding lives in the
  provisioner (``Provisioner._admit``): when a tick cannot solve the
  whole pending set within budget it solves a deterministic
  priority/age-ordered PREFIX and defers the rest
  (``karpenter_overload_shed_total``) -- deferred pods stay pending, so
  nothing is lost, only delayed;
- ``BrownoutController`` -- a fixed, documented shed ladder above the
  transport degrade ladder, driven by an EWMA of tick-budget overrun:
  (1) consolidation/disruption sweeps downgrade to a bounded
  singleton-only device pass (or stand down when no device engine is
  wired), (2) trace sampling
  stops feeding the stats/metrics volume, (3) delta-epoch staging (and
  its restage retry roundtrips) stands down. Recovery is hysteretic
  (exit threshold below the enter threshold, plus a dwell) so the
  ladder never flaps tick to tick;
- ``StuckTickWatchdog`` -- detects a tick wedged past N x deadline (the
  solver hang the breaker's finish-level failure counter never sees)
  and escalates through a fixed ladder: cancel the wire (unblocks ring
  waits and forces the degrade ladder), force the breaker open (regular
  traffic stops touching the wire), and finally an async-raised
  ``OperatorCrashed`` into the stuck thread -- the PR-6 recovery sweep
  then takes over exactly as for any other crash.

Everything is OFF at ``tick_deadline == 0`` (the default): no budget, no
brownout, no watchdog, bit-identical behavior to the pre-overload tree.
The deterministic shedding knob (``Options.admission_max_pods``) works
with or without a deadline, which is what the sim's overload-storm
scenario pins byte-deterministically.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

from karpenter_tpu import metrics
from karpenter_tpu.logging import get_logger

# hierarchical stage decomposition of one tick deadline, on the PR-2
# trace span boundaries. Fractions are budget CEILINGS, not predictions:
# a stage that finishes early donates its slack to everything after it
# (stage_deadline() is min(ceiling, remaining)).
STAGE_FRACTIONS = {
    "snapshot": 0.10,
    "encode": 0.15,
    "wire": 0.20,
    "device": 0.25,
    "decode": 0.15,
    "bind": 0.15,
}
# the solve share of a tick (everything between snapshot and bind): the
# admission sizing divides this by the EWMA per-pod solve cost
SOLVE_FRACTION = (
    STAGE_FRACTIONS["encode"] + STAGE_FRACTIONS["wire"]
    + STAGE_FRACTIONS["device"] + STAGE_FRACTIONS["decode"]
)


class TickBudget:
    """One tick's deadline budget on a monotonic clock. Cheap by design
    (two floats); constructed at tick start, consulted by whoever wants
    to shed early."""

    __slots__ = ("deadline", "started", "_clock")

    def __init__(self, deadline: float, clock: Callable[[], float] = time.monotonic):
        self.deadline = float(deadline)
        self._clock = clock
        self.started = clock()

    def elapsed(self) -> float:
        return self._clock() - self.started

    def remaining(self) -> float:
        return self.deadline - self.elapsed()

    def overrun(self) -> float:
        """elapsed / deadline: < 1 inside budget, > 1 blown."""
        return self.elapsed() / self.deadline if self.deadline > 0 else 0.0

    def stage_budget(self, stage: str) -> float:
        """The stage's budget ceiling (its fraction of the deadline)."""
        return STAGE_FRACTIONS.get(stage, 1.0) * self.deadline

    def stage_deadline(self, stage: str) -> float:
        """Seconds this stage may still spend: its ceiling or whatever is
        left of the whole tick, whichever is smaller -- floored so a
        nearly-blown budget degrades (short timeouts -> the ladder) but
        never hands a zero/negative timeout to a transport."""
        floor = max(0.05, 0.1 * self.deadline)
        return max(floor, min(self.stage_budget(stage), self.remaining()))

    def solve_budget(self) -> float:
        """Seconds the solve stages (encode+wire+device+decode) may still
        spend this tick -- the admission sizing's numerator."""
        return max(0.0, min(SOLVE_FRACTION * self.deadline, self.remaining()))


# -- thread-local active budget ------------------------------------------------
#
# The budget rides the sweep as a thread-local (the same shape as the
# tracer's current-span context): the operator pushes it around the tick
# body, and deep layers -- the solver client's read-timeout clamp, the
# provisioner's admission sizing -- read it without any parameter
# threading through ~10 call layers.

_local = threading.local()


@contextmanager
def active(budget: Optional[TickBudget]):
    """Install `budget` as THIS thread's active tick budget for the
    duration (None = no budget: every consumer behaves exactly as before
    the overload subsystem existed)."""
    prev = getattr(_local, "budget", None)
    _local.budget = budget
    try:
        yield budget
    finally:
        _local.budget = prev


def current() -> Optional[TickBudget]:
    return getattr(_local, "budget", None)


def clamp_timeout(default: float) -> float:
    """The read budget a blocking wire call should use: the caller's
    default, clamped to the active tick budget's REMAINING time (floored
    like stage_deadline, so a nearly-blown budget degrades rather than
    hands out a zero timeout). The whole remainder, not the wire stage's
    ceiling: the client-side read wait spans wire + device compute +
    fetch, and the shed criterion is "the TICK cannot afford to keep
    waiting", not one stage's share. No active budget = the default,
    untouched. A clamped timeout expiring surfaces as the same
    timeout/ConnectionError every degrade ladder already handles -- the
    tick sheds the wire EARLY instead of blowing its deadline waiting."""
    budget = current()
    if budget is None:
        return default
    floor = max(0.05, 0.1 * budget.deadline)
    return min(default, max(floor, budget.remaining()))


# -- brownout ladder -----------------------------------------------------------

class BrownoutController:
    """Sheds optional work in a FIXED documented order under sustained
    deadline pressure, recovering hysteretically. Levels:

        0 normal           -- nothing shed
        1 shed-disruption  -- consolidation/disruption sweeps downgrade:
                              with the batched device engine wired
                              (solver/disrupt/), the sweep runs a BOUNDED
                              singleton-only device pass (one dispatch
                              over the cheapest candidates, deletion
                              verdicts only) -- cheap enough to leave on;
                              without it, the sweep stands down entirely
                              (controllers/disruption.py gates on this)
        2 shed-tracing     -- trace sampling stops feeding the per-span
                              stats/metrics volume, and an armed
                              jax.profiler capture (obs/profiler.py)
                              defers -- profiling has a real device-side
                              cost and must not deepen the overload it
                              would diagnose. The slow-tick trace
                              recorder still judges every sweep, and the
                              flight-data recorder (obs/flight.py) keeps
                              writing its per-tick record on EVERY rung:
                              it is the black box, and the ticks that
                              caused the brownout must stay visible
        3 shed-delta       -- delta-epoch class staging stands down (the
                              wire ships full; no staging diffs, no
                              restage retry roundtrips; bit-identical by
                              construction)

    Driven by an EWMA of tick overrun (tick duration / deadline): one
    rung per transition, entered at ``enter`` (default: ticks exceed the
    deadline on average), exited at ``exit`` (default: half the
    deadline), with a ``dwell`` of ticks between transitions so the
    ladder cannot flap. Level reads are lock-free (int store)."""

    LEVELS = ("normal", "shed-disruption", "shed-tracing", "shed-delta")
    log = get_logger("brownout")

    def __init__(self, deadline: float, enter: float = 1.0, exit: float = 0.5,
                 alpha: float = 0.3, dwell: int = 3):
        self.deadline = float(deadline)
        self.enter = float(enter)
        self.exit = float(exit)
        self.alpha = float(alpha)
        self.dwell = int(dwell)
        self._lock = threading.Lock()
        self._ewma: Optional[float] = None
        self._level = 0
        self._dwell_left = 0
        self.transitions = 0
        metrics.OVERLOAD_BROWNOUT_LEVEL.set(0.0)

    # -- pressure input (the operator calls this once per tick) --------------
    def observe(self, tick_seconds: float) -> int:
        """Feed one finished tick's duration; returns the (possibly new)
        level. Transition side effects (tracer throttle, metrics, log)
        run OUTSIDE the lock -- they touch other subsystems' locks."""
        ratio = tick_seconds / self.deadline if self.deadline > 0 else 0.0
        metrics.OVERLOAD_TICK_OVERRUN.observe(ratio)
        changed = False
        with self._lock:
            self._ewma = (
                ratio if self._ewma is None
                else (1.0 - self.alpha) * self._ewma + self.alpha * ratio
            )
            if self._dwell_left > 0:
                self._dwell_left -= 1
            elif self._ewma >= self.enter and self._level < len(self.LEVELS) - 1:
                self._level += 1
                changed = True
            elif self._ewma <= self.exit and self._level > 0:
                self._level -= 1
                changed = True
            if changed:
                self._dwell_left = self.dwell
                self.transitions += 1
            level, ewma = self._level, self._ewma
        if changed:
            self._apply(level, ewma)
        return level

    def _apply(self, level: int, ewma: float) -> None:
        from karpenter_tpu import tracing
        from karpenter_tpu.obs import profiler as obs_profiler

        metrics.OVERLOAD_BROWNOUT_LEVEL.set(float(level))
        metrics.OVERLOAD_BROWNOUT_TRANSITIONS.inc(to=self.LEVELS[level])
        # rung 2's effect applies on the transition edge in both
        # directions: throttle keeps the configured sample rate around
        # for the hysteretic recovery (tracing.Tracer.set_throttled).
        # The profiler capture throttles on the same edge -- an armed
        # capture defers and resumes when the ladder recovers. The
        # flight-data recorder is deliberately NOT on this rung.
        tracing.TRACER.set_throttled(level >= 2)
        obs_profiler.PROFILER.set_throttled(level >= 2)
        self.log.warning(
            "brownout ladder transition",
            ladder_level=self.LEVELS[level], overrun_ewma=round(ewma, 3),
        )

    # -- level reads (lock-free: int stores are atomic in CPython) ------------
    @property
    def level(self) -> int:
        return self._level

    def sheds_disruption(self) -> bool:
        return self._level >= 1

    def sheds_tracing(self) -> bool:
        return self._level >= 2

    def sheds_delta(self) -> bool:
        return self._level >= 3

    def describe(self) -> dict:
        """Brownout state document for /debug/overload."""
        with self._lock:
            return {
                "level": self._level,
                "level_name": self.LEVELS[self._level],
                "overrun_ewma": round(self._ewma, 4) if self._ewma is not None else None,
                "enter_threshold": self.enter,
                "exit_threshold": self.exit,
                "dwell_ticks_left": self._dwell_left,
                "transitions": self.transitions,
                "sheds": {
                    "disruption": self._level >= 1,
                    "tracing": self._level >= 2,
                    "delta": self._level >= 3,
                },
            }


# process-wide brownout handle, installed by the last-constructed
# Operator (the same process-policy shape as tracing.TRACER and the
# metrics registry; None = no brownout configured). Module-level so the
# solver client's delta shed needs no plumbing through ~6 layers.
_BROWNOUT: Optional[BrownoutController] = None


def install_brownout(ctrl: Optional[BrownoutController]) -> None:
    global _BROWNOUT
    _BROWNOUT = ctrl
    from karpenter_tpu import tracing
    from karpenter_tpu.obs import profiler as obs_profiler

    # the tracer/profiler throttles follow the INSTALLED brownout's
    # state: a new Operator replacing a mid-brownout one (tests,
    # restarts) must not inherit a stuck throttle from the previous reign
    throttled = ctrl is not None and ctrl.sheds_tracing()
    tracing.TRACER.set_throttled(throttled)
    obs_profiler.PROFILER.set_throttled(throttled)


def brownout() -> Optional[BrownoutController]:
    return _BROWNOUT


def sheds_delta() -> bool:
    """True while the brownout ladder's rung 3 is active (the solver
    client checks this per solve and ships full instead of delta)."""
    ctrl = _BROWNOUT
    return ctrl is not None and ctrl.sheds_delta()


# -- stuck-tick watchdog -------------------------------------------------------

def _async_raise_crash(thread_id: int) -> bool:
    """Raise OperatorCrashed INSIDE the (wedged) thread `thread_id` via
    the CPython async-exception hook. The exception lands at the
    thread's next bytecode boundary -- which is why the `stall`
    failpoint action sleeps in slices instead of one long sleep."""
    import ctypes

    from karpenter_tpu.failpoints import OperatorCrashed

    n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(OperatorCrashed)
    )
    if n > 1:
        # invalid/ambiguous target: undo rather than poison another thread
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(thread_id), None)
        return False
    return n == 1


class StuckTickWatchdog:
    """Detects a tick wedged past N x deadline and escalates through a
    fixed ladder -- the failure mode the breaker cannot see: its
    finish-level failure counter only advances when a wire call RETURNS,
    and a truly wedged solve (a hung device tunnel, a stalled stage
    inside the read timeout) never returns.

        cancel       (default  4 x deadline) -- close the solver wire:
                     a blocked ring wait sees the closed flag and raises,
                     a blocked socket read dies with its fd; either way
                     the solve ladder degrades and the tick completes
        breaker-open (default  8 x deadline) -- force the breaker open so
                     regular traffic stops touching the wire at all
        crash        (default 16 x deadline) -- async-raise
                     OperatorCrashed into the stuck thread; the run-loop
                     driver (or the process supervisor) restarts the
                     operator and the PR-6 recovery sweep takes over

    Deterministic rigs drive ``check_now()`` from their own loop; the
    production binary runs the background thread (``start()``)."""

    STAGES = ("cancel", "breaker-open", "crash")
    log = get_logger("watchdog")

    def __init__(self, deadline: float, *, cancel: Optional[Callable[[], None]] = None,
                 breaker=None, multiples=(4.0, 8.0, 16.0),
                 clock: Callable[[], float] = time.monotonic):
        self.deadline = float(deadline)
        self.multiples = tuple(float(m) for m in multiples)
        self._cancel = cancel
        self._breaker = breaker
        self._clock = clock
        self._lock = threading.Lock()
        self._started: Optional[float] = None
        self._thread_id: Optional[int] = None
        self._stage = 0
        # tick generation: bumps on every tick_started, so the crash
        # escalation can re-verify under the lock that the SAME tick is
        # still wedged immediately before the async raise (a tick that
        # un-wedged in the window between decision and raise must not
        # get OperatorCrashed injected into a now-healthy loop)
        self._generation = 0
        self.escalations = {s: 0 for s in self.STAGES}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- tick bracketing (called by Operator.tick on the loop thread) ---------
    def tick_started(self) -> None:
        with self._lock:
            self._started = self._clock()
            self._thread_id = threading.get_ident()
            self._stage = 0
            self._generation += 1

    def tick_finished(self) -> None:
        with self._lock:
            self._started = None
            self._stage = 0

    # -- escalation ----------------------------------------------------------
    def check_now(self) -> Optional[str]:
        """Evaluate the ladder once; returns the stage name fired, or
        None. The cancel/breaker hooks run OUTSIDE the lock (they take
        other subsystems' locks: the client's, the breaker's); the crash
        raise alone runs UNDER it -- see the comment at that rung."""
        with self._lock:
            if self._started is None or self._stage >= len(self.STAGES):
                return None
            elapsed = self._clock() - self._started
            if elapsed < self.multiples[self._stage] * self.deadline:
                return None
            stage = self._stage
            self._stage += 1
            tid = self._thread_id
            gen = self._generation
        name = self.STAGES[stage]
        if name == "crash":
            # flush the flight-data black box BEFORE the raise, from this
            # (the watchdog's own) thread: the wedged tick may never reach
            # a bytecode boundary (a C-level hang), in which case the
            # async exception never lands and the tick-side
            # OperatorCrashed flush never runs -- and once the raise is
            # pending, nothing after it in THIS thread is guaranteed
            # either (deterministic rigs drive check_now from the tick
            # thread itself)
            try:
                from karpenter_tpu.obs import flight

                flight.flush_blackbox(reason="watchdog-crash")
            except Exception:  # noqa: BLE001 -- best-effort, like cancel
                metrics.HANDLED_ERRORS.inc(site="overload.watchdog.flush")
            # re-check AND raise under the lock: tick_finished takes this
            # same lock, so the exception is pending in the wedged thread
            # before the tick can possibly be marked finished -- a tick
            # that un-wedged first stands the escalation down instead of
            # crashing a healthy loop. The raise itself takes no other
            # locks (one C call), so holding the lock across it is safe.
            with self._lock:
                still_wedged = (
                    self._started is not None and self._generation == gen
                    and tid is not None
                )
                if still_wedged:
                    _async_raise_crash(tid)
            if not still_wedged:
                self.log.warning(
                    "stuck tick un-wedged before the crash escalation; "
                    "standing down")
                return None
        self.escalations[name] += 1
        metrics.OVERLOAD_WATCHDOG.inc(stage=name)
        self.log.warning(
            "stuck-tick watchdog escalation",
            stage=name, elapsed_s=round(elapsed, 3), deadline_s=self.deadline,
        )
        if name == "cancel":
            if self._cancel is not None:
                try:
                    self._cancel()
                except Exception:  # noqa: BLE001 -- cancel is best-effort
                    metrics.HANDLED_ERRORS.inc(site="overload.watchdog.cancel")
        elif name == "breaker-open":
            if self._breaker is not None:
                try:
                    self._breaker.force_open(reason="stuck-tick watchdog")
                except Exception:  # noqa: BLE001 -- escalation is best-effort
                    metrics.HANDLED_ERRORS.inc(site="overload.watchdog.breaker")
        # (the crash rung already raised above, under the lock)
        return name

    # -- background loop (the wall-clock binary) ------------------------------
    def start(self) -> "StuckTickWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="stuck-tick-watchdog"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        interval = max(0.05, self.deadline / 2.0)
        while not self._stop.wait(timeout=interval):
            self.check_now()

    def stop(self) -> None:
        self._stop.set()

    def describe(self) -> dict:
        with self._lock:
            active_s = (
                round(self._clock() - self._started, 3)
                if self._started is not None else None
            )
        return {
            "deadline_s": self.deadline,
            "multiples": list(self.multiples),
            "tick_active_for_s": active_s,
            "escalations": dict(self.escalations),
        }
