"""Flight-data recorder: the always-on black box of the last 256 ticks.

The slow-tick trace recorder (tracing.FlightRecorder) keeps whole span
TREES, but only for ticks past a slowness threshold -- after a crash the
question is not "show me the slow ones" but "show me EVERYTHING that
led here". This ring keeps one compact record per tick, every tick,
regardless of tracing state or brownout rung (rung 2 throttles trace
*sampling*; the black box is exactly what must keep writing while the
system degrades -- test-pinned in tests/test_obs.py):

    {seq, t_mono_s, tick_ms, stages_ms, device_ms, hbm_*, dirty_fraction,
     consolidation_ms, consolidation_mode, consolidation_sets,
     deferred_pods, shed_total, brownout_level, breaker, nodes_ready,
     pods_bound_total, crashed?}

Two exits:

- ``/debug/flightdata`` (operator/health.py, loopback-only) serves the
  live ring as JSON;
- ``flush_blackbox(reason)`` writes the ring to a JSONL file (header
  line first, then one record per line, write-then-rename so a crashing
  process never leaves a torn file). The stuck-tick watchdog's crash
  escalation and the ``OperatorCrashed`` path through ``Operator.tick``
  both call it, so every postmortem starts with the last 256 ticks; the
  chaos/crash-chaos/overload CI jobs upload the file as an artifact on
  failure. Path: ``$KARPENTER_TPU_FLIGHTDATA`` (default
  ``flightdata.jsonl`` in the working directory).

Timestamps are MONOTONIC seconds plus the ring seq -- the recorder sits
on the replay path, and a wall-clock read here would be the exact
entropy the determinism lint exists to reject; correlate to wall time
through the log lines the same crash emits.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, Optional

from karpenter_tpu import metrics
from karpenter_tpu.logging import get_logger

BLACKBOX_ENV = "KARPENTER_TPU_FLIGHTDATA"
BLACKBOX_DEFAULT = "flightdata.jsonl"
CAPACITY_DEFAULT = 256

FLIGHT_RECORDS = metrics.REGISTRY.counter(
    "karpenter_flightdata_records_total",
    "Per-tick flight-data records appended to the black-box ring (one "
    "per operator sweep with the observatory enabled; keeps counting "
    "through every brownout rung by design)",
)
FLIGHT_FLUSHES = metrics.REGISTRY.counter(
    "karpenter_flightdata_flushes_total",
    "Black-box JSONL flushes by trigger (operator-crashed = the "
    "OperatorCrashed path through the tick; watchdog-crash = the "
    "stuck-tick watchdog's crash escalation; manual = operator-requested)",
    labels=("reason",),
)

log = get_logger("flightdata")


class FlightDataRecorder:
    """Bounded ring of per-tick records. ``record`` is the per-tick hot
    call: one lock, one deque append -- microseconds, measured into
    ``observatory_overhead_pct`` by bench. The lock is a leaf (nothing
    is called while holding it), so the recorder composes with every
    caller's locks."""

    def __init__(self, capacity: int = CAPACITY_DEFAULT):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self.flushes = 0
        self._last_flush_path: Optional[str] = None

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def configure(self, capacity: Optional[int] = None) -> "FlightDataRecorder":
        if capacity is not None and capacity != self._ring.maxlen:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(1, int(capacity)))
        return self

    def record(self, rec: Dict[str, Any]) -> int:
        """Append one tick's record; returns its seq. The record dict is
        stored as-is (callers build it fresh per tick; nothing mutates
        it after)."""
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            seq = self._seq
        FLIGHT_RECORDS.inc()
        return seq

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def dump(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self._ring.maxlen,
                "ticks_recorded": self._seq,
                "flushes": self.flushes,
                "last_flush_path": self._last_flush_path,
                "records": list(self._ring),
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self.flushes = 0
            self._last_flush_path = None

    def flush_blackbox(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the ring to a JSONL black box: one header line
        ``{"flight_data": ..., "reason": ...}`` then one record per
        line, oldest first. Write-then-rename (the PR-5 side-file
        pattern): the crash that triggered the flush must never leave a
        torn file. Returns the path, or None when the ring is empty or
        the write failed (a flush must never turn a crash into a
        different crash)."""
        with self._lock:
            records = list(self._ring)
            seq = self._seq
        if not records:
            return None
        path = path or os.environ.get(BLACKBOX_ENV) or BLACKBOX_DEFAULT
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    "flight_data": 1,
                    "reason": reason,
                    "ticks_recorded": seq,
                    "records": len(records),
                    "capacity": self._ring.maxlen,
                }) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, default=repr) + "\n")
            os.replace(tmp, path)
        except OSError as e:
            log.warning("flight-data flush failed", path=path, error=str(e))
            return None
        with self._lock:
            self.flushes += 1
            self._last_flush_path = path
        FLIGHT_FLUSHES.inc(reason=reason)
        log.warning(
            "flight data flushed", path=path, reason=reason, records=len(records),
        )
        return path


# process-wide recorder, the same shape as tracing.TRACER and
# metrics.REGISTRY: the operator feeds it per tick, /debug/flightdata
# and the crash paths read it without any plumbing
RECORDER = FlightDataRecorder()


def record(rec: Dict[str, Any]) -> int:
    return RECORDER.record(rec)


def flush_blackbox(reason: str, path: Optional[str] = None) -> Optional[str]:
    return RECORDER.flush_blackbox(reason, path=path)


def dump_json(indent: Optional[int] = None) -> str:
    return json.dumps(RECORDER.dump(), indent=indent, default=repr)


# span names whose durations the per-tick record keys on: the PR-2 span
# vocabulary (docs/observability.md tree) -- stable identifiers, same
# contract as bench's trace_stages_ms
STAGE_NAMES = (
    "snapshot", "dispatch", "drain", "launch", "bind", "disruption",
    "encode", "wire", "wire_dispatch", "device", "decode", "fetch",
)


def build_tick_record(root_sp, t0: float, *, solver=None, brownout=None,
                      breaker=None, disruption=None, crashed: bool = False,
                      clock=None) -> Dict[str, Any]:
    """ONE tick's flight record, the single source of what a record
    contains: the operator's per-tick path (Operator._observe_tick) and
    bench's observatory-overhead measurement both call THIS, so the <1%
    overhead contract is measured on exactly the work production pays --
    a field added here is automatically in both. Stage ms from the
    finished span tree, the rate-limited HBM poll, the solver's
    churn/staging state, and the overload/fleet gauges (plain dict
    reads)."""
    import time

    from karpenter_tpu import metrics
    from karpenter_tpu.obs import hbm

    now = (clock or time.monotonic)()
    rec: Dict[str, Any] = {
        "t_mono_s": round(now, 3),
        "tick_ms": round((now - t0) * 1e3, 3),
    }
    rec.update(stage_summary(root_sp))
    snap = hbm.poll()
    if snap["devices"]:
        rec["hbm_bytes_in_use"] = sum(
            d["bytes_in_use"] for d in snap["devices"].values()
        )
        rec["hbm_peak_bytes"] = hbm.peak_bytes_max()
    if snap["headroom_fraction"] is not None:
        rec["hbm_headroom"] = round(snap["headroom_fraction"], 4)
    if solver is not None:
        st = getattr(solver, "last_group_stats", None)
        if st and "dirty_fraction" in st:
            rec["dirty_fraction"] = round(float(st["dirty_fraction"]), 4)
        staged = getattr(solver, "staged_bytes_by_kind", None)
        if callable(staged):
            rec["staged_bytes"] = staged()
        # solution-quality observatory (obs/quality.py): the last solve's
        # gap + waste attribution headline fields -- cheap dict reads of
        # the document solve_finish already built, so the black box shows
        # answer quality next to where the time went
        q = getattr(solver, "last_quality", None)
        if q:
            if "optimality_gap" in q:
                rec["optimality_gap"] = q["optimality_gap"]
            rec["quality"] = {
                k: q[k]
                for k in ("bound_per_h", "realized_per_h",
                          "stranded_cpu_fraction", "stranded_memory_fraction",
                          "fragmentation_index")
                if k in q
            }
        # mesh fault tolerance: stamp the tick with the live topology
        # document (epoch, healthy/quarantined devices, ladder mode) so a
        # post-incident trace shows which device set each decision ran
        # under -- plain dict reads, same <1% overhead discipline
        engine = getattr(solver, "mesh_engine", None)
        topo = getattr(engine, "topology", None)
        if topo is not None:
            rec["topology"] = topo.describe()
            rec["topology"]["mode"] = topo.mode()
        if breaker is None:
            breaker = getattr(solver, "breaker", None)
    if breaker is not None:
        rec["breaker"] = breaker.state
    if brownout is not None:
        rec["brownout_level"] = brownout.level
    if disruption is not None:
        # device-consolidation sweep (controllers/disruption.py
        # last_sweep_stats): sweep mode + wall ms + candidate-set counts
        # by enumeration kind -- the black box must show whether the
        # rung-1 bounded sweep kept running through a brownout
        st = getattr(disruption, "last_sweep_stats", None)
        if st and "consolidation_ms" in st:
            rec["consolidation_ms"] = st["consolidation_ms"]
            rec["consolidation_mode"] = st.get("mode", "full")
            sets = st.get("sets") or {}
            if sets:
                rec["consolidation_sets"] = dict(sets)
    rec["deferred_pods"] = int(metrics.OVERLOAD_DEFERRED.value())
    shed = {
        reason: int(metrics.OVERLOAD_SHED.value(reason=reason))
        for reason in ("admission-cap", "deadline", "launch-bound")
    }
    if any(shed.values()):
        rec["shed_total"] = shed
    rec["nodes_ready"] = int(metrics.NODES_READY.value())
    rec["pods_bound_total"] = int(metrics.PODS_BOUND.value())
    if crashed:
        rec["crashed"] = True
    return rec


def stage_summary(root) -> Dict[str, Any]:
    """{stages_ms, device_ms} from one finished tick span tree (a
    tracing.Span root). Sums durations per STAGE_NAMES name across the
    tree -- ~20 nodes on a full tick, so the walk is cheap enough for
    every tick. Non-Span roots (tracing disabled -> the no-op
    singleton) summarize to nothing; the record still lands."""
    stages: Dict[str, float] = {}
    if root is None or not getattr(root, "children", None):
        return {}
    stack = list(root.children)
    while stack:
        sp = stack.pop()
        stack.extend(sp.children)
        if sp.name in STAGE_NAMES:
            end = sp.end if sp.end is not None else sp.start
            stages[sp.name] = stages.get(sp.name, 0.0) + (end - sp.start) * 1e3
    out: Dict[str, Any] = {}
    if stages:
        out["stages_ms"] = {k: round(v, 3) for k, v in sorted(stages.items())}
        if "device" in stages:
            out["device_ms"] = round(stages["device"], 3)
    return out
