"""Per-entry jit cost attribution: which program costs what, per tick.

The jax witness (analysis/jax_witness.py) already owns the compile
listener and the ``JIT_ENTRY_FUNCTIONS`` decoration-site registry, but
it answers one binary question -- "did the warm path retrace?". This
module extends it into a continuous accounting TABLE: per jit entry,
how many dispatches, how much cumulative dispatch wall time, how many
compiles, how much compile time -- the attribution CvxCluster-style
batching work needs ("which entry pays when the candidate batch grows")
and the `/debug/solver` surface serves.

Mechanism: ``install()`` wraps every registered entry function (the
module attribute -- every call site in the tree calls through the
module, verified at review) in a probe that

- counts the call and its wall time into the entry's row. On an async
  backend this is DISPATCH cost (trace + lowering on a cache miss,
  argument staging + launch on a hit); device EXECUTION overlaps
  asynchronously and lands behind the sanctioned fetch barriers, so the
  per-entry on-device timeline is the profiler capture's job
  (obs/profiler.py), not this table's -- the column is named
  ``dispatch_ms`` for exactly that reason;
- attributes compiles: the witness's compile listener runs
  synchronously in the compiling thread, so a delta of THIS thread's
  trace totals (``jax_witness.thread_trace_totals``) across one probe
  call belongs to that entry -- a concurrent compile on another thread
  (auto_warm precompile, a sidecar handler) lands in its own thread's
  ledger and is never misattributed. Attribution only populates while
  the witness is installed (tests, bench, and any deployment that opts
  in) and reads zero otherwise.

The probe forwards ``_cache_size`` (jax's own per-function cache
introspection) so ``jax_witness.entry_cache_sizes()`` keeps working
through the wrapper, and forwards the jitted function's ``__wrapped__``
(the raw Python function -- mesh.py re-jits it with shardings);
uninstall restores from its own originals map.
Cost: two clock reads + four counter bumps per dispatch -- a handful of
microseconds against a millisecond-scale solve, inside the bench
observatory overhead budget.
"""
from __future__ import annotations

import importlib
import threading
import time
from typing import Any, Dict

from karpenter_tpu import metrics
from karpenter_tpu.analysis import jax_witness
from karpenter_tpu.analysis.checkers.jax_discipline import JIT_ENTRY_FUNCTIONS

JIT_DISPATCHES = metrics.REGISTRY.counter(
    "karpenter_jit_entry_dispatches_total",
    "Calls into each registered jit entry point (JIT_ENTRY_FUNCTIONS), "
    "per entry -- the denominator of every per-entry cost claim",
    labels=("entry",),
)
JIT_DISPATCH_SECS = metrics.REGISTRY.counter(
    "karpenter_jit_entry_dispatch_seconds_total",
    "Cumulative wall seconds inside each jit entry call: trace+lower on "
    "a cache miss, argument staging + async launch on a hit (device "
    "execution overlaps and is NOT in here -- capture it with "
    "/debug/profile)",
    labels=("entry",),
)
JIT_COMPILES = metrics.REGISTRY.counter(
    "karpenter_jit_entry_compiles_total",
    "Jit traces attributed to each entry (compile-counter delta across "
    "one dispatch; populated while the jax witness's compile listener "
    "is installed)",
    labels=("entry",),
)
JIT_COMPILE_SECS = metrics.REGISTRY.counter(
    "karpenter_jit_entry_compile_seconds_total",
    "Cumulative jaxpr-trace seconds attributed to each entry (the "
    "retrace stall cost; backend-compile time comes on top when the "
    "persistent compilation cache misses)",
    labels=("entry",),
)
# AOT precompiles (solver/aot.py) attribute to their own counters --
# phase="aot" in spirit: warmup-ladder compiles must never pollute the
# hot-path per-entry compile counters above, whose zeros the bench and
# the zero-retrace tests assert
JIT_AOT_COMPILES = metrics.REGISTRY.counter(
    "karpenter_jit_entry_aot_compiles_total",
    "Warmup-ladder AOT precompiles per jit entry family (solver/aot.py; "
    "kept apart from karpenter_jit_entry_compiles_total so background "
    "precompilation never reads as hot-path compile cost)",
    labels=("entry",),
)
JIT_AOT_COMPILE_SECS = metrics.REGISTRY.counter(
    "karpenter_jit_entry_aot_compile_seconds_total",
    "Cumulative wall seconds the AOT warmup ladder spent precompiling "
    "each entry family (lower+compile, off the tick thread)",
    labels=("entry",),
)
COMPILE_CACHE_HITS = metrics.REGISTRY.counter(
    "karpenter_compile_cache_hits_total",
    "Persistent XLA compilation-cache hits (the backend binary came "
    "from disk; only the trace/lower phases ran)",
)
COMPILE_CACHE_MISSES = metrics.REGISTRY.counter(
    "karpenter_compile_cache_misses_total",
    "Persistent XLA compilation-cache misses (a full backend compile "
    "ran and its artifact was written). The CI cache-persistence drill "
    "asserts this stays 0 in a second process over a warm cache",
)
COMPILE_CACHE_BYTES = metrics.REGISTRY.gauge(
    "karpenter_compile_cache_bytes",
    "On-disk size of the persistent compile cache's versioned directory "
    "(XLA entries + serialized AOT executables), for the cache-sizing "
    "runbook in docs/operations.md",
)

_lock = threading.Lock()
# entry -> [dispatches, dispatch_secs, compiles, compile_secs]
_table: Dict[str, list] = {}
# entry family -> [aot compiles, aot compile secs] (the warmup ladder)
_aot_table: Dict[str, list] = {}
# modname -> {fn_name: original}; non-empty = installed
_originals: Dict[str, Dict[str, Any]] = {}
_cache_listener_installed = False


def _probe(entry: str, fn):
    thread_totals = jax_witness.thread_trace_totals

    def probed(*args: Any, **kwargs: Any):
        t0 = time.perf_counter()
        # THREAD-LOCAL trace totals: the compile listener runs
        # synchronously in the compiling thread, so a delta on this
        # thread's counters belongs to THIS dispatch -- a concurrent
        # compile (the auto_warm precompile thread, a sidecar handler)
        # lands in its own thread's ledger, never double-attributed here
        tr0, ts0 = thread_totals()
        try:
            return fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            tr1, ts1 = thread_totals()
            d_traces = tr1 - tr0
            d_secs = ts1 - ts0
            with _lock:
                row = _table.setdefault(entry, [0, 0.0, 0, 0.0])
                row[0] += 1
                row[1] += dt
                row[2] += d_traces
                row[3] += d_secs
            JIT_DISPATCHES.inc(entry=entry)
            JIT_DISPATCH_SECS.inc(dt, entry=entry)
            if d_traces:
                JIT_COMPILES.inc(d_traces, entry=entry)
                JIT_COMPILE_SECS.inc(d_secs, entry=entry)

    probed._karpenter_jit_probe = True  # type: ignore[attr-defined]
    # __wrapped__ forwards what the jitted function itself exposes --
    # jax.jit sets it to the RAW Python function, and mesh.py re-jits
    # exactly that with shardings (disrupt/kernel.disrupt_repack
    # .__wrapped__); pointing it at the jitted fn would silently build
    # pjit-in-pjit
    probed.__wrapped__ = getattr(fn, "__wrapped__", fn)  # type: ignore[attr-defined]
    probed.__name__ = getattr(fn, "__name__", entry)
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is not None:
        # entry_cache_sizes() polls this through the module attribute;
        # the probe must stay transparent to it
        probed._cache_size = cache_size  # type: ignore[attr-defined]
    return probed


def install() -> int:
    """Wrap every registered jit entry with the dispatch probe; returns
    the number of probes installed. Idempotent. Imports the solver
    modules -- callers are the operator (which already built a solver)
    and bench, never a lint/analysis process."""
    installed = 0
    for modname, fns in JIT_ENTRY_FUNCTIONS.items():
        mod = importlib.import_module(modname)
        saved = _originals.setdefault(modname, {})
        for fn_name in fns:
            if fn_name in saved:
                continue
            fn = getattr(mod, fn_name, None)
            # jax.jit itself sets __wrapped__, so the probe carries its
            # own marker to make reinstall idempotent
            if fn is None or getattr(fn, "_karpenter_jit_probe", False):
                continue
            saved[fn_name] = fn
            setattr(mod, fn_name, _probe(f"{modname}.{fn_name}", fn))
            installed += 1
    return installed


def original(modname: str, fn_name: str):
    """The pre-probe jitted function for an installed entry, or None --
    the AOT plan builder (solver/aot.py) lowers through THIS (the probe
    wrapper has no .lower()); transparent when probes are absent."""
    return _originals.get(modname, {}).get(fn_name)


def note_aot(entry: str, secs: float) -> None:
    """Attribute one warmup-ladder precompile to `entry`'s AOT row --
    the phase=\"aot\" seam: solver/aot.py calls this per ladder task so
    precompiles show up in table() without touching the hot-path
    compile counters."""
    with _lock:
        row = _aot_table.setdefault(entry, [0, 0.0])
        row[0] += 1
        row[1] += secs
    JIT_AOT_COMPILES.inc(entry=entry)
    JIT_AOT_COMPILE_SECS.inc(secs, entry=entry)


_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_cache_event(event: str, **kw: Any) -> None:
    if event == _CACHE_HIT_EVENT:
        COMPILE_CACHE_HITS.inc()
    elif event == _CACHE_MISS_EVENT:
        COMPILE_CACHE_MISSES.inc()


def install_cache_listener() -> None:
    """Register the persistent-compilation-cache hit/miss listener
    (plain jax.monitoring events, fired by jax's cache layer on every
    backend-compile lookup). Idempotent; jax.monitoring has no
    unregister, so reinstall is a no-op rather than a double-count."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    import jax

    jax.monitoring.register_event_listener(_on_cache_event)
    _cache_listener_installed = True


def update_cache_bytes(path: str) -> int:
    """Walk the versioned cache directory and publish its size (jax
    emits no bytes event, so the gauge is a dir scan -- called at
    startup and by /debug/aot scrapes, never per tick)."""
    import os

    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for name in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                continue
    COMPILE_CACHE_BYTES.set(float(total))
    return total


def cache_stats() -> Dict[str, float]:
    """{hits, misses, bytes} snapshot of the persistent-cache counters
    (bench coldstart stage + the CI cache-persistence drill)."""
    return {
        "hits": COMPILE_CACHE_HITS.value(),
        "misses": COMPILE_CACHE_MISSES.value(),
        "bytes": COMPILE_CACHE_BYTES.value(),
    }


def uninstall() -> None:
    import sys

    for modname, saved in _originals.items():
        mod = sys.modules.get(modname)
        if mod is None:
            continue
        for fn_name, fn in saved.items():
            setattr(mod, fn_name, fn)
    _originals.clear()


def installed() -> bool:
    return bool(_originals)


def reset() -> None:
    with _lock:
        _table.clear()
        _aot_table.clear()


def table() -> Dict[str, Dict[str, Any]]:
    """The accounting table, per entry: {dispatches, dispatch_ms,
    compiles, compile_ms, cache_size}. Cache sizes ride along from the
    witness's registry poll so a grown entry is visible next to its
    dispatch cost ({} while probes are not installed)."""
    with _lock:
        rows = {k: list(v) for k, v in _table.items()}
        aot_rows = {k: list(v) for k, v in _aot_table.items()}
    if not rows and not aot_rows and not _originals:
        return {}
    sizes = jax_witness.entry_cache_sizes()
    out: Dict[str, Dict[str, Any]] = {}
    for entry, (dispatches, d_secs, compiles, c_secs) in sorted(rows.items()):
        out[entry] = {
            "dispatches": dispatches,
            "dispatch_ms": round(d_secs * 1e3, 3),
            "compiles": compiles,
            "compile_ms": round(c_secs * 1e3, 3),
        }
        if entry in sizes:
            out[entry]["cache_size"] = sizes[entry]
    # the warmup ladder's precompiles ride along under their own columns
    # (phase="aot"): visible per family, never mixed into "compiles"
    for entry, (n, secs) in sorted(aot_rows.items()):
        row = out.setdefault(entry, {"dispatches": 0, "dispatch_ms": 0.0,
                                     "compiles": 0, "compile_ms": 0.0})
        row["aot_compiles"] = n
        row["aot_compile_ms"] = round(secs * 1e3, 3)
    # entries registered but never dispatched still show their cache
    # size: "this program exists and is resident" is attribution too
    for entry, size in sorted(sizes.items()):
        out.setdefault(entry, {"dispatches": 0, "dispatch_ms": 0.0,
                               "compiles": 0, "compile_ms": 0.0,
                               "cache_size": size})
    return out
