"""Solution-quality observatory: waste attribution + the optimality gap.

Every observability layer so far measured where the time and bytes go;
this module measures whether the ANSWERS are any good. Two halves:

- ``solver/bound.py`` computes the in-jit fractional lower bound on
  hourly fleet price (each placed pod billed the cheapest feasible price
  per unit of its binding resource); ``TPUSolver.solve_finish``
  dispatches it per warm tick and records the result here.
- this module turns decode outputs (and, for sim replays, the live node
  set) into waste attribution: per-node stranded CPU/mem fractions, a
  fleet fragmentation index, hourly price decomposed by nodepool and
  capacity type, and the headline ``karpenter_quality_optimality_gap``
  = realized fleet price / bound.

Strictly observe-only: nothing downstream of a scheduling decision reads
any of it (the sim corpus pins every existing decision digest
byte-unchanged with quality KPIs on), and every producer wraps its calls
so a quality failure can never take a tick down.

Exits: the flight-recorder tick record (obs/flight.py reads
``solver.last_quality``), the Prometheus gauges below, the loopback-only
``/debug/quality`` endpoint (operator/health.py serves ``dump_json``),
and the sim replay KPIs (``optimality_gap_p50``/``_final``,
``stranded_cpu_fraction``, ...) gated by tests/golden/scenarios/
quality.json.

Interpreting the numbers (docs/observability.md has the runbook): the
gap is realized/bound, so 1.0 is a certificate of fractional optimality
and a RISING gap means the packer is leaving more money on the table --
correlate with ``stranded_*`` (capacity bought but unusable: the binpack
residue) and ``fragmentation_index`` (how scattered the free capacity
is: near 1.0 the residue is spread too thin to host anything).

This module is jax-free at import by design (it must be importable from
the sim CLI and the metrics generator without initializing a backend).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.scheduling import resources as res

QUALITY_GAP = metrics.REGISTRY.gauge(
    "karpenter_quality_optimality_gap",
    "Realized hourly fleet price of the last solve's new groups divided "
    "by the fractional lower bound (solver/bound.py) -- 1.0 is a "
    "certificate of fractional optimality; a rising value means the "
    "packer is leaving money on the table (observe-only)",
)
QUALITY_BOUND = metrics.REGISTRY.gauge(
    "karpenter_quality_bound_price_per_hour",
    "The fractional lower bound on the hourly price of hosting the last "
    "solve's placed pods (solver/bound.py fractional_price_bound)",
)
QUALITY_STRANDED = metrics.REGISTRY.gauge(
    "karpenter_quality_stranded_fraction",
    "Fraction of bought allocatable capacity the last solve's packing "
    "left unusable (stranded), by resource axis -- the binpack residue "
    "waste attribution charges the solver with",
    labels=("resource",),
)
QUALITY_FRAGMENTATION = metrics.REGISTRY.gauge(
    "karpenter_quality_fragmentation_index",
    "Fleet fragmentation index in [0, 1]: 1 - (largest single-node free "
    "CPU / total free CPU). 0 = all free capacity on one node (usable); "
    "near 1 = free capacity scattered too thin to host anything",
)

# last computed quality document, process-wide (the same shape as
# tracing.TRACER / flight.RECORDER): solve_finish records here,
# /debug/quality and the flight recorder read without plumbing
_LOCK = threading.Lock()
_LAST: Dict[str, Any] = {}


def record(q: Dict[str, Any]) -> None:
    global _LAST
    with _LOCK:
        _LAST = q


def snapshot() -> Dict[str, Any]:
    with _LOCK:
        return dict(_LAST)


def reset() -> None:
    record({})


def dump_json(indent: Optional[int] = None) -> str:
    doc = snapshot()
    return json.dumps(doc if doc else {"configured": False}, indent=indent,
                      default=repr)


def solve_quality(
    result, bound_per_h: Optional[float], binding_resource: Optional[int],
) -> Dict[str, Any]:
    """One solve's quality document from the DECODE outputs: realized
    price (sum of each new group's cheapest surviving type -- exactly
    what the launch pays), waste attribution against those same chosen
    types, and the optimality gap against the device bound. Sets the
    gauges and records the doc (callers additionally stash it on
    ``solver.last_quality`` for the flight recorder). Pure dict/object
    reads -- no device values anywhere near this."""
    realized = 0.0
    by_pool: Dict[str, float] = {}
    by_captype: Dict[str, float] = {}
    alloc_tot = {res.CPU: 0.0, res.MEMORY: 0.0}
    used_tot = {res.CPU: 0.0, res.MEMORY: 0.0}
    free_cpus: List[float] = []
    for g in result.new_groups:
        chosen = g.instance_types[0]
        price = chosen.cheapest_price()
        realized += price
        pool_name = getattr(g.nodepool, "name", "?")
        by_pool[pool_name] = by_pool.get(pool_name, 0.0) + price
        offerings = chosen.available_offerings()
        ct = min(offerings, key=lambda o: o.price).capacity_type if offerings else "?"
        by_captype[ct] = by_captype.get(ct, 0.0) + price
        alloc = chosen.allocatable()
        for axis in (res.CPU, res.MEMORY):
            a = alloc.get(axis)
            u = min(g.requested.get(axis), a)
            alloc_tot[axis] += a
            used_tot[axis] += u
        free_cpus.append(max(alloc.get(res.CPU) - g.requested.get(res.CPU), 0.0))
    q: Dict[str, Any] = {
        "groups": len(result.new_groups),
        "realized_per_h": round(realized, 6),
        "price_by_pool": {k: round(v, 6) for k, v in sorted(by_pool.items())},
        "price_by_capacity_type": {
            k: round(v, 6) for k, v in sorted(by_captype.items())
        },
        "stranded_cpu_fraction": stranded_fraction(
            alloc_tot[res.CPU], used_tot[res.CPU]),
        "stranded_memory_fraction": stranded_fraction(
            alloc_tot[res.MEMORY], used_tot[res.MEMORY]),
        "fragmentation_index": fragmentation_index(free_cpus),
    }
    if bound_per_h is not None and bound_per_h > 0.0 and realized > 0.0:
        q["bound_per_h"] = round(bound_per_h, 6)
        q["optimality_gap"] = round(realized / bound_per_h, 6)
        if binding_resource is not None:
            q["binding_resource"] = res.RESOURCE_AXES[binding_resource]
    _set_gauges(q)
    record(q)
    return q


def _set_gauges(q: Dict[str, Any]) -> None:
    if "optimality_gap" in q:
        QUALITY_GAP.set(float(q["optimality_gap"]))
    if "bound_per_h" in q:
        QUALITY_BOUND.set(float(q["bound_per_h"]))
    QUALITY_STRANDED.set(float(q["stranded_cpu_fraction"]), resource="cpu")
    QUALITY_STRANDED.set(float(q["stranded_memory_fraction"]), resource="memory")
    QUALITY_FRAGMENTATION.set(float(q["fragmentation_index"]))


def stranded_fraction(alloc_total: float, used_total: float) -> float:
    """Fraction of bought allocatable capacity left unusable by the
    packing. 0 when nothing was bought (an empty fleet strands nothing)."""
    if alloc_total <= 0.0:
        return 0.0
    return round(max(alloc_total - used_total, 0.0) / alloc_total, 6)


def fragmentation_index(free_per_node: List[float]) -> float:
    """1 - (largest single-node free CPU / total free CPU), in [0, 1].
    All free capacity concentrated on one node scores 0 (a big hole a
    big pod can use); the same total scattered evenly over N nodes
    scores 1 - 1/N (residue too thin to host anything)."""
    total = sum(free_per_node)
    if total <= 0.0 or len(free_per_node) <= 1:
        return 0.0
    return round(1.0 - max(free_per_node) / total, 6)


# -- sim-replay reference quality (host, any backend) -------------------------
#
# Wire-mode rigs stage nothing locally, so the device bound only runs
# in-process; replays instead compute the SAME fractional bound on host
# from the catalog the operator's provider serves -- coarser (no
# per-class feasibility masks: the min ranges over the whole catalog,
# which only loosens the bound, never unsounds it) but backend-uniform,
# so host/wire/pipelined KPIs are comparable. Per-type price rates are
# memoized by catalog-list identity (providers rebuild the list when
# pricing changes; a stale tick between price event and refresh can dip
# a tick's gap below 1, which is why the corpus gate pins UPPER bounds).

_rates_cache: Dict[int, tuple] = {}


def _fleet_rates(instance_types) -> Optional[list]:
    """[R] $/h per base unit of each resource axis: min over catalog
    types of cheapest_price / capacity -- the whole-fleet analogue of
    bound.py's per-class rate."""
    key = id(instance_types)
    hit = _rates_cache.get(key)
    if hit is not None and hit[0] is instance_types:
        return hit[1]
    R = res.NUM_RESOURCE_AXES
    rates = [float("inf")] * R
    for it in instance_types:
        price = it.cheapest_price()
        if price == float("inf"):
            continue
        cap = it.capacity.to_vector()
        for r in range(R):
            if cap[r] > 0.0:
                rate = price / cap[r]
                if rate < rates[r]:
                    rates[r] = rate
    if all(r == float("inf") for r in rates):
        return None
    _rates_cache[key] = (instance_types, rates)
    while len(_rates_cache) > 64:
        _rates_cache.pop(next(iter(_rates_cache)))
    return rates


def fleet_bound(bound_pods, instance_types) -> float:
    """Fractional lower bound on the hourly price of any fleet hosting
    ``bound_pods``: max over resource axes of (total demand * cheapest
    per-unit rate). Sound because a node of type t hosting usage u_r
    has price >= cheapest_price(t) >= rate_r * cap_r(t) >= rate_r * u_r,
    and usage sums to at least the bound pods' requests."""
    rates = _fleet_rates(instance_types)
    if rates is None:
        return 0.0
    R = res.NUM_RESOURCE_AXES
    demand = [0.0] * R
    pods_axis = res.RESOURCE_AXES.index(res.PODS) if res.PODS in res.RESOURCE_AXES else None
    for p in bound_pods:
        vec = p.requests.to_vector()
        for r in range(R):
            demand[r] += vec[r]
        if pods_axis is not None:
            demand[pods_axis] += 1.0  # every pod occupies one pod slot
    best = 0.0
    for r in range(R):
        if rates[r] != float("inf") and demand[r] > 0.0:
            best = max(best, demand[r] * rates[r])
    return best


def fleet_waste(nodes, usage_map) -> Dict[str, float]:
    """Live-fleet waste attribution for sim replays: stranded CPU/mem
    fractions (allocatable bought vs used) and the fragmentation index,
    from the node set + the usage map the invariant check already
    built."""
    alloc_cpu = used_cpu = alloc_mem = used_mem = 0.0
    free_cpus: List[float] = []
    for n in nodes:
        alloc = n.allocatable
        used = usage_map.get(n.metadata.name)
        a_cpu, a_mem = alloc.get(res.CPU), alloc.get(res.MEMORY)
        u_cpu = min(used.get(res.CPU), a_cpu) if used is not None else 0.0
        u_mem = min(used.get(res.MEMORY), a_mem) if used is not None else 0.0
        alloc_cpu += a_cpu
        used_cpu += u_cpu
        alloc_mem += a_mem
        used_mem += u_mem
        free_cpus.append(max(a_cpu - u_cpu, 0.0))
    return {
        "stranded_cpu_fraction": stranded_fraction(alloc_cpu, used_cpu),
        "stranded_memory_fraction": stranded_fraction(alloc_mem, used_mem),
        "fragmentation_index": fragmentation_index(free_cpus),
    }


def fleet_price_decomposition(nodes, node_price) -> Dict[str, Dict[str, float]]:
    """Hourly fleet price decomposed by nodepool and capacity type from
    live node labels (sim replays; the per-solve decomposition in
    solve_quality reads decode outputs instead)."""
    from karpenter_tpu.apis import labels as wk

    by_pool: Dict[str, float] = {}
    by_captype: Dict[str, float] = {}
    for n in nodes:
        p = node_price(n)
        pool = n.metadata.labels.get(wk.NODEPOOL_LABEL, "?")
        ct = n.metadata.labels.get(wk.CAPACITY_TYPE_LABEL, "?")
        by_pool[pool] = by_pool.get(pool, 0.0) + p
        by_captype[ct] = by_captype.get(ct, 0.0) + p
    return {
        "price_by_pool": {k: round(v, 6) for k, v in sorted(by_pool.items())},
        "price_by_capacity_type": {
            k: round(v, 6) for k, v in sorted(by_captype.items())
        },
    }
