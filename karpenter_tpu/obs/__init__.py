"""Device performance observatory: where device time and memory go.

Every scale claim since the r04 TPU capture is CPU-rig-only, and the
tree had no device-side truth at all: no HBM accounting, no
``jax.profiler`` integration, no per-jit-entry cost attribution. Before
catalogs shard across an 8-device mesh or consolidation candidate sets
stage on-device, the repo needs the instrument panel that says what each
staged epoch costs in HBM and what each jit entry costs in compile and
dispatch time. Four layers, one package:

- ``hbm``       -- HBM accounting: ``device.memory_stats()`` polled per
  tick into ``karpenter_device_hbm_*`` gauges, staged tensor bytes
  attributed by owner (catalog seqnum vs class epoch vs solve
  temporaries -- ``karpenter_solver_staged_bytes{kind}``), and a
  headroom signal that lets the staged LRUs evict on memory PRESSURE
  instead of only at their fixed capacity.
- ``jitstats``  -- per-entry jit cost attribution: the compile listener
  the jax witness already owns, extended from a zero-retrace assert
  into a continuous accounting table (compile ms, dispatch count,
  cumulative dispatch ms per ``JIT_ENTRY_FUNCTIONS`` entry), served on
  ``/debug/solver`` and scraped as ``karpenter_jit_entry_*``.
- ``profiler``  -- on-demand ``jax.profiler`` capture: ``/debug/profile
  ?ticks=N`` (and ``--profile-ticks N``) brackets the next N production
  ticks in a programmatic trace for TensorBoard/xprof; brownout rung 2
  throttles it exactly like trace sampling.
- ``flight``    -- the always-on flight-data recorder: a bounded ring
  of per-tick records (stage ms from the span tree, device ms, HBM
  watermark, dirty fraction, shed counts, brownout rung, breaker
  state, fleet KPIs) behind ``/debug/flightdata``, flushed to a JSONL
  black box by the stuck-tick watchdog's crash escalation and the
  ``OperatorCrashed`` path -- every postmortem starts with the last
  256 ticks.

The whole observatory is a measured <1% of the warm tick
(``observatory_overhead_pct`` in bench) and a no-op when idle; the
profiler and memory-stats seams are sanctioned in the jaxhost manifest
so ``make lint`` and the runtime witnesses stay zero-violation.
"""
from __future__ import annotations

from karpenter_tpu.obs import flight, hbm, jitstats, profiler

__all__ = ["flight", "hbm", "jitstats", "profiler"]
