"""HBM accounting: device memory truth, per tick, with owner attribution.

``device.memory_stats()`` is the runtime's own allocator ledger (bytes
in use, bytes reservable, peak) -- a cheap C call, safe on the warm
tick. This module polls it into gauges, tracks the process-lifetime
peak, and derives one **headroom** signal the staging layers consume:
when the fraction of HBM still free drops below the evict threshold,
the staged-catalog and class-epoch LRUs (solver/service.py,
solver/rpc.py) shrink to a floor of one entry instead of waiting for
their fixed capacity of 4 -- memory pressure evicts, not just slot
count.

Attribution rides next to the raw gauges: the solver service and the
sidecar already know their staged dicts, so summing ``nbytes`` per
entry splits staged bytes by owner into
``karpenter_solver_staged_bytes{kind=catalog|class_epoch|
solve_temporaries}`` (see ``TPUSolver.staged_bytes_by_kind`` and
``SolverServer._staged_bytes``). ``sum_nbytes`` here is the shared
walker: ``.nbytes`` is array METADATA on both numpy and jax arrays --
reading it never transfers, which is why this whole layer stays
witness-clean.

The CPU backend returns ``memory_stats() -> None`` (no allocator
ledger); polls then record nothing and ``headroom()`` is None, so every
pressure consumer degrades to capacity-only eviction -- the exact
pre-observatory behavior. Tests inject a provider
(``set_stats_provider``) to exercise the pressure paths off-device.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from karpenter_tpu import metrics

HBM_IN_USE = metrics.REGISTRY.gauge(
    "karpenter_device_hbm_bytes_in_use",
    "Device HBM bytes currently allocated, per device, from the runtime's "
    "own allocator ledger (device.memory_stats(); absent on backends "
    "without one, e.g. CPU)",
    labels=("device",),
)
HBM_LIMIT = metrics.REGISTRY.gauge(
    "karpenter_device_hbm_bytes_limit",
    "Device HBM capacity visible to the allocator, per device "
    "(bytes_limit from device.memory_stats())",
    labels=("device",),
)
HBM_PEAK = metrics.REGISTRY.gauge(
    "karpenter_device_hbm_peak_bytes",
    "High-water mark of device HBM bytes in use since process start, per "
    "device (max over every observatory poll; the runtime's own "
    "peak_bytes_in_use when it reports one)",
    labels=("device",),
)
HBM_HEADROOM = metrics.REGISTRY.gauge(
    "karpenter_device_hbm_headroom_fraction",
    "Fraction of HBM still free on the FULLEST device (min over devices "
    "of 1 - in_use/limit); below the evict threshold the staged-catalog "
    "and class-epoch LRUs shrink on pressure instead of capacity -- see "
    "karpenter_solver_staged_pressure_evictions_total",
)

# headroom fraction below which the staging LRUs evict down to their
# floor (docs/observability.md HBM runbook); 0 disables pressure
# eviction entirely
EVICT_HEADROOM_ENV = "KARPENTER_TPU_HBM_EVICT_HEADROOM"
EVICT_HEADROOM_DEFAULT = 0.10

# polls within this window reuse the last snapshot: the per-tick caller
# (the flight recorder) and the per-stage caller (the sidecar's LRU
# insert) must not turn a fast tick loop into a memory_stats() storm
POLL_MAX_AGE_S = 0.2

_lock = threading.Lock()
_peak: Dict[str, int] = {}
_last_snapshot: Dict[str, Any] = {"devices": {}, "headroom_fraction": None}
_last_poll: float = -1e9
# test seam: () -> {device_label: {"bytes_in_use": int, "bytes_limit":
# int, ...}} | None; None = read the real jax devices
_stats_provider: Optional[Callable[[], Optional[Dict[str, dict]]]] = None


def set_stats_provider(fn: Optional[Callable[[], Optional[Dict[str, dict]]]]) -> None:
    """Inject a memory-stats source (tests / fakes); None restores the
    real ``jax.devices()`` walk. Resets the peak ledger: a provider swap
    is a new device world."""
    global _stats_provider, _last_poll
    with _lock:
        _stats_provider = fn
        _peak.clear()
        _last_poll = -1e9


def _real_stats() -> Optional[Dict[str, dict]]:
    import sys

    if "jax" not in sys.modules:
        # accounting must never be the reason the jax runtime comes up:
        # a solver-less operator (oracle mode, light tests) polls nothing
        return None
    try:
        import jax

        out: Dict[str, dict] = {}
        for d in jax.devices():
            st = d.memory_stats()
            if st:
                out[f"{d.platform}:{d.id}"] = dict(st)
        return out or None
    except Exception:  # noqa: BLE001 -- accounting must never fail a tick
        metrics.HANDLED_ERRORS.inc(site="obs.hbm.memory_stats")
        return None


def poll(max_age_s: float = POLL_MAX_AGE_S) -> Dict[str, Any]:
    """One accounting pass: read memory stats, update the gauges and the
    per-device peak ledger, return the snapshot. Recent polls (within
    ``max_age_s``) return the cached snapshot untouched."""
    global _last_poll
    now = time.monotonic()
    with _lock:
        if now - _last_poll < max_age_s:
            return dict(_last_snapshot)
        provider = _stats_provider
    stats = provider() if provider is not None else _real_stats()
    devices: Dict[str, dict] = {}
    headroom: Optional[float] = None
    if stats:
        for label, st in sorted(stats.items()):
            in_use = int(st.get("bytes_in_use", 0))
            limit = int(st.get("bytes_limit", 0))
            peak = max(int(st.get("peak_bytes_in_use", 0)), in_use)
            with _lock:
                peak = max(peak, _peak.get(label, 0))
                _peak[label] = peak
            HBM_IN_USE.set(float(in_use), device=label)
            HBM_PEAK.set(float(peak), device=label)
            if limit > 0:
                HBM_LIMIT.set(float(limit), device=label)
                free = 1.0 - in_use / limit
                headroom = free if headroom is None else min(headroom, free)
            devices[label] = {
                "bytes_in_use": in_use, "bytes_limit": limit,
                "peak_bytes": peak,
            }
        if headroom is not None:
            HBM_HEADROOM.set(headroom)
    snapshot = {"devices": devices, "headroom_fraction": headroom}
    with _lock:
        _last_snapshot.clear()
        _last_snapshot.update(snapshot)
        _last_poll = now
    return snapshot


def headroom() -> Optional[float]:
    """Min-over-devices free-HBM fraction from a fresh-enough poll;
    None when no device reports an allocator ledger (CPU backend)."""
    return poll().get("headroom_fraction")


def evict_threshold() -> float:
    try:
        return float(os.environ.get(EVICT_HEADROOM_ENV, EVICT_HEADROOM_DEFAULT))
    except ValueError:
        return EVICT_HEADROOM_DEFAULT


def under_pressure() -> bool:
    """True when the fullest device's free fraction is below the evict
    threshold -- the staging LRUs' signal to shrink to their floor. No
    ledger (CPU) = never under pressure (capacity eviction still holds)."""
    thresh = evict_threshold()
    if thresh <= 0:
        return False
    free = headroom()
    return free is not None and free < thresh


def peak_bytes_max() -> int:
    """Largest per-device peak seen since process start (bench persists
    this as device_hbm_peak_bytes)."""
    with _lock:
        return max(_peak.values(), default=0)


def reset_peaks() -> None:
    with _lock:
        _peak.clear()


def sum_nbytes(obj: Any) -> int:
    """Total ``nbytes`` under obj: arrays count themselves; tuples/lists/
    dicts/NamedTuples/objects with ``_fields`` or ``__dict__`` walk one
    level of their values. Metadata reads only -- never a transfer."""
    n = getattr(obj, "nbytes", None)
    if isinstance(n, int):
        return n
    if obj is None:
        return 0
    if isinstance(obj, dict):
        values = obj.values()
    elif isinstance(obj, (tuple, list)):
        values = obj
    elif hasattr(obj, "_fields"):  # NamedTuple
        values = (getattr(obj, f) for f in obj._fields)
    elif hasattr(obj, "__dict__"):
        values = vars(obj).values()
    else:
        return 0
    total = 0
    for v in values:
        n = getattr(v, "nbytes", None)
        if isinstance(n, int):
            total += n
        elif isinstance(v, (tuple, list, dict)):
            total += sum_nbytes(v)
    return total
