"""On-demand ``jax.profiler`` capture bracketing production ticks.

The jit cost table (obs/jitstats.py) attributes DISPATCH cost; the
on-device timeline -- kernel durations, HBM traffic, the gaps between
dispatches -- only exists in an XLA profiler trace. This module arms a
programmatic ``jax.profiler.start_trace``/``stop_trace`` pair around
the next N production ticks, on demand:

- ``GET /debug/profile?ticks=N`` (operator/health.py, loopback-only)
  arms a capture on the live controller; ``GET /debug/profile`` reads
  the capture state without arming anything;
- ``python -m karpenter_tpu --profile-ticks N`` arms one at startup
  (the cold path: warmup compiles land in the trace, which is exactly
  what a first-tick investigation wants).

The operator brackets every sweep with ``on_tick_start``/
``on_tick_end``; both are a lock-free int check when nothing is armed
(the no-op-when-idle contract bench measures). Traces land under
``$KARPENTER_TPU_PROFILE_DIR`` (default ``profiles/``) in per-capture
subdirectories, ready for TensorBoard/xprof (``tensorboard --logdir``).

Brownout rung 2 throttles capture exactly like trace sampling
(overload.BrownoutController._apply): an armed capture WAITS while the
ladder sheds tracing -- profiling is the one observatory layer with a
real device-side cost, so a brownout must not let a debug request deepen
the overload it is diagnosing. Armed ticks resume when the ladder
recovers; the flight recorder (obs/flight.py) is deliberately NOT
throttled the same way.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from karpenter_tpu import metrics
from karpenter_tpu.logging import get_logger

PROFILE_DIR_ENV = "KARPENTER_TPU_PROFILE_DIR"
PROFILE_DIR_DEFAULT = "profiles"
MAX_TICKS_PER_CAPTURE = 1000

PROFILER_CAPTURES = metrics.REGISTRY.counter(
    "karpenter_profiler_captures_total",
    "Completed on-demand jax.profiler captures by outcome (ok = trace "
    "written; error = start/stop raised and the capture was abandoned)",
    labels=("outcome",),
)
PROFILER_ARMED = metrics.REGISTRY.gauge(
    "karpenter_profiler_armed_ticks",
    "Production ticks still to be captured by the armed jax.profiler "
    "request (0 = idle; holds while brownout rung 2 defers the capture)",
)


class ProfilerCapture:
    """Arms and drives one capture at a time. State transitions happen
    under the lock; the actual ``jax.profiler`` start/stop calls run
    outside it (they do real work and must not serialize against a
    concurrent ``describe`` from the debug handler thread)."""

    log = get_logger("profiler")

    def __init__(self):
        self._lock = threading.Lock()
        self._armed = 0          # ticks still to capture (0 = idle)
        self._active = False     # a start_trace is live
        self._throttled = False  # brownout rung 2: defer, keep armed
        self._out_dir: Optional[str] = None
        self._capture_seq = 0
        self.captures = 0
        self.errors = 0
        self.last_trace_dir: Optional[str] = None

    # -- arming (debug endpoint / CLI) ---------------------------------------
    def request(self, ticks: int, out_dir: Optional[str] = None) -> Dict[str, Any]:
        """Arm a capture of the next `ticks` production ticks; returns
        the state document. A request while a capture is armed/active
        REPLACES the remaining tick count (the operator asked again for
        a reason) but never the live trace directory."""
        ticks = max(1, min(int(ticks), MAX_TICKS_PER_CAPTURE))
        with self._lock:
            self._armed = ticks
            if not self._active:
                self._capture_seq += 1
                base = out_dir or os.environ.get(PROFILE_DIR_ENV) or PROFILE_DIR_DEFAULT
                self._out_dir = os.path.join(base, f"capture-{self._capture_seq}")
        PROFILER_ARMED.set(float(ticks))
        self.log.info("profiler capture armed", ticks=ticks, dir=self._out_dir)
        return self.describe()

    def set_throttled(self, throttled: bool) -> None:
        """Brownout ladder rung 2 (karpenter_tpu/overload.py): while
        throttled, an armed capture waits and a live one stops at the
        current tick boundary -- same edge semantics as the tracer's
        sample throttle."""
        with self._lock:
            self._throttled = throttled

    # -- tick bracketing (Operator.tick) -------------------------------------
    def on_tick_start(self) -> None:
        with self._lock:
            if self._armed <= 0 or self._active or self._throttled:
                return
            out_dir = self._out_dir
            self._active = True
        try:
            import jax

            os.makedirs(out_dir, exist_ok=True)  # type: ignore[arg-type]
            jax.profiler.start_trace(out_dir)
        except Exception as e:  # noqa: BLE001 -- profiling must never fail a tick
            with self._lock:
                self._active = False
                self._armed = 0
            self.errors += 1
            PROFILER_ARMED.set(0.0)
            PROFILER_CAPTURES.inc(outcome="error")
            self.log.warning("profiler start failed", error=str(e)[:200])

    def on_tick_end(self) -> None:
        with self._lock:
            if not self._active:
                return
            self._armed -= 1
            finish = self._armed <= 0 or self._throttled
            if not finish:
                PROFILER_ARMED.set(float(self._armed))
                return
            self._active = False
            out_dir = self._out_dir
        try:
            import jax

            jax.profiler.stop_trace()
            self.captures += 1
            self.last_trace_dir = out_dir
            PROFILER_CAPTURES.inc(outcome="ok")
            self.log.info("profiler capture written", dir=out_dir)
        except Exception as e:  # noqa: BLE001
            self.errors += 1
            PROFILER_CAPTURES.inc(outcome="error")
            self.log.warning("profiler stop failed", error=str(e)[:200])
        PROFILER_ARMED.set(float(max(0, self._armed)))

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "armed_ticks": self._armed,
                "active": self._active,
                "throttled": self._throttled,
                "out_dir": self._out_dir,
                "captures": self.captures,
                "errors": self.errors,
                "last_trace_dir": self.last_trace_dir,
            }

    def reset(self) -> None:
        with self._lock:
            self._armed = 0
            self._active = False
            self._throttled = False
            self._out_dir = None
        # the outcome fields are only ever written from the tick thread
        # (on_tick_start/on_tick_end) and read for display -- they stay
        # outside the lock everywhere, including here
        self.captures = 0
        self.errors = 0
        self.last_trace_dir = None
        PROFILER_ARMED.set(0.0)


# process-wide capture handle (the same policy shape as tracing.TRACER):
# the health server arms it, the operator brackets ticks with it, the
# brownout ladder throttles it
PROFILER = ProfilerCapture()
