"""Abstract cloud API interfaces.

The seam the reference cuts at pkg/aws/sdk.go:1-75 (EC2API/EKSAPI/PricingAPI/
SQSAPI/SSMAPI/IAMAPI): providers depend on these interfaces only, so the
in-memory emulator (karpenter_tpu.kwok.cloud) and any real backend are
interchangeable. Methods mirror the call surface the reference providers
actually use, not whole cloud SDKs.
"""
from __future__ import annotations

import abc
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.cloud.types import (
    CapacityReservationInfo,
    CloudInstance,
    FleetRequest,
    FleetResult,
    ImageInfo,
    InstanceTypeInfo,
    LaunchTemplateInfo,
    QueueMessage,
    SecurityGroupInfo,
    SubnetInfo,
    ZoneInfo,
)


class ComputeAPI(abc.ABC):
    """EC2-equivalent surface."""

    @abc.abstractmethod
    def describe_zones(self) -> List[ZoneInfo]: ...

    @abc.abstractmethod
    def describe_instance_types(self) -> List[InstanceTypeInfo]: ...

    @abc.abstractmethod
    def describe_instance_type_offerings(self) -> Dict[str, List[str]]:
        """instance type name -> zone names where offered."""

    @abc.abstractmethod
    def describe_subnets(self) -> List[SubnetInfo]: ...

    @abc.abstractmethod
    def describe_security_groups(self) -> List[SecurityGroupInfo]: ...

    @abc.abstractmethod
    def describe_images(self) -> List[ImageInfo]: ...

    @abc.abstractmethod
    def describe_capacity_reservations(self) -> List[CapacityReservationInfo]: ...

    @abc.abstractmethod
    def create_fleet(self, request: FleetRequest) -> FleetResult: ...

    @abc.abstractmethod
    def describe_instances(self, ids: Sequence[str] = (), tag_filter: Optional[Dict[str, str]] = None) -> List[CloudInstance]: ...

    @abc.abstractmethod
    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        """Returns ids accepted for termination."""

    @abc.abstractmethod
    def create_tags(self, resource_id: str, tags: Dict[str, str]) -> None: ...

    # launch templates
    @abc.abstractmethod
    def create_launch_template(self, lt: LaunchTemplateInfo) -> LaunchTemplateInfo: ...

    @abc.abstractmethod
    def describe_launch_templates(self, names: Sequence[str] = ()) -> List[LaunchTemplateInfo]: ...

    @abc.abstractmethod
    def delete_launch_template(self, name: str) -> None: ...

    @abc.abstractmethod
    def spot_price_history(self) -> Dict[tuple, float]:
        """(instance_type, zone) -> current spot $/hr."""


class PricingAPI(abc.ABC):
    @abc.abstractmethod
    def on_demand_prices(self) -> Dict[str, float]:
        """instance type name -> $/hr."""


class QueueAPI(abc.ABC):
    """SQS-equivalent interruption feed (reference: pkg/providers/sqs)."""

    @abc.abstractmethod
    def queue_url(self) -> str: ...

    @abc.abstractmethod
    def receive(self, max_messages: int = 10) -> List[QueueMessage]: ...

    @abc.abstractmethod
    def delete(self, receipt: str) -> None: ...

    @abc.abstractmethod
    def send(self, body: str) -> None: ...


class ParamStoreAPI(abc.ABC):
    """SSM-equivalent parameter store (image alias resolution)."""

    @abc.abstractmethod
    def get_parameter(self, name: str) -> Optional[str]: ...


class IdentityAPI(abc.ABC):
    """IAM-equivalent: instance profile lifecycle for spec.role."""

    @abc.abstractmethod
    def create_instance_profile(self, name: str, tags: Dict[str, str]) -> None: ...

    @abc.abstractmethod
    def get_instance_profile(self, name: str) -> Optional[Dict]: ...

    @abc.abstractmethod
    def delete_instance_profile(self, name: str) -> None: ...

    @abc.abstractmethod
    def add_role(self, profile_name: str, role: str) -> None: ...


class ClusterAPI(abc.ABC):
    """EKS-equivalent control-plane discovery (endpoint, version)."""

    @abc.abstractmethod
    def cluster_endpoint(self) -> str: ...

    @abc.abstractmethod
    def cluster_version(self) -> str: ...

    @abc.abstractmethod
    def cluster_ca_bundle(self) -> str: ...
