"""Raw cloud-facing data types.

These are the wire-shape analogues of the aws-sdk types the reference's shim
exposes (pkg/aws/sdk.go wraps EC2/EKS/Pricing/SQS/SSM/IAM clients): instance
type info as DescribeInstanceTypes returns it, fleet create requests as
CreateFleet consumes them, etc. Providers convert these into scheduling-aware
types; nothing below this layer knows about pods or NodePools.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class ZoneInfo:
    name: str           # e.g. "us-central1-a"
    zone_id: str        # e.g. "uc1-az1"
    zone_type: str = "availability-zone"  # or "local-zone"


@dataclass
class InstanceTypeInfo:
    """Raw machine shape, as the cloud describes it (before overhead math)."""

    name: str                       # "m5.large"
    category: str                   # "m"
    family: str                     # "m5"
    generation: int                 # 5
    size: str                       # "large"
    vcpu: int
    memory_mib: int
    arch: str                       # "amd64" | "arm64"
    cpu_manufacturer: str           # "intel" | "amd" | "arm-native"
    sustained_clock_mhz: int = 3100
    hypervisor: str = "nitro"       # "nitro" | "xen" | "" (metal)
    bare_metal: bool = False
    burstable: bool = False
    network_gbps: float = 10.0
    ebs_gbps: float = 4.75
    max_network_interfaces: int = 4
    ipv4_per_interface: int = 15
    local_nvme_gib: int = 0
    gpu_name: str = ""
    gpu_manufacturer: str = ""
    gpu_count: int = 0
    gpu_memory_mib: int = 0
    accelerator_name: str = ""
    accelerator_manufacturer: str = ""
    accelerator_count: int = 0
    nic_count: int = 0              # EFA-like high-perf NICs
    encryption_in_transit: bool = True
    supported_usage_classes: Tuple[str, ...] = ("on-demand", "spot")
    zones: Tuple[str, ...] = ()     # zone names offering this type

    def eni_pod_limit(self, reserved_nics: int = 0) -> int:
        """ENI-limited pod density (reference: pkg/providers/instancetype/
        types.go:461-475: interfaces * (ipv4-1) + 2), minus interfaces
        reserved for high-perf NICs."""
        return (self.max_network_interfaces - reserved_nics) * (self.ipv4_per_interface - 1) + 2

    @property
    def max_pods_eni(self) -> int:
        return self.eni_pod_limit()


@dataclass
class SubnetInfo:
    id: str
    zone: str
    zone_id: str
    available_ip_count: int
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SecurityGroupInfo:
    id: str
    name: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class ImageInfo:
    id: str
    name: str
    arch: str                      # "amd64" | "arm64"
    family: str = "Standard"
    creation_time: float = 0.0
    deprecated: bool = False
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class CapacityReservationInfo:
    id: str
    instance_type: str
    zone: str
    total_count: int
    available_count: int
    owner_id: str = "self"
    reservation_type: str = "default"    # "default" | "capacity-block"
    state: str = "active"                # "active" | "expiring"
    start_time: float = 0.0
    end_time: Optional[float] = None
    instance_match_criteria: str = "targeted"
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class LaunchTemplateInfo:
    id: str
    name: str
    image_id: str
    security_group_ids: List[str]
    user_data: str = ""
    tags: Dict[str, str] = field(default_factory=dict)
    metadata_http_tokens: str = "required"
    block_devices: List[dict] = field(default_factory=list)
    instance_profile: str = ""
    capacity_reservation_id: Optional[str] = None
    nic_count: int = 0
    created_at: float = 0.0


@dataclass
class FleetOverride:
    """One (instance type x subnet) launch alternative inside a fleet request
    (reference: getOverrides pkg/providers/instance/instance.go:392-439)."""

    instance_type: str
    subnet_id: str
    zone: str
    priority: float = 0.0           # lower = preferred (capacity-optimized-prioritized)
    image_id: str = ""
    capacity_reservation_id: Optional[str] = None


@dataclass
class FleetRequest:
    launch_template_name: str
    capacity_type: str              # "spot" | "on-demand" | "reserved"
    overrides: List[FleetOverride]
    target_capacity: int = 1
    tags: Dict[str, str] = field(default_factory=dict)
    context: str = ""
    # idempotency client tokens, one per capacity slot (the EC2 ClientToken
    # analogue, minted by the provisioning journal): a replayed slot whose
    # token already backs a live instance returns THAT instance instead of
    # launching a second. Deliberately outside the batcher's bucket hash --
    # identical requests still merge, the merged call carries the union of
    # tokens slot-aligned (batcher/cloud.py).
    client_tokens: Tuple[Optional[str], ...] = ()


@dataclass
class FleetError:
    """Per-override launch failure (reference parses these into the ICE cache:
    pkg/providers/instance/instance.go:441-484)."""

    code: str                       # e.g. "InsufficientInstanceCapacity"
    message: str
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""


@dataclass
class CloudInstance:
    id: str
    instance_type: str
    zone: str
    subnet_id: str
    capacity_type: str
    image_id: str
    state: str = "running"          # pending|running|shutting-down|terminated|stopped
    launch_time: float = 0.0
    tags: Dict[str, str] = field(default_factory=dict)
    capacity_reservation_id: Optional[str] = None
    provider_id: str = ""
    nic_count: int = 0
    security_group_ids: List[str] = field(default_factory=list)
    # fault injection (kwok rig): a degraded-but-running instance surfaces
    # this condition type as False on its Node (repair-path exercise)
    impaired_condition: str = ""

    def __post_init__(self):
        if not self.provider_id:
            self.provider_id = f"tpu:///{self.zone}/{self.id}"


@dataclass
class FleetResult:
    instances: List[CloudInstance]
    errors: List[FleetError]


@dataclass
class QueueMessage:
    id: str
    receipt: str
    body: str                       # JSON payload
