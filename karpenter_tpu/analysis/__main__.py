"""Invariant linter CLI (`make lint`).

    python -m karpenter_tpu.analysis                  # all families, baseline-aware
    python -m karpenter_tpu.analysis --rules locks    # one family
    python -m karpenter_tpu.analysis --json           # machine-readable
    python -m karpenter_tpu.analysis --graph          # dump the lock graph
    python -m karpenter_tpu.analysis --graph --family errflow   # seam escape sets
    python -m karpenter_tpu.analysis --write-baseline # (re)seed the allowlist

Exit codes: 0 clean, 1 violations (or a stale baseline entry), 2 usage.
A stale baseline entry -- one that no longer matches any violation --
fails the run: the allowlist shrinks through deliberate edits, never rots.
"""
from __future__ import annotations

import argparse
import json
import sys

from karpenter_tpu.analysis.base import (BASELINE_PATH, apply_baseline,
                                         checkers, iter_modules,
                                         load_baseline, run_suite,
                                         write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.analysis",
        description="AST invariant checkers: determinism, lock discipline, "
                    "zero-copy wire, registry drift, jax compilation "
                    "discipline (jaxjit retrace hazards + jaxhost sync "
                    "rules), error-path soundness (errflow), and resource "
                    "lifecycle (reslife)")
    ap.add_argument("--rules", action="append", default=None,
                    metavar="FAMILY", help="run only these rule families "
                    f"(choices: {', '.join(checkers())}; repeatable)")
    ap.add_argument("--baseline", default=str(BASELINE_PATH),
                    help="allowlist file (default hack/lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report everything, including baselined exceptions")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current violations as the new baseline "
                    "(justifications from matching old entries are kept)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--graph", action="store_true",
                    help="dump a static graph and exit (default: the "
                         "lock-acquisition graph; --family errflow dumps "
                         "the per-seam exception-propagation graph)")
    ap.add_argument("--family", default="locks", metavar="FAMILY",
                    help="which graph --graph dumps: locks (default) or "
                         "errflow")
    ap.add_argument("--seam", default=None, metavar="KEY",
                    help="with --graph --family errflow: restrict the dump "
                         "to seams whose key contains KEY (debugging aid)")
    args = ap.parse_args(argv)

    if args.graph:
        if args.family == "errflow":
            from karpenter_tpu.analysis.checkers import errflow

            mods = iter_modules()
            # ONE analyzer serves both the dump and the exit code: the
            # interprocedural escape-set pass is the expensive part
            an = errflow.ExcAnalyzer(mods)
            payload = errflow.exception_graph(mods, analyzer=an)
            if args.seam:
                payload["seams"] = {k: v for k, v in payload["seams"].items()
                                    if args.seam in k}
            print(json.dumps(payload, indent=2))
            seam_violations = [v for v in errflow.check(mods, analyzer=an)
                               if v.rule.startswith("errflow/seam-")]
            return 0 if not seam_violations else 1
        if args.family != "locks":
            ap.error(f"--graph knows families 'locks' and 'errflow', "
                     f"not {args.family!r}")
        from karpenter_tpu.analysis.checkers.locks import lock_graph

        g = lock_graph(iter_modules())
        payload = {
            "locks": {lid: {"kind": ld.kind, "site": ld.site}
                      for lid, ld in sorted(g.locks.items())},
            "edges": sorted({(e.src, e.dst) for e in g.edges}),
            "cycles": g.cycles(),
        }
        print(json.dumps(payload, indent=2))
        return 0 if not payload["cycles"] else 1

    violations = run_suite(args.rules)

    import pathlib
    baseline_path = pathlib.Path(args.baseline)
    entries = [] if args.no_baseline else load_baseline(baseline_path)

    if args.write_baseline:
        old_entries = load_baseline(baseline_path)
        old = {(e["rule"], e["path"], e["line_text"]): e["justification"]
               for e in old_entries}
        # a partial (--rules) rewrite replaces only the selected families'
        # entries; everything out of scope is preserved verbatim
        kept = [e for e in old_entries
                if e["rule"].split("/")[0] not in set(args.rules)] \
            if args.rules else []
        write_baseline(violations, baseline_path, justifications=old,
                       keep=kept)
        print(f"wrote {baseline_path} ({len(violations) + len(kept)} entries)")
        return 0

    fresh, matched, stale = apply_baseline(violations, entries)
    # a partial run must not flag out-of-scope baseline entries as stale
    if args.rules:
        stale = [e for e in stale if e["rule"].split("/")[0] in args.rules]

    if args.json:
        print(json.dumps({
            "violations": [v.__dict__ for v in fresh],
            "baselined": len(matched),
            "stale_baseline": stale,
        }, indent=2))
        return 1 if fresh or stale else 0

    for v in fresh:
        print(v.render())
    for e in stale:
        print(f"{e['path']}: [baseline] stale entry for {e['rule']} "
              f"({e['line_text']!r}) matches nothing; remove it from "
              f"{baseline_path.name}", file=sys.stderr)
    if fresh or stale:
        print(f"\nlint: {len(fresh)} violation(s), {len(stale)} stale "
              f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
              f"({len(matched)} baselined exception(s) suppressed)",
              file=sys.stderr)
        return 1
    print(f"lint: clean ({len(matched)} baselined exception(s), "
          f"{len(entries)} baseline entr{'y' if len(entries) == 1 else 'ies'})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
