"""Checker plumbing: violation model, tree walking, baseline discipline.

Kept dependency-free (stdlib only): the witness import path in
tests/conftest.py runs BEFORE jax/numpy are importable-cheap, and the CLI
must work in a bare container. Checkers are imported lazily by
``run_suite`` for the same reason.
"""
from __future__ import annotations

import ast
import json
import pathlib
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
PACKAGE_ROOT = REPO_ROOT / "karpenter_tpu"
BASELINE_PATH = REPO_ROOT / "hack" / "lint_baseline.json"

# the analysis package itself is tooling, not production code: its rule
# tables mention the very constructs it hunts, and the witness's repr
# strings would trip the determinism scan
EXCLUDE_PARTS = ("analysis", "__pycache__")


@dataclass(frozen=True)
class Violation:
    """One rule firing at one site. ``line_text`` (the stripped source
    line) is part of the baseline match key so a baselined exception
    survives unrelated edits shifting line numbers -- but NOT edits to
    the excepted line itself, which must be re-vetted."""

    rule: str           # e.g. "determinism/uuid4"
    path: str           # repo-relative, forward slashes
    line: int
    message: str
    line_text: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.line_text)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Module:
    """One parsed source file handed to every AST checker."""

    path: pathlib.Path
    rel: str
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def violation(self, rule: str, node_or_line, message: str) -> Violation:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Violation(rule=rule, path=self.rel, line=int(line),
                         message=message, line_text=self.line_text(int(line)))


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None (the checkers' shared
    call-site flattener)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_modules(root: Optional[pathlib.Path] = None) -> List[Module]:
    """Parse every production source file under the package root,
    excluding tooling (see EXCLUDE_PARTS). Sorted walk: violation output
    and baseline files are diff-stable across filesystems."""
    root = root or PACKAGE_ROOT
    modules: List[Module] = []
    for path in sorted(root.rglob("*.py")):
        if any(part in EXCLUDE_PARTS for part in path.parts):
            continue
        source = path.read_text()
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError as e:  # pragma: no cover - the tree must parse
            raise SystemExit(f"lint: cannot parse {path}: {e}")
        try:
            rel = str(path.relative_to(REPO_ROOT))
        except ValueError:
            rel = str(path)
        modules.append(Module(path=path, rel=rel.replace("\\", "/"),
                              source=source, tree=tree,
                              lines=source.splitlines()))
    return modules


# -- baseline -----------------------------------------------------------------
#
# hack/lint_baseline.json is the committed allowlist: the FEW intentional
# exceptions, each vetted and justified. Matching is by (rule, path,
# stripped source line): renumbering-only edits keep an entry valid,
# touching the excepted line invalidates it (forcing a re-vet), and a
# stale entry -- one matching nothing -- fails the run so the baseline
# can only shrink through deliberate edits.


def load_baseline(path: Optional[pathlib.Path] = None) -> List[dict]:
    path = path or BASELINE_PATH
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    entries = data.get("entries", data if isinstance(data, list) else [])
    for e in entries:
        for k in ("rule", "path", "line_text", "justification"):
            if not isinstance(e.get(k), str) or not e[k]:
                raise SystemExit(
                    f"lint: baseline entry {e!r} lacks required field {k!r} "
                    "(every exception carries a justification)")
    return entries


def write_baseline(violations: Sequence[Violation],
                   path: Optional[pathlib.Path] = None,
                   justifications: Optional[Dict[Tuple[str, str, str], str]] = None,
                   keep: Optional[Sequence[dict]] = None) -> None:
    """``keep`` carries prior entries to preserve verbatim -- a partial
    (--rules) rewrite must not drop the other families' vetted exceptions."""
    path = path or BASELINE_PATH
    entries = list(keep or [])
    for v in sorted(violations, key=lambda v: (v.rule, v.path, v.line)):
        just = (justifications or {}).get(v.key(), "TODO: justify or fix")
        entries.append({"rule": v.rule, "path": v.path, "line": v.line,
                        "line_text": v.line_text, "justification": just})
    entries.sort(key=lambda e: (e["rule"], e["path"], e["line"]))
    path.write_text(json.dumps({"entries": entries}, indent=2) + "\n")


def apply_baseline(violations: Sequence[Violation], entries: Sequence[dict]
                   ) -> Tuple[List[Violation], List[dict], List[dict]]:
    """Partition into (unbaselined violations, matched entries, stale
    entries). One baseline entry absorbs every violation with its key --
    a rule firing twice on one unchanged line is one exception."""
    by_key: Dict[Tuple[str, str, str], dict] = {}
    for e in entries:
        by_key[(e["rule"], e["path"], e["line_text"])] = e
    matched: Dict[Tuple[str, str, str], dict] = {}
    fresh: List[Violation] = []
    for v in violations:
        e = by_key.get(v.key())
        if e is not None:
            matched[v.key()] = e
        else:
            fresh.append(v)
    stale = [e for k, e in by_key.items() if k not in matched]
    return fresh, list(matched.values()), stale


# -- suite --------------------------------------------------------------------

CheckerFn = Callable[[List[Module]], List[Violation]]


def checkers() -> Dict[str, CheckerFn]:
    """The rule families, imported lazily (keeps `import
    karpenter_tpu.analysis` feather-light for the witness path)."""
    from karpenter_tpu.analysis.checkers import (determinism, errflow,
                                                 jax_discipline, locks,
                                                 registry_drift, reslife,
                                                 zerocopy)

    return {
        "determinism": determinism.check,
        "locks": locks.check,
        "zerocopy": zerocopy.check,
        "registry": registry_drift.check,
        "jaxjit": jax_discipline.check_retrace,
        "jaxhost": jax_discipline.check_hostsync,
        "errflow": errflow.check,
        "reslife": reslife.check,
    }


def run_suite(families: Optional[Iterable[str]] = None,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    modules = iter_modules(root)
    table = checkers()
    selected = list(families) if families else list(table)
    unknown = [f for f in selected if f not in table]
    if unknown:
        raise SystemExit(f"lint: unknown rule families {unknown}; have {sorted(table)}")
    out: List[Violation] = []
    for fam in selected:
        out.extend(table[fam](modules))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out
