"""Runtime exception-escape witness: the dynamic half of the errflow checker.

The static pass (checkers/errflow.py) proves the RESOLVABLE call graph's
ladder contract -- but callbacks, injected functions, and duck-typed
receivers hide handler sites it cannot see, and a broad handler that is
lint-sanctioned because it logs can still be the wrong place for a
ladder-class exception to die. This module is the runtime complement: a
``sys.settrace``-based witness that watches every exception of a LADDER
class (``OperatorCrashed``, ``ShmError``, ``StaleSeqnumError``,
``CloudError`` -- matched by name anywhere in the MRO, so subclasses
count) propagate through package frames, and records the handler site
whenever one is SWALLOWED: caught in a package function that then
resumed normal execution, and garbage-collected without ever being
re-raised, converted (``raise X from e`` / implicit context), or handed
to a waiter. Every swallow counts into
``karpenter_errflow_swallowed_total{site}``; the session-end gate in
tests/conftest.py asserts that no UNSANCTIONED site swallowed one
(sanctioned = the LADDER_SEAMS functions plus the
SANCTIONED_CRASH_SWALLOWS / SANCTIONED_ESCAPE_SITES manifests, shared
verbatim with the static checker).

Mechanics (CPython 3.10 trace semantics, pinned by tests):

- ``install()`` TAPS the four ladder base classes' ``__init__``; no
  tracing runs until one is constructed (construction immediately
  precedes raising). The tap arms ``sys.settrace`` on the constructing
  thread and back-fills ``f_trace`` onto the live repo frames; the
  thread disarms itself after a short fuse of call events with nothing
  in flight -- the witness's standing cost is ZERO, and each ladder
  exception pays a sub-millisecond tracing window. While armed, the
  local handler is returned only for frames under the repo (package +
  tests; the analysis package itself is skipped), and
  ``frame.f_trace_lines = False`` keeps it down to
  ``exception``/``return`` events.
- An ``exception`` event for a ladder-class instance opens (or re-binds)
  a RECORD keyed by the exception's identity: state ``propagating`` in
  that frame. A later event for the SAME frame decides its fate:
  a ``return`` whose line equals the exception line, lands on a
  ``raise`` statement, or inside a ``finally`` block is an UNWIND (the
  record keeps propagating -- the caller's events or GC resolve it);
  any other same-frame activity (a different line's return, a nested
  call, another exception) means the frame CAUGHT it -- state ``held``
  at that (file, function) site.
- A ``held`` record is not yet a swallow: a later ``raise`` of the same
  instance (an exception event anywhere, any thread -- the batcher's
  future fan-out re-raises in the waiter) or of an exception carrying
  it in its ``__cause__``/``__context__`` chain resolves it ESCAPED.
  Garbage collection is the verdict: a weakref callback on the instance
  turns a still-held record into a SWALLOW at its site, and drops a
  still-propagating one (it left traced code -- a test caught it).
  Records held by TEST frames resolve silently: pytest.raises is not a
  package swallow.
- ``finally``-block returns are invisible to this witness (the static
  ``errflow/return-in-finally`` rule owns that spelling), and Python
  scalar C-level handling is out of reach -- same division of labor as
  the jax witness vs the jaxhost rules.

Controls mirror the lock witness: installed session-wide by
tests/conftest.py, ``KARPENTER_TPU_ERRFLOW_WITNESS=0`` disables,
``=strict`` raises ``EscapeWitnessViolation`` from ``flush()`` (never
from inside a trace callback, where CPython would silently disarm
tracing and land the violation in an unrelated frame).
The chaos / crash-chaos / overload make targets keep it on while fault
injection widens the schedule space -- an armed drill is exactly when a
wrong handler meets a ladder exception.
"""
from __future__ import annotations

import ast
import sys
import threading
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from karpenter_tpu.analysis.base import PACKAGE_ROOT, REPO_ROOT

_PKG_PREFIX = str(PACKAGE_ROOT) + "/"
_REPO_PREFIX = str(REPO_ROOT) + "/"
_SKIP_PREFIX = str(PACKAGE_ROOT / "analysis") + "/"

# class names that make an exception LADDER-CLASS when any of them
# appears in the MRO (subclasses count; ConnectionError/OSError stay
# out -- generic transport errors are the static checker's domain, the
# witness watches the TYPED rungs and the crash)
LADDER_NAMES = frozenset({
    "OperatorCrashed", "ShmError", "StaleSeqnumError", "CloudError",
})

_SWALLOWED = None


def _swallowed_metric():
    """Lazy like the lock witness's: importing this module must not
    import karpenter_tpu.metrics (conftest imports witnesses before
    install(), and an eager metrics import would allocate the Registry
    locks unwitnessed). metrics_gen reaches it via _register_metrics."""
    global _SWALLOWED
    if _SWALLOWED is None:
        from karpenter_tpu import metrics

        _SWALLOWED = metrics.REGISTRY.counter(
            "karpenter_errflow_swallowed_total",
            "Ladder-class exceptions (OperatorCrashed/ShmError/"
            "StaleSeqnumError/CloudError subclasses) observed by the "
            "runtime escape witness being swallowed, by handler site "
            "(file:function). The session-end gate asserts no "
            "UNSANCTIONED site swallowed one during tier-1 or the "
            "chaos/overload soaks.",
            labels=("site",),
        )
    return _SWALLOWED


_register_metrics = _swallowed_metric

if "karpenter_tpu.metrics" in sys.modules:
    _swallowed_metric()


class EscapeWitnessViolation(RuntimeError):
    """Raised in strict mode at the GC point of an unsanctioned swallow."""


@dataclass
class Swallow:
    site: str        # "rel/path.py:function"
    exc_type: str
    message: str
    raised_line: int  # line in the handler's frame where the exc surfaced
    sanctioned: bool

    def render(self) -> str:
        tag = "sanctioned" if self.sanctioned else "UNSANCTIONED"
        return (f"[{tag}] {self.site} swallowed {self.exc_type} "
                f"(surfaced at line {self.raised_line}): {self.message}")


@dataclass
class _Record:
    exc_id: int
    exc_type: str
    message: str
    state: str                    # "propagating" | "held"
    frame_id: Optional[int]       # binding frame while it is alive
    file: str = ""
    func: str = ""
    exc_line: int = 0             # f_lineno of the last exception event
    ref: Any = None               # weakref to the exception


@dataclass
class _State:
    guard: Any = field(default_factory=threading.Lock)
    records: Dict[int, _Record] = field(default_factory=dict)
    swallows: List[Swallow] = field(default_factory=list)
    strict: bool = False
    installed: bool = False
    # ladder classes whose __init__ carries the arming tap -> original
    patched: Dict[type, Any] = field(default_factory=dict)
    # per-file (raise-statement lines, finally-block lines) for the
    # unwind-vs-handled judgment
    lines_cache: Dict[str, Tuple[Set[int], Set[int]]] = field(default_factory=dict)
    ladder_memo: Dict[type, bool] = field(default_factory=dict)
    sanctioned: Optional[Set[Tuple[str, str]]] = None


_state = _State()
_gc_queue: "deque[int]" = deque()
# frame id -> record, for PROPAGATING records only: the per-call and
# per-return fast paths key off this tiny transient index (reads are
# lock-free under the GIL; an exception is in flight for microseconds,
# while HELD records -- which can live as long as the object they were
# recorded on -- never burden the hot path)
_by_frame: Dict[int, "_Record"] = {}


def _is_ladder(tp: type) -> bool:
    hit = _state.ladder_memo.get(tp)
    if hit is None:
        try:
            hit = any(c.__name__ in LADDER_NAMES for c in tp.__mro__)
        except Exception:  # noqa: BLE001 -- exotic metaclasses stay out
            hit = False
        _state.ladder_memo[tp] = hit
    return hit


def _file_lines(filename: str) -> Tuple[Set[int], Set[int]]:
    hit = _state.lines_cache.get(filename)
    if hit is not None:
        return hit
    raise_lines: Set[int] = set()
    finally_lines: Set[int] = set()
    try:
        with open(filename) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Raise):
                raise_lines.add(node.lineno)
            elif isinstance(node, ast.Try) and node.finalbody:
                lo = node.finalbody[0].lineno
                hi = max((getattr(n, "end_lineno", lo) or lo)
                         for n in node.finalbody)
                finally_lines.update(range(lo, hi + 1))
    except (OSError, SyntaxError, ValueError):
        pass
    _state.lines_cache[filename] = (raise_lines, finally_lines)
    return raise_lines, finally_lines


def _sanctioned_sites() -> Set[Tuple[str, str]]:
    """(rel, function) sites allowed to absorb a ladder-class exception:
    the LADDER_SEAMS functions themselves plus the two sanctioned-swallow
    manifests -- imported lazily so module import stays feather-light."""
    if _state.sanctioned is None:
        from karpenter_tpu.analysis.checkers import errflow

        sites: Set[Tuple[str, str]] = set()
        for seam in errflow.LADDER_SEAMS:
            sites.add((seam.rel, seam.func))
        sites.update(errflow.SANCTIONED_CRASH_SWALLOWS)
        sites.update(errflow.SANCTIONED_ESCAPE_SITES)
        _state.sanctioned = sites
    return _state.sanctioned


def _rel(filename: str) -> str:
    if filename.startswith(_REPO_PREFIX):
        return filename[len(_REPO_PREFIX):]
    return filename


# -- record resolution --------------------------------------------------------


def _resolve_held(rec: _Record, *, swallowed: bool) -> Optional[Swallow]:
    """Caller holds the guard. Returns the Swallow to report (metric is
    incremented OUTSIDE the guard by the caller), or None."""
    _state.records.pop(rec.exc_id, None)
    if rec.frame_id is not None:
        _by_frame.pop(rec.frame_id, None)
        rec.frame_id = None
    if not swallowed:
        return None
    rel = _rel(rec.file)
    if not rel.startswith("karpenter_tpu/"):
        return None  # a test (or harness) absorbed it: not a package swallow
    site_key = (rel, rec.func)
    sw = Swallow(
        site=f"{rel}:{rec.func}",
        exc_type=rec.exc_type,
        message=rec.message,
        raised_line=rec.exc_line,
        sanctioned=site_key in _sanctioned_sites(),
    )
    _state.swallows.append(sw)
    return sw


def _on_gc(exc_id: int) -> None:
    """Weakref callback: the exception was garbage-collected. GC can run
    at ANY allocation -- including while this thread holds the guard --
    so the callback only enqueues (deque.append is atomic, lock-free);
    the verdict happens in _drain_gc at the next trace event."""
    _gc_queue.append(exc_id)


def _drain_gc(strict_ok: bool = False) -> None:
    """Judge queued GC verdicts: a still-held record is a swallow, a
    still-propagating one left traced code (escaped). Runs on a real
    thread at trace events (strict_ok=False: raising from a trace
    callback would make CPython silently disarm tracing and land the
    violation in whatever unrelated frame is executing) and from
    flush()/swallows() (strict_ok=True: the strict raise happens here,
    AFTER every hit's metric increment, so the counter never diverges
    from the report)."""
    hits: List[Swallow] = []
    while _gc_queue:
        try:
            exc_id = _gc_queue.popleft()
        except IndexError:
            break
        with _state.guard:
            rec = _state.records.get(exc_id)
            if rec is None:
                continue
            sw = _resolve_held(rec, swallowed=(rec.state == "held"))
        if sw is not None:
            hits.append(sw)
    for sw in hits:
        _swallowed_metric().inc(site=sw.site)
    if strict_ok and _state.strict:
        bad = [sw for sw in hits if not sw.sanctioned]
        if bad:
            raise EscapeWitnessViolation(
                "\n".join(sw.render() for sw in bad))


def _mark_held(rec: _Record) -> None:
    """Caller holds the guard: the binding frame resumed execution, so
    it caught the exception. Held records leave the per-frame fast-path
    index -- only GC, a re-raise, or a conversion resolves them now."""
    rec.state = "held"
    if rec.frame_id is not None:
        _by_frame.pop(rec.frame_id, None)


def _chain_ids(exc: BaseException) -> Set[int]:
    out: Set[int] = set()
    seen = 0
    while exc is not None and seen < 8:
        out.add(id(exc))
        exc = exc.__cause__ if exc.__cause__ is not None else exc.__context__
        seen += 1
    return out


# -- trace callbacks ----------------------------------------------------------


def _on_exception(frame, exc: BaseException) -> None:
    exc_id = id(exc)
    fid = id(frame)
    with _state.guard:
        # a new exception in a frame where a DIFFERENT record was
        # propagating means that frame caught the old one first
        prior = _by_frame.get(fid)
        if prior is not None and prior.exc_id != exc_id:
            _mark_held(prior)
        # conversion / re-raise resolution through the cause chain
        chain = _chain_ids(exc)
        chain.discard(exc_id)
        for cid in chain:
            crec = _state.records.get(cid)
            if crec is not None:
                _resolve_held(crec, swallowed=False)  # escaped as a cause
        rec = _state.records.get(exc_id)
        if rec is not None:
            # the SAME exception surfacing again: re-raised or still
            # unwinding -- either way it is propagating in THIS frame now
            if rec.frame_id is not None:
                _by_frame.pop(rec.frame_id, None)
            rec.state = "propagating"
            rec.frame_id = fid
            rec.file = frame.f_code.co_filename
            rec.func = frame.f_code.co_name
            rec.exc_line = frame.f_lineno
            _by_frame[fid] = rec
            return
        rec = _Record(
            exc_id=exc_id, exc_type=type(exc).__name__,
            message=str(exc)[:200], state="propagating",
            frame_id=fid, file=frame.f_code.co_filename,
            func=frame.f_code.co_name, exc_line=frame.f_lineno,
        )
        try:
            rec.ref = weakref.ref(exc, lambda _r, i=exc_id: _on_gc(i))
        except TypeError:
            return  # not weakref-able: cannot judge its lifetime
        _state.records[exc_id] = rec
        _by_frame[fid] = rec


def _on_return(frame) -> None:
    fid = id(frame)
    with _state.guard:
        rec = _by_frame.get(fid)
        if rec is None:
            return
        _by_frame.pop(fid, None)
        rec.frame_id = None
        raise_lines, finally_lines = _file_lines(frame.f_code.co_filename)
        line = frame.f_lineno
        if line == rec.exc_line or line in raise_lines \
                or line in finally_lines:
            # unwinding through this frame: the caller's events (or GC)
            # decide; the frame binding dies with it
            return
        # the frame caught it and completed normally
        rec.state = "held"


def _on_call(frame) -> None:
    """A nested call while a record is propagating in the CALLER frame
    means the caller's handler is running: the exception was caught.
    EXCEPT when the caller is unwinding: a ``finally`` block's cleanup
    calls, a ``raise``-statement's constructor, and a ``with`` block's
    Python ``__exit__`` all run mid-unwind -- judged by the caller's
    current line (finally span / raise line / still on the exception
    line), the same tables _on_return uses."""
    caller = frame.f_back
    if caller is None:
        return
    rec = _by_frame.get(id(caller))
    if rec is None:
        return
    with _state.guard:
        rec = _by_frame.get(id(caller))
        if rec is None or rec.state != "propagating":
            return
        line = caller.f_lineno
        if line == rec.exc_line:
            return  # still on the raising line: a with-exit, not a handler
        raise_lines, finally_lines = _file_lines(caller.f_code.co_filename)
        if line in raise_lines or line in finally_lines:
            return  # unwind-path cleanup, not handler code
        _mark_held(rec)


# -- the arming tap -----------------------------------------------------------
#
# Tracing a 5-minute suite wholesale costs ~2.4x wall clock (a Python
# callback per interpreter-level call). The witness instead ARMS
# per-thread tracing only while a ladder-class exception is plausibly in
# flight: the four ladder base classes' __init__ is tapped, and
# constructing one (which immediately precedes raising one) enables
# sys.settrace on the constructing thread AND back-fills f_trace onto
# the live repo frames (frames predating settrace get no call event).
# Tracing disarms itself after _FUSE call events with no record in
# flight -- the witness's standing cost is zero, and each ladder
# exception pays a sub-millisecond tracing window. The known blind spot:
# a HELD instance re-raised on another thread long after the fuse burned
# (the batcher future fan-out) resolves at GC as a swallow -- those
# designed hand-off sites are exactly what SANCTIONED_ESCAPE_SITES
# carries.

_FUSE = 512
_tls = threading.local()


def _local_trace(frame, event, arg):
    if event == "exception":
        if isinstance(arg[1], BaseException) and _is_ladder(type(arg[1])):
            _tls.fuse = _FUSE
            _on_exception(frame, arg[1])
    elif event == "return" and _by_frame:
        _on_return(frame)
    if _gc_queue:
        _drain_gc()
    return _local_trace


def _global_trace(frame, event, arg):
    if event != "call":
        return None
    if _by_frame:
        _on_call(frame)
        _tls.fuse = _FUSE
    else:
        fuse = getattr(_tls, "fuse", 0) - 1
        _tls.fuse = fuse
        if fuse <= 0:
            sys.settrace(None)  # this thread disarms itself
            return None
    if _gc_queue:
        _drain_gc()
    fn = frame.f_code.co_filename
    if fn.startswith(_SKIP_PREFIX) or not fn.startswith(_REPO_PREFIX):
        return None
    frame.f_trace_lines = False
    return _local_trace


def _arm_thread() -> None:
    """Enable tracing on the CURRENT thread and back-fill f_trace onto
    the live repo frames (they predate settrace, so call events alone
    would never reach them). A foreign tracer (debugger, coverage) wins:
    the witness stays dark rather than fighting over sys.settrace."""
    _tls.fuse = _FUSE
    cur = sys.gettrace()
    if cur is not None and cur is not _global_trace:
        return
    if cur is None:
        sys.settrace(_global_trace)
    f = sys._getframe(2)
    depth = 0
    while f is not None and depth < 48:
        fn = f.f_code.co_filename
        if fn.startswith(_REPO_PREFIX) and not fn.startswith(_SKIP_PREFIX):
            if f.f_trace is None:
                f.f_trace = _local_trace
                f.f_trace_lines = False
        f = f.f_back
        depth += 1


def _on_construct(exc: BaseException) -> None:
    if _state.installed:
        _arm_thread()


def _make_tap(cls: type):
    orig = cls.__init__

    def __init__(self, *args, **kwargs):  # noqa: A002
        orig(self, *args, **kwargs)
        _on_construct(self)

    __init__._errwitness_tap = True  # type: ignore[attr-defined]
    __init__.__wrapped__ = orig      # type: ignore[attr-defined]
    return __init__, orig


# (module path, class name) of the ladder BASE classes; subclasses
# inherit the tapped __init__ unless they override without super() --
# the CloudError taxonomy and the Shm/Stale families all chain up
_TAP_CLASSES = (
    ("karpenter_tpu.failpoints", "OperatorCrashed"),
    ("karpenter_tpu.solver.shm", "ShmError"),
    ("karpenter_tpu.solver.rpc", "StaleSeqnumError"),
    ("karpenter_tpu.errors.errors", "CloudError"),
)


# -- public api ---------------------------------------------------------------


def install(strict: bool = False) -> None:
    """Tap the ladder exception classes (importing their modules -- call
    AFTER the lock witness is installed so their module-level locks stay
    witnessed). No tracing is active until a ladder-class exception is
    constructed; threads disarm themselves when the flight ends."""
    import importlib

    _state.strict = strict
    if _state.installed:
        return
    for modpath, clsname in _TAP_CLASSES:
        mod = importlib.import_module(modpath)
        cls = getattr(mod, clsname)
        if cls in _state.patched:
            continue
        tapped, orig = _make_tap(cls)
        cls.__init__ = tapped
        _state.patched[cls] = orig
    _state.installed = True


def uninstall() -> None:
    if not _state.installed:
        return
    _state.installed = False
    for cls, orig in _state.patched.items():
        cls.__init__ = orig
    _state.patched.clear()
    if sys.gettrace() is _global_trace:
        sys.settrace(None)


def installed() -> bool:
    return _state.installed


def reset() -> None:
    """Drop accumulated records/swallows (a fresh witness epoch; the
    installed trace stays)."""
    _gc_queue.clear()
    with _state.guard:
        _by_frame.clear()
        _state.records.clear()
        _state.swallows.clear()


def flush() -> None:
    """Force pending verdicts: collect garbage so dropped exceptions
    reach their weakref callbacks, then drain the verdict queue -- the
    session gate calls this before judging. In strict mode, this is
    where an unsanctioned swallow raises EscapeWitnessViolation."""
    import gc

    gc.collect()
    _drain_gc(strict_ok=True)


def swallows(unsanctioned_only: bool = False) -> List[Swallow]:
    _drain_gc(strict_ok=False)
    with _state.guard:
        out = list(_state.swallows)
    if unsanctioned_only:
        out = [s for s in out if not s.sanctioned]
    return out


def pending_count() -> int:
    with _state.guard:
        return len(_state.records)


def report() -> str:
    sws = swallows()
    bad = [s for s in sws if not s.sanctioned]
    head = (f"escape witness: {len(sws)} ladder-class swallow(s), "
            f"{len(bad)} unsanctioned, {pending_count()} pending record(s)")
    if not sws:
        return head
    return "\n".join([head] + [s.render() for s in sws])
