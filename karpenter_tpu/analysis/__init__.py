"""Invariant linter suite: the repo's load-bearing contracts, machine-checked.

The guarantees this codebase leans on -- byte-deterministic replay (the
sim subsystem's golden-digest discipline), deadlock-free threading across
the pipelined solve / breaker / shm ring / elector, the zero-copy wire
path, and generated-doc registries that cannot drift -- were enforced
only at runtime until this package. Runtime tests catch a violation when
a schedule happens to exercise it; the `uuid4` NodeClaim-name
nondeterminism (PR 4) and the scrape-vs-observe histogram race (PR 2)
both shipped before a test met them. These checkers walk the package AST
and fail `make lint` the moment a violation is WRITTEN:

- ``determinism``   -- bare ``uuid.uuid4()`` / ``random.*()`` /
  ``time.time()`` / ``datetime.now()`` calls and iteration-order hazards
  outside the seeding.py-derived streams and the named clock seams
  (checkers/determinism.py).
- ``locks``         -- the static lock-acquisition graph across every
  ``threading.Lock/RLock``-holding class: lock-order cycles are rejected,
  and attributes written both under and outside their class's lock are
  flagged (checkers/locks.py).
- ``zerocopy``      -- copying constructs (``.tobytes()``, ``bytes(view)``,
  ``b"".join``, ...) on the rpc.py/shm.py framing hot path: the runtime
  ``payload_copies == 0`` assertion, made static (checkers/zerocopy.py).
- ``registry``      -- every failpoint site, metric family, and RPC
  feature flag must appear in its docs table (checkers/registry_drift.py).
- ``jaxjit``        -- retrace hazards at jax.jit decoration sites:
  static args outside the bounded-cardinality bucketing manifest,
  closures over mutable state, Python branching on traced values, and
  weak-dtype array creation (checkers/jax_discipline.py).
- ``jaxhost``       -- host-sync discipline over the per-tick encode ->
  dispatch -> decode manifest: ``.item()``, scalar casts of live device
  values, unsanctioned ``np.asarray``/``device_get``, and hot-path
  barriers (checkers/jax_discipline.py).
- ``errflow``       -- interprocedural exception-flow soundness over the
  ``LADDER_SEAMS`` manifest: every wire failure provably degrades
  through the shm->tcp->breaker->host ladder (escape sets checked
  against per-seam must_handle/may_raise contracts), no handler can
  swallow ``OperatorCrashed`` outside the sanctioned run-loop drivers,
  broad ``except Exception`` must re-raise/convert/count/log, and no
  ``return`` hides in a ``finally`` (checkers/errflow.py).
- ``reslife``       -- resource lifecycle: sockets, shm segments/mmaps,
  fds, files, tempfiles, and threads are released on every path,
  error edges included -- the static analogue of ``cleanup_stale``
  (checkers/reslife.py).

Intentional exceptions live in ``hack/lint_baseline.json`` -- each entry
carries file:line, the offending source line, and a justification; the
suite fails if the baseline grows stale. Run it:

    python -m karpenter_tpu.analysis            # == make lint
    python -m karpenter_tpu.analysis --json     # machine-readable
    python -m karpenter_tpu.analysis --write-baseline   # (re)seed

The static lock pass is paired with a RUNTIME lock-order witness
(witness.py): a debug wrapper around ``threading.Lock/RLock`` that
records acquisition order per thread and reports any inversion of an
observed edge -- the Python race detector for interleavings the chaos
schedules cannot force. Tier-1 and the chaos soaks run under it and
assert zero inversions (tests/conftest.py). The jax pass is paired the
same way with a runtime retrace/transfer witness (jax_witness.py):
compile events and unsanctioned device->host conversions inside
declared-warm hot sections are recorded per call site, asserted zero by
tier-1's warm-delta gate and the bench warm stage. The errflow pass is
paired with a runtime exception-escape witness (errwitness.py): the
ladder exception classes are construction-tapped to arm per-thread
tracing only while one is in flight, and every ladder-class exception
SWALLOWED by a package handler counts into
``karpenter_errflow_swallowed_total{site}`` -- tier-1 and the
chaos/overload soaks assert no unsanctioned site swallowed one.
"""
from karpenter_tpu.analysis.base import (  # noqa: F401
    Violation,
    load_baseline,
    run_suite,
    write_baseline,
)
