"""XLA compilation-discipline checker: the jit contract, static.

The whole performance story (ROADMAP "Where the time goes now") rests on
the jitted solve path never silently recompiling and never syncing to
host mid-tick: ~8 ms of device exec against a warm tick that must cost
O(churn). Nothing enforced that contract -- one unbounded static-arg
value or one stray ``.item()`` turns the 8 ms solve into a multi-second
XLA compile stall (exactly round 2's p99 tail) that no decision-level
test can see. This checker rejects the hazard the moment it is written;
``analysis/jax_witness.py`` is the runtime complement (compile events
and host transfers counted per call site after warmup).

Two rule families over ``solver/`` and ``parallel/``:

``jaxjit/*`` -- retrace hazards at ``jax.jit`` decoration sites and
inside jitted bodies (module-local helpers resolved transitively):

- ``jaxjit/unbounded-static``: every ``static_argnames`` entry must be
  declared in ``STATIC_ARG_BUCKETS``, the bounded-cardinality bucketing
  manifest. A static arg whose value set is not provably finite compiles
  a fresh program per distinct value -- the manifest records WHY each
  name is bounded (padding buckets, catalog geometry, a closed enum) and
  makes a new static axis a reviewed decision instead of a drive-by.
  Non-literal ``static_argnames`` and any use of ``static_argnums``
  (positional indices drift silently under refactors) also fire here.
- ``jaxjit/closure-state``: a jitted body reading ``self.X`` or a
  module-level MUTABLE name (lowercase by convention; ALL_CAPS constants
  are exempt) closes over state jax hashes by identity at trace time --
  a rebind never retriggers tracing (stale constant baked into the
  program) or, for arrays, retraces per object. Thread state through
  arguments instead.
- ``jaxjit/traced-branch``: ``if``/``while``/ternary/``for`` over a
  TRACED value inside a jitted body -- a ConcretizationError at best, a
  silent per-value recompile via an intermediate ``static_argnames``
  "fix" at worst. Shape/dtype reads (``x.shape[0]`` and friends) are
  trace-time Python ints and do not taint.
- ``jaxjit/weak-dtype``: array creation (``jnp.arange``/``zeros``/
  ``full``/...) without an explicit dtype inside a jitted body leaks
  weak types; a weak-vs-committed dtype mismatch between two call paths
  is a signature change and a retrace (and on TPU a silent f32/bf16
  surprise). ``*_like`` constructors inherit and are exempt.

``jaxhost/*`` -- host-sync discipline over ``DEVICE_HOT_PATH``, the
explicit manifest of the per-tick encode -> dispatch -> decode functions
(the zero-copy ``HOT_PATH`` pattern). Within manifest functions:

- ``jaxhost/item``: ``.item()`` synchronously round-trips device->host.
- ``jaxhost/scalar-cast``: ``float()``/``int()`` on a value produced by
  a jit entry point (local dataflow; a fetch through ``np.asarray`` /
  ``jax.device_get`` clears the taint) blocks on device compute.
- ``jaxhost/np-on-device``: ``np.asarray``/``np.array``/``np.copy`` or
  ``jax.device_get`` on a bare name/attribute forces a synchronous
  device->host copy. The SANCTIONED fetch sites -- the one designed
  barrier per path, prefetched via ``copy_to_host_async`` -- are the
  ``SANCTIONED_FETCH`` manifest, shared verbatim with the runtime
  witness so both halves bless exactly the same seams.
- ``jaxhost/block-until-ready``: an explicit barrier in the hot path
  serializes dispatch against the device; the pipelined tick exists to
  avoid exactly that wait (trace-mode attribution barriers are vetted
  baseline entries).

Stdlib-only by design: `make lint` and the CI lint job never import jax.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from karpenter_tpu.analysis.base import Module, Violation
from karpenter_tpu.analysis.base import dotted as _dotted

# -- the bucketing manifest ---------------------------------------------------
#
# Every static_argnames entry in the tree must appear here with the
# argument for WHY its value set is bounded (so the jit cache stays a
# handful of programs per geometry, not one per tick). Adding a static
# axis = adding an entry = explaining the bound in review.

STATIC_ARG_BUCKETS: Dict[str, str] = {
    "g_max": "open-group slot budget: fixed per solver instance "
             "(TPUSolver(g_max=...)); bench/prod use one value per tier",
    "nnz_max": "sparse-take budget: ffd.nnz_budget(c_pad, g_max), a pure "
               "function of the padded class bucket and g_max -- one value "
               "per (c_pad bucket, g_max) pair",
    "word_offsets": "packed-bitset geometry: cumsum of the catalog's "
                    "requirement-dimension word counts; one value per "
                    "catalog encoding (staged once per seqnum)",
    "words": "packed-bitset geometry: per-dimension word counts, fixed by "
             "the catalog encoding alongside word_offsets",
    "objective": "closed enum {'price', 'fit'}: two programs total",
    "iters": "convex-tier iteration budget: fixed per process "
             "(relax.DEFAULT_ITERS; the repack oracle's budget runs "
             "host-side) -- one program per budget actually used",
    "od_col": "on-demand column of the closed capacity-type vocabulary "
              "(encode.CAPTYPE_INDEX): one value per process",
}

# rel-path prefixes the jaxjit rules scan (jit entry points live here;
# the control plane holds no jitted code by design)
JIT_SCAN_PREFIXES: Tuple[str, ...] = (
    "karpenter_tpu/solver/",
    "karpenter_tpu/parallel/",
    "karpenter_tpu/fleet/",
)

# module -> jit-decorated function names (the decoration-site registry).
# The runtime witness resolves these for per-entry compilation-cache
# attribution, and tests/test_analysis.py asserts the checker's
# discovered decoration sites match -- a new jit entry point must be
# ADDED here to get witness coverage.
JIT_ENTRY_FUNCTIONS: Dict[str, Tuple[str, ...]] = {
    "karpenter_tpu.solver.ffd": (
        "ffd_solve", "select_offerings", "ffd_solve_packed",
        "ffd_solve_compact", "ffd_solve_fused",
    ),
    "karpenter_tpu.solver.disrupt.kernel": ("disrupt_repack", "disrupt_replace"),
    "karpenter_tpu.solver.kernels.ffd_pallas": ("ffd_solve_fused_pallas",),
    "karpenter_tpu.solver.kernels.disrupt_pallas": ("disrupt_repack_pallas",),
    # solution-quality observatory: the fractional price bound runs on
    # every warm tick right behind the solve (observe-only)
    "karpenter_tpu.solver.bound": ("fractional_price_bound",),
    # convex global-solve tier: the LP relaxation dispatches behind the
    # fused FFD solve on every convex-tier tick
    "karpenter_tpu.solver.convex.relax": ("convex_relax",),
}

# every Pallas kernel entry must keep a registered XLA twin: the
# dispatch fallback rung (service._dispatch_fused / _dispatch_disrupt_
# repack) pins the process to the twin on any lowering or runtime
# failure, so a kernel without one would strand the degrade ladder.
# Maps (kernel rel, jit entry) -> (twin rel, twin function); the
# jaxjit/pallas-twin rule verifies both sides exist by AST.
PALLAS_TWINS: Dict[Tuple[str, str], Tuple[str, str]] = {
    ("karpenter_tpu/solver/kernels/ffd_pallas.py", "ffd_solve_fused_pallas"):
        ("karpenter_tpu/solver/ffd.py", "ffd_solve_fused"),
    ("karpenter_tpu/solver/kernels/disrupt_pallas.py", "disrupt_repack_pallas"):
        ("karpenter_tpu/solver/disrupt/kernel.py", "disrupt_repack"),
}

# modules that build jit wrappers dynamically (jax.jit(...) call sites,
# cached per mesh/statics); the witness polls their caches instead
DYNAMIC_JIT_MODULES: Tuple[str, ...] = (
    "karpenter_tpu.parallel.mesh",
    "karpenter_tpu.fleet.shard",
)

# -- the device hot-path manifest ---------------------------------------------
#
# Same shape as zerocopy.HOT_PATH: rel -> (module functions, {class:
# methods}). These are the per-tick encode -> dispatch -> decode
# functions; a host sync inside any of them stalls the tick on device
# compute (or worse, serializes the pipelined begin/finish overlap).

DEVICE_HOT_PATH: Dict[str, Tuple[Tuple[str, ...], Dict[str, Tuple[str, ...]]]] = {
    "karpenter_tpu/solver/encode.py": (
        ("group_pods", "encode_classes"),
        {},
    ),
    "karpenter_tpu/solver/spread.py": (
        ("split_zone_spread",),
        {},
    ),
    "karpenter_tpu/solver/ffd.py": (
        ("make_inputs_staged", "solve_dense_tuple", "expand_fused",
         "expand_compact"),
        {},
    ),
    # solution-quality observatory: the bound is dispatched per warm tick
    # inside solve_finish (overlapping decode), fetched through the one
    # SANCTIONED barrier below -- hot-path by construction even though
    # its output is observe-only
    "karpenter_tpu/solver/bound.py": (
        ("fractional_price_bound", "fractional_price_bound_impl",
         "fetch_bound"),
        {},
    ),
    # convex tier: relax dispatch + fetch run per convex-tier tick right
    # behind the fused solve; its one designed barrier is fetch_relax
    # (SANCTIONED below) -- rounding/tier/repack are host-side numpy and
    # touch no device values
    "karpenter_tpu/solver/convex/relax.py": (
        ("convex_relax", "convex_relax_impl", "fetch_relax"),
        {},
    ),
    "karpenter_tpu/solver/service.py": (
        (),
        {"TPUSolver": ("solve_begin", "solve_finish", "_finish_remote",
                       "_solve_local_dense", "_pack_existing",
                       "_dispatch_fused", "_dispatch_disrupt_repack",
                       "_dispatch_bound", "_begin_quality",
                       "_dispatch_convex", "_finish_convex")},
    ),
    # Pallas kernel entries: the wrappers run per tick when selected
    # (TPUSolver(kernels="pallas")), so their prologue/epilogue code is
    # hot-path like the twins' -- no host syncs around the pallas_call
    "karpenter_tpu/solver/kernels/ffd_pallas.py": (
        ("ffd_solve_fused_pallas",),
        {},
    ),
    "karpenter_tpu/solver/kernels/disrupt_pallas.py": (
        ("disrupt_repack_pallas",),
        {},
    ),
    "karpenter_tpu/solver/rpc.py": (
        (),
        {
            "SolverServer": ("_op_solve_delta", "_staged_inputs",
                             "_op_solve", "_op_solve_compact",
                             "_op_solve_disrupt", "_op_solve_convex"),
            "SolverClient": ("begin_solve_compact", "finish_solve_compact"),
        },
    ),
    "karpenter_tpu/solver/disrupt/engine.py": (
        (),
        {"DisruptEngine": ("evaluate", "_dispatch_local", "_evaluate_local",
                           "_evaluate_wire", "_assemble")},
    ),
    "karpenter_tpu/parallel/mesh.py": (
        ("sharded_solve", "sharded_repack", "_fetch_multiprocess"),
        {},
    ),
    # fleet subsystem: the mesh engine's dispatch methods run on every
    # tick of a mesh-configured solver/sidecar -- hot-path by
    # construction; its one designed barrier is `fetch` (SANCTIONED
    # below; outputs are replicated on device by the in-jit all-gather,
    # so the fetch is a local read)
    "karpenter_tpu/fleet/shard.py": (
        (),
        {"MeshSolveEngine": ("solve_fused", "solve_compact", "solve_dense",
                             "price_bound", "repack", "replace", "fetch",
                             "_put_inputs")},
    ),
    # device performance observatory (karpenter_tpu/obs/): these run on
    # EVERY tick, so they are hot-path by construction and the jaxhost
    # rules must machine-check they stay sync-free -- their designed
    # runtime-introspection seams (device.memory_stats, the programmatic
    # jax.profiler bracket) are the SANCTIONED entries below
    "karpenter_tpu/obs/hbm.py": (
        ("poll", "sum_nbytes"),
        {},
    ),
    "karpenter_tpu/obs/flight.py": (
        ("stage_summary",),
        {"FlightDataRecorder": ("record",)},
    ),
    "karpenter_tpu/obs/profiler.py": (
        (),
        {"ProfilerCapture": ("on_tick_start", "on_tick_end")},
    ),
}

# (rel-path, function-name) pairs where a device->host conversion is THE
# designed fetch barrier for its path (prefetched via
# copy_to_host_async, one round trip per tick). The runtime witness
# (jax_witness.py) exempts transfers whose call stack passes through one
# of these, so the static and dynamic passes bless identical seams.
SANCTIONED_FETCH: Set[Tuple[str, str]] = {
    ("karpenter_tpu/solver/ffd.py", "solve_dense_tuple"),
    ("karpenter_tpu/solver/ffd.py", "expand_fused"),
    ("karpenter_tpu/solver/ffd.py", "expand_compact"),
    ("karpenter_tpu/solver/service.py", "solve_finish"),
    ("karpenter_tpu/solver/service.py", "_pack_existing"),
    ("karpenter_tpu/solver/rpc.py", "_op_solve"),
    ("karpenter_tpu/solver/rpc.py", "_op_solve_compact"),
    ("karpenter_tpu/solver/rpc.py", "_op_solve_disrupt"),
    ("karpenter_tpu/solver/disrupt/engine.py", "_dispatch_local"),
    ("karpenter_tpu/solver/disrupt/engine.py", "_evaluate_local"),
    ("karpenter_tpu/parallel/mesh.py", "_fetch_multiprocess"),
    ("karpenter_tpu/fleet/shard.py", "fetch"),
    # the optimality-gap bound's designed barrier: drains the
    # copy_to_host_async issued when solve_finish dispatched the bound
    ("karpenter_tpu/solver/bound.py", "fetch_bound"),
    # the convex tier's designed barrier: drains the relaxation's async
    # copies at the finish barrier (in-process) / fetch stage (sidecar)
    ("karpenter_tpu/solver/convex/relax.py", "fetch_relax"),
    ("karpenter_tpu/solver/rpc.py", "_op_solve_convex"),
    # observatory introspection seams: memory_stats() reads the
    # allocator ledger (metadata, no transfer) and the profiler bracket
    # drives the runtime's own trace collection -- both are designed
    # device-runtime touchpoints, blessed for the static rules AND the
    # runtime witness exactly like the fetch barriers above
    ("karpenter_tpu/obs/hbm.py", "poll"),
    ("karpenter_tpu/obs/profiler.py", "on_tick_start"),
    ("karpenter_tpu/obs/profiler.py", "on_tick_end"),
}

RULE_UNBOUNDED = "jaxjit/unbounded-static"
RULE_PALLAS_TWIN = "jaxjit/pallas-twin"
RULE_CLOSURE = "jaxjit/closure-state"
RULE_BRANCH = "jaxjit/traced-branch"
RULE_DTYPE = "jaxjit/weak-dtype"
RULE_ITEM = "jaxhost/item"
RULE_CAST = "jaxhost/scalar-cast"
RULE_NP = "jaxhost/np-on-device"
RULE_BLOCK = "jaxhost/block-until-ready"

# attribute reads that produce trace-time Python values (no taint)
_SHAPE_ATTRS = ("shape", "dtype", "ndim", "size", "weak_type", "sharding")
# calls whose result is never a traced value regardless of arguments
_TAINT_KILLERS = ("len", "isinstance", "type", "range", "id", "repr", "str")
_CREATION_FNS = ("zeros", "ones", "full", "empty", "arange", "linspace",
                 "eye", "identity", "array", "asarray")
_DTYPE_NAME_HINTS = (
    "float32", "float64", "float16", "bfloat16", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
)
_NP_SYNC_TAILS = ("asarray", "array", "copy")
_JIT_ENTRY_NAMES = frozenset(
    name for names in JIT_ENTRY_FUNCTIONS.values() for name in names
)


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _jit_call_of(dec: ast.AST) -> Optional[ast.Call]:
    """The jax.jit(...) Call a decorator represents, or None. Handles
    ``@jax.jit``, ``@jax.jit(...)``, and ``@functools.partial(jax.jit,
    ...)`` (the repo idiom)."""
    if isinstance(dec, ast.Call):
        if _is_jax_jit(dec.func):
            return dec
        if _dotted(dec.func) in ("functools.partial", "partial") and dec.args \
                and _is_jax_jit(dec.args[0]):
            return dec
    return None


def _literal_argnames(call: ast.Call) -> Optional[Tuple[Optional[List[str]], bool]]:
    """(static_argnames as a list of strings or None when absent,
    uses_static_argnums). Returns None when static_argnames is present
    but not a literal (itself a violation)."""
    names: Optional[List[str]] = None
    has_nums = False
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            has_nums = True
        elif kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts
            ):
                names = [e.value for e in v.elts]  # type: ignore[misc]
            else:
                return None
    return names, has_nums


class _ModuleContext:
    """Per-module name classification for the jitted-body rules."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.constants: Set[str] = set()
        self.mutables: Set[str] = set()
        imported: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node  # type: ignore[assignment]
            elif isinstance(node, ast.ClassDef):
                imported.add(node.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    imported.add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    imported.add(a.asname or a.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            if n.id.lstrip("_").isupper():
                                self.constants.add(n.id)
                            else:
                                self.mutables.add(n.id)
        self.imported = imported
        # a name both assigned and imported counts as imported (re-export)
        self.mutables -= imported


class _BodyScan:
    """Taint-tracking walk of ONE jitted body (plus module-local helpers,
    transitively). Taint = "this expression is a traced value"."""

    def __init__(self, ctx: _ModuleContext, out: List[Violation],
                 seen: Set[Tuple[str, int, str]]):
        self.ctx = ctx
        self.out = out
        self.seen = seen          # (rule, line, detail) dedup across entry points
        # (FunctionDef id, frozen traced-param set): a helper is
        # re-scanned per DISTINCT taint mapping -- one call site passing
        # only statics must not shadow a later one passing traced values
        self.visited: Set[Tuple[int, frozenset]] = set()

    def _emit(self, rule: str, node: ast.AST, msg: str) -> None:
        key = (rule, getattr(node, "lineno", 0), msg)
        if key in self.seen:
            return
        self.seen.add(key)
        self.out.append(self.ctx.mod.violation(rule, node, msg))

    # -- taint evaluation -----------------------------------------------------
    def _taint(self, node: ast.AST, env: Dict[str, bool]) -> bool:
        if isinstance(node, ast.Name):
            return env.get(node.id, False)
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return False
            return self._taint(node.value, env)
        if isinstance(node, ast.Subscript):
            return self._taint(node.value, env)
        if isinstance(node, ast.Call):
            f = _dotted(node.func) or ""
            if f in _TAINT_KILLERS:
                return False
            # a method call on a traced value (x.sum(), v.max()) is traced
            recv = self._taint(node.func.value, env) \
                if isinstance(node.func, ast.Attribute) else False
            return recv or any(self._taint(a, env) for a in node.args) or any(
                self._taint(kw.value, env) for kw in node.keywords
            )
        if isinstance(node, (ast.BinOp,)):
            return self._taint(node.left, env) or self._taint(node.right, env)
        if isinstance(node, ast.BoolOp):
            return any(self._taint(v, env) for v in node.values)
        if isinstance(node, ast.Compare):
            return self._taint(node.left, env) or any(
                self._taint(c, env) for c in node.comparators
            )
        if isinstance(node, ast.UnaryOp):
            return self._taint(node.operand, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._taint(e, env) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self._taint(node.body, env) or self._taint(node.orelse, env)
                    or self._taint(node.test, env))
        if isinstance(node, ast.Starred):
            return self._taint(node.value, env)
        return False

    def _bind(self, target: ast.AST, tainted: bool, env: Dict[str, bool]) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                env[n.id] = tainted

    # -- the walk -------------------------------------------------------------
    def scan_function(self, fn: ast.FunctionDef,
                      traced_params: Optional[Iterable[str]] = None,
                      outer_env: Optional[Dict[str, bool]] = None) -> None:
        args = fn.args
        all_params = [a.arg for a in
                      args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            all_params.append(args.vararg.arg)
        if args.kwarg:
            all_params.append(args.kwarg.arg)
        traced = set(traced_params) if traced_params is not None else set(all_params)
        key = (id(fn), frozenset(traced))
        if key in self.visited:
            return
        self.visited.add(key)
        env: Dict[str, bool] = dict(outer_env or {})
        for p in all_params:
            env[p] = p in traced
        self._scan_block(fn.body, env)

    def _scan_block(self, body: List[ast.stmt], env: Dict[str, bool]) -> None:
        for stmt in body:
            self._scan_stmt(stmt, env)

    def _scan_stmt(self, stmt: ast.stmt, env: Dict[str, bool]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def inside a jitted body is (almost always) traced
            # through lax control flow: every parameter is a traced value,
            # free variables resolve through the enclosing taint env
            self.scan_function(stmt, None, outer_env=env)  # type: ignore[arg-type]
            env[stmt.name] = False
            return
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value, env)
            t = self._taint(stmt.value, env)
            for target in stmt.targets:
                self._bind(target, t, env)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._scan_expr(stmt.value, env)
            self._bind(stmt.target, self._taint(stmt.value, env), env)
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value, env)
            if self._taint(stmt.value, env):
                self._bind(stmt.target, True, env)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, env)
            if self._taint(stmt.test, env):
                self._emit(RULE_BRANCH, stmt,
                           "Python branching on a traced value inside a jitted "
                           "body; use jnp.where/lax.cond (or make the input a "
                           "manifest-declared static)")
            self._scan_block(stmt.body, env)
            self._scan_block(stmt.orelse, env)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter, env)
            if self._taint(stmt.iter, env):
                self._emit(RULE_BRANCH, stmt,
                           "Python loop over a traced value inside a jitted "
                           "body; use lax.scan/fori_loop")
            self._bind(stmt.target, False, env)
            self._scan_block(stmt.body, env)
            self._scan_block(stmt.orelse, env)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._scan_expr(stmt.value, env)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, env)
            return
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, env)
            self._scan_block(stmt.body, env)
            return
        if isinstance(stmt, (ast.Try,)):
            self._scan_block(stmt.body, env)
            for h in stmt.handlers:
                self._scan_block(h.body, env)
            self._scan_block(stmt.orelse, env)
            self._scan_block(stmt.finalbody, env)
            return
        # raise/pass/assert/etc: walk expressions for rule hits
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, env)

    def _scan_expr(self, expr: ast.expr, env: Dict[str, bool]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" and isinstance(node.ctx, ast.Load):
                self._emit(RULE_CLOSURE, node,
                           f"jitted body reads instance state self.{node.attr}; "
                           "jax hashes closures by identity -- pass it as an "
                           "argument (static if bounded, traced otherwise)")
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in self.ctx.mutables and node.id not in env:
                self._emit(RULE_CLOSURE, node,
                           f"jitted body reads module-level mutable {node.id!r}; "
                           "a rebind is invisible to the compiled program -- "
                           "pass it as an argument or promote it to an "
                           "ALL_CAPS constant")
            elif isinstance(node, ast.IfExp) and self._taint(node.test, env):
                self._emit(RULE_BRANCH, node,
                           "ternary on a traced value inside a jitted body; "
                           "use jnp.where")
            elif isinstance(node, ast.Call):
                self._check_call(node, env)

    def _check_call(self, node: ast.Call, env: Dict[str, bool]) -> None:
        f = _dotted(node.func) or ""
        parts = f.split(".")
        # weak-dtype: array creation without an explicit dtype
        if len(parts) >= 2 and parts[-1] in _CREATION_FNS \
                and parts[-2] in ("jnp", "numpy", "np"):
            if not self._has_dtype(node):
                self._emit(RULE_DTYPE, node,
                           f"{f}() without an explicit dtype inside a jitted "
                           "body leaks a weak type; a weak-vs-committed dtype "
                           "mismatch between call paths is a retrace")
        # transitive scan of module-local helpers, with argument taints
        # mapped onto the callee's parameters
        target = None
        if len(parts) == 1 and parts[0] in self.ctx.functions:
            target = self.ctx.functions[parts[0]]
        if target is not None:
            # scan_function dedupes by (function, taint set): each call
            # site contributes its own mapping
            traced = self._map_call_taints(target, node, env)
            self.scan_function(target, traced)

    def _map_call_taints(self, fn: ast.FunctionDef, call: ast.Call,
                         env: Dict[str, bool]) -> Set[str]:
        params = [a.arg for a in
                  fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs]
        traced: Set[str] = set()
        for i, a in enumerate(call.args):
            if i < len(params) and self._taint(a, env):
                traced.add(params[i])
        for kw in call.keywords:
            if kw.arg in params and self._taint(kw.value, env):
                traced.add(kw.arg)
        return traced

    @staticmethod
    def _has_dtype(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg == "dtype":
                return True
        for a in node.args[1:]:
            if isinstance(a, ast.Attribute) and a.attr in _DTYPE_NAME_HINTS:
                return True
            if isinstance(a, ast.Name) and a.id in ("bool", "float", "int"):
                return True
        return False


def jit_decoration_sites(modules: List[Module]) -> Dict[str, List[Tuple[str, ast.FunctionDef, Optional[ast.Call]]]]:
    """rel -> [(name, function node, jit call or None for bare @jax.jit)]
    for every jit-decorated function under the scan prefixes."""
    out: Dict[str, List[Tuple[str, ast.FunctionDef, Optional[ast.Call]]]] = {}
    for mod in modules:
        if not mod.rel.startswith(JIT_SCAN_PREFIXES):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                call = _jit_call_of(dec)
                if call is not None or _is_jax_jit(dec):
                    out.setdefault(mod.rel, []).append(
                        (node.name, node, call))  # type: ignore[arg-type]
    return out


def _validate_jit_statics(mod: Module, call: ast.Call, where: str,
                          out: List[Violation]) -> List[str]:
    """Shared static_argnames/static_argnums policy for BOTH discovery
    paths (decorators and standalone jax.jit(...) wrappers): literal
    argnames only, no positional argnums, every name a declared bucket.
    Returns the parsed static names (empty on a non-literal)."""
    lit = _literal_argnames(call)
    if lit is None:
        out.append(mod.violation(
            RULE_UNBOUNDED, call,
            f"{where}: static_argnames must be a literal tuple of strings "
            "so the bucketing manifest can be checked"))
        return []
    names, has_nums = lit
    if has_nums:
        out.append(mod.violation(
            RULE_UNBOUNDED, call,
            f"{where}: static_argnums is positional and drifts silently "
            "under refactors; use static_argnames"))
    for sn in names or []:
        if sn not in STATIC_ARG_BUCKETS:
            out.append(mod.violation(
                RULE_UNBOUNDED, call,
                f"{where}: static arg {sn!r} is not in the "
                "bounded-cardinality bucketing manifest (STATIC_ARG_BUCKETS); "
                "an unbounded static compiles one program per distinct value"))
    return list(names or [])


def check_retrace(modules: List[Module]) -> List[Violation]:
    out: List[Violation] = []
    sites = jit_decoration_sites(modules)
    for mod in modules:
        if not mod.rel.startswith(JIT_SCAN_PREFIXES):
            continue
        entries = sites.get(mod.rel, [])
        decorator_calls = {id(call) for _, _, call in entries if call is not None}
        ctx = _ModuleContext(mod)
        seen: Set[Tuple[str, int, str]] = set()
        scan = _BodyScan(ctx, out, seen)
        for name, fn, call in entries:
            static_names: List[str] = []
            if call is not None:
                static_names = _validate_jit_statics(mod, call, name, out)
            args = fn.args
            params = [a.arg for a in
                      args.posonlyargs + args.args + args.kwonlyargs]
            traced = [p for p in params if p not in static_names]
            scan.scan_function(fn, traced)
        # standalone jax.jit(...) call sites (dynamic wrappers, mesh.py):
        # statics still validate; bodies resolve only for local names
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func) \
                    and id(node) not in decorator_calls:
                _validate_jit_statics(mod, node, "jax.jit call", out)
    out.extend(_check_pallas_twins(modules, sites))
    return out


def _check_pallas_twins(
    modules: List[Module],
    sites: Dict[str, List[Tuple[str, ast.FunctionDef, Optional[ast.Call]]]],
) -> List[Violation]:
    """jaxjit/pallas-twin: every jit entry in a module that lowers
    through pallas_call must declare a twin in PALLAS_TWINS, and the
    declared twin function must exist (by AST) in its module -- the
    fallback rung is a manifest contract, not a convention."""
    out: List[Violation] = []
    by_rel = {m.rel: m for m in modules}
    for mod in modules:
        has_pallas = any(
            isinstance(n, ast.Call)
            and (_dotted(n.func) or "").split(".")[-1] == "pallas_call"
            for n in ast.walk(mod.tree))
        if not has_pallas:
            continue
        for name, fn, _call in sites.get(mod.rel, []):
            twin = PALLAS_TWINS.get((mod.rel, name))
            if twin is None:
                out.append(mod.violation(
                    RULE_PALLAS_TWIN, fn,
                    f"{name}: Pallas kernel entry has no registered XLA twin "
                    "(PALLAS_TWINS); the dispatch fallback rung would be "
                    "orphaned"))
                continue
            twin_rel, twin_fn = twin
            twin_mod = by_rel.get(twin_rel)
            defined = twin_mod is not None and any(
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name == twin_fn
                for n in ast.walk(twin_mod.tree))
            if not defined:
                out.append(mod.violation(
                    RULE_PALLAS_TWIN, fn,
                    f"{name}: declared XLA twin {twin_rel}:{twin_fn} "
                    "does not exist"))
    return out


# -- host-sync rules ----------------------------------------------------------


def _taints_from_jit_calls(fn: ast.AST) -> Set[str]:
    """Names whose LAST assignment (in source order) in this function is
    directly a jit entry-point call. Any other reassignment clears
    (fetching through np.asarray / jax.device_get launders the device
    value by design). ast.walk is breadth-first, so assignments are
    explicitly re-sorted by source position -- a nested conditional
    assign must not be processed after a later top-level one."""
    assigns = sorted(
        (n for n in ast.walk(fn) if isinstance(n, ast.Assign)),
        key=lambda n: (n.lineno, n.col_offset),
    )
    tainted: Set[str] = set()
    for node in assigns:
        v = node.value
        d = _dotted(v.func) if isinstance(v, ast.Call) else None
        is_jit = d is not None and d.split(".")[-1] in _JIT_ENTRY_NAMES
        for target in node.targets:
            for n in ast.walk(target):
                if isinstance(n, ast.Name):
                    if is_jit:
                        tainted.add(n.id)
                    else:
                        tainted.discard(n.id)
    return tainted


def _scan_hot_function(mod: Module, fn: ast.AST, where: str,
                       sanctioned: bool) -> List[Violation]:
    out: List[Violation] = []
    tainted = _taints_from_jit_calls(fn)

    def root_name(e: ast.AST) -> Optional[str]:
        while isinstance(e, (ast.Attribute, ast.Subscript)):
            e = e.value
        return e.id if isinstance(e, ast.Name) else None

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        d = _dotted(f) or ""
        tail = d.split(".")[-1]
        if tail == "item" and isinstance(f, ast.Attribute):
            out.append(mod.violation(RULE_ITEM, node,
                                     f"{where}: .item() synchronously round-trips "
                                     "device->host on the tick hot path"))
        elif tail == "block_until_ready":
            out.append(mod.violation(RULE_BLOCK, node,
                                     f"{where}: explicit device barrier on the hot "
                                     "path serializes the pipelined tick"))
        elif d in ("float", "int") and node.args:
            arg = node.args[0]
            an = root_name(arg)
            arg_call = _dotted(arg.func) if isinstance(arg, ast.Call) else None
            from_jit = arg_call is not None and \
                arg_call.split(".")[-1] in _JIT_ENTRY_NAMES
            if (an is not None and an in tainted) or from_jit:
                out.append(mod.violation(RULE_CAST, node,
                                         f"{where}: {d}() on a jit-entry result "
                                         "blocks on device compute; fetch through "
                                         "the sanctioned barrier first"))
        elif not sanctioned and (
            (tail in _NP_SYNC_TAILS and len(d.split(".")) >= 2
             and d.split(".")[-2] in ("np", "numpy"))
            or tail == "device_get"
        ):
            if node.args and isinstance(node.args[0], (ast.Name, ast.Attribute)):
                out.append(mod.violation(RULE_NP, node,
                                         f"{where}: {d}() forces a synchronous "
                                         "device->host copy; route through a "
                                         "SANCTIONED_FETCH site (prefetched via "
                                         "copy_to_host_async)"))
    return out


def check_hostsync(modules: List[Module]) -> List[Violation]:
    out: List[Violation] = []
    by_rel = {m.rel: m for m in modules}
    for rel, (func_names, class_methods) in DEVICE_HOT_PATH.items():
        mod = by_rel.get(rel)
        if mod is None:
            continue
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in func_names:
                out.extend(_scan_hot_function(
                    mod, node, node.name, (rel, node.name) in SANCTIONED_FETCH))
            elif isinstance(node, ast.ClassDef) and node.name in class_methods:
                wanted = class_methods[node.name]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                            and item.name in wanted:
                        out.extend(_scan_hot_function(
                            mod, item, f"{node.name}.{item.name}",
                            (rel, item.name) in SANCTIONED_FETCH))
    return out


def hot_path_functions(rel: str) -> Optional[Tuple[Tuple[str, ...], Dict[str, Tuple[str, ...]]]]:
    """Manifest lookup (the zerocopy contract shape): a new hot-path
    function must be ADDED here to be guarded."""
    return DEVICE_HOT_PATH.get(rel)
