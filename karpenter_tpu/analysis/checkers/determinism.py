"""Determinism checker: replay-breaking entropy, caught at lint time.

The golden-digest discipline (docs/simulation.md) holds only if every
RNG and clock a replay can observe derives from Options.seed through
karpenter_tpu/seeding.py. PR 4 found the NodeClaim-name ``uuid4`` only
at replay time; this checker finds the next one at lint time.

Call names are resolved through the module's import aliases before
matching (``import time as _time`` / ``from random import choice`` /
``from datetime import datetime as dt`` cannot launder an entropy or
clock read), mirroring the lock checker's import maps.

Rules:

- ``determinism/uuid4``     -- a ``uuid.uuid4()`` CALL. Exempt ONLY on
  the unseeded-fallback arm of an ``X_rng``-vs-None test: the documented
  shape of a seedable stream's production fallback (apis/objects.py
  generate_name / generate_uid / generate_intent_token). A uuid4 call on
  the SEEDED arm -- or anywhere else in a function that happens to touch
  a ``*_rng`` stream -- is a violation.
- ``determinism/random``    -- a ``random.X(...)`` or ``np.random.X(...)``
  call drawing from process-global entropy. Seeded STREAM CONSTRUCTION
  is exempt: ``random.Random(seed_expr)`` / ``np.random.default_rng(seed)``
  with arguments. Bare references (``rng=random.random`` as an
  injectable default) are not calls and never flagged -- injection
  points are the sanctioned pattern.
- ``determinism/wallclock`` -- ``time.time()`` / ``time.time_ns()`` /
  ``datetime.now()`` / ``datetime.utcnow()`` calls outside a function
  named ``now``/``_now``: wall-clock reads live behind a NAMED clock
  seam with an injectable clock (cache/ttl.py Clock), so FakeClock can
  own time everywhere else. ``time.monotonic``/``perf_counter`` measure
  durations, never feed decisions, and are not flagged.
- ``determinism/iter-order`` -- iteration whose order the runtime does
  not define: looping a set display / ``set(...)`` / set comprehension
  directly (PYTHONHASHSEED-dependent), or ``os.listdir``/``glob.glob``/
  ``os.scandir`` results consumed without ``sorted()`` anywhere above
  them (a listing feeding a comprehension inside ``sorted(...)`` is
  order-independent and exempt).

karpenter_tpu/seeding.py is exempt wholesale: it IS the sanctioned
entropy seam.
"""
from __future__ import annotations

import ast
from typing import List, Set

from karpenter_tpu.analysis.base import Module, Violation
from karpenter_tpu.analysis.base import dotted as _dotted

EXEMPT_MODULES = ("karpenter_tpu/seeding.py",)
CLOCK_SEAM_NAMES = ("now", "_now")
WALLCLOCK_CALLS = {
    ("time", "time"), ("time", "time_ns"),
    ("datetime", "now"), ("datetime", "utcnow"),
}
LISTING_CALLS = {
    ("os", "listdir"), ("os", "scandir"), ("glob", "glob"), ("glob", "iglob"),
}


def _aliases(tree: ast.AST):
    """(imports, from_imports) like the lock checker's _collect: the
    canonicalizer resolves aliased call spellings through these."""
    imports = {}
    from_imports = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    # `import os.path` binds the ROOT name to the root module
                    root = a.name.split(".")[0]
                    imports[root] = root
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                from_imports[a.asname or a.name] = (node.module, a.name)
    return imports, from_imports


def _uuid4_fallback_ids(tree: ast.AST) -> Set[int]:
    """Node ids on the unseeded-fallback arm of an ``X_rng``-vs-None test
    inside a function -- the one place a bare uuid4 is sanctioned. For
    `if X_rng is None:` / `if not X_rng:` the fallback arm is the body;
    for `if X_rng is not None:` / `if X_rng:` it is everything else in
    the function (the else-or-after region of the generate_* shape)."""
    exempt: Set[int] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_ids = None
        for iff in ast.walk(fn):
            if not isinstance(iff, ast.If):
                continue
            t = iff.test
            rng_expr = none_in_body = None
            if (isinstance(t, ast.Compare) and len(t.ops) == 1
                    and isinstance(t.ops[0], (ast.Is, ast.IsNot))
                    and isinstance(t.comparators[0], ast.Constant)
                    and t.comparators[0].value is None):
                rng_expr = t.left
                none_in_body = isinstance(t.ops[0], ast.Is)
            elif isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
                rng_expr = t.operand
                none_in_body = True
            elif isinstance(t, (ast.Name, ast.Attribute)):
                rng_expr = t
                none_in_body = False
            if rng_expr is None:
                continue
            name = _dotted(rng_expr) or ""
            if not name.split(".")[-1].endswith("_rng"):
                continue
            body_ids = {id(n) for st in iff.body for n in ast.walk(st)}
            if none_in_body:
                exempt |= body_ids
            else:
                if fn_ids is None:
                    fn_ids = {id(n) for n in ast.walk(fn)}
                exempt |= fn_ids - body_ids
    return exempt


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


class _Scan(ast.NodeVisitor):
    def __init__(self, mod: Module):
        self.mod = mod
        self.out: List[Violation] = []
        # enclosing def stack: names only (the clock-seam check)
        self.funcs: List[str] = []
        # call nodes anywhere INSIDE a sorted() first argument (the
        # listing may feed a filtering comprehension; the sort still
        # erases its order)
        self.sorted_args: Set[int] = set()
        self.imports, self.from_imports = _aliases(mod.tree)
        self.uuid4_fallback = _uuid4_fallback_ids(mod.tree)

    # -- scope tracking -------------------------------------------------------
    def _enter(self, node, name: str):
        self.funcs.append(name)
        self.generic_visit(node)
        self.funcs.pop()

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._enter(node, node.name)

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>")

    def _in_clock_seam(self) -> bool:
        return bool(self.funcs) and self.funcs[-1] in CLOCK_SEAM_NAMES

    def _canonical(self, dotted: str) -> str:
        """Resolve the spelling's root through the import aliases:
        `_time.time` -> `time.time`, `choice` -> `random.choice`,
        `dt.now` (from `datetime import datetime as dt`) ->
        `datetime.datetime.now`. Unknown roots pass through unchanged."""
        parts = dotted.split(".")
        head = parts[0]
        if head in self.imports:
            return ".".join([self.imports[head]] + parts[1:])
        if head in self.from_imports:
            mod, orig = self.from_imports[head]
            return ".".join([mod, orig] + parts[1:])
        return dotted

    # -- rules ----------------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dotted = _dotted(node.func)
        if dotted:
            self._check_dotted_call(node, dotted)
        self.generic_visit(node)

    def _check_dotted_call(self, node: ast.Call, dotted: str):
        v = self.mod.violation
        parts = tuple(self._canonical(dotted).split("."))
        tail2 = parts[-2:] if len(parts) >= 2 else (None, parts[-1])
        if parts[-1] == "uuid4":
            if id(node) not in self.uuid4_fallback:
                self.out.append(v("determinism/uuid4", node,
                                  "bare uuid.uuid4() outside a seedable *_rng "
                                  "stream's unseeded-fallback arm (derive from "
                                  "seeding.seeded_rng or baseline with a "
                                  "uniqueness justification)"))
            return
        if tail2 in WALLCLOCK_CALLS and not self._in_clock_seam():
            self.out.append(v("determinism/wallclock", node,
                              f"wall-clock read {dotted}() outside a now()/_now() "
                              "clock seam; thread an injectable clock instead"))
            return
        if tail2 in LISTING_CALLS and id(node) not in self.sorted_args:
            self.out.append(v("determinism/iter-order", node,
                              f"{dotted}() order is filesystem-dependent; wrap "
                              "in sorted(...)"))
            return
        # random.X(...) / np.random.X(...): module-level entropy draws
        if len(parts) >= 2 and parts[-2] == "random":
            if parts[-1] in ("Random", "default_rng", "RandomState") and node.args:
                return  # seeded stream construction
            self.out.append(v("determinism/random", node,
                              f"{dotted}() draws process-global entropy; use a "
                              "seeding.seeded_rng stream or inject the rng"))

    def visit_For(self, node: ast.For):
        if _is_set_expr(node.iter):
            self.out.append(self.mod.violation(
                "determinism/iter-order", node,
                "iterating a set: order is PYTHONHASHSEED-dependent; sort first"))
        self.generic_visit(node)

    def visit_comprehension_iter(self, node):  # helper, not a visitor hook
        pass

    def _check_comp(self, node):
        for gen in node.generators:
            if _is_set_expr(gen.iter):
                self.out.append(self.mod.violation(
                    "determinism/iter-order", node,
                    "comprehension over a set: order is PYTHONHASHSEED-"
                    "dependent; sort first"))
        self.generic_visit(node)

    def visit_ListComp(self, node):
        self._check_comp(node)

    def visit_GeneratorExp(self, node):
        self._check_comp(node)

    def visit_DictComp(self, node):
        self._check_comp(node)


def check(modules: List[Module]) -> List[Violation]:
    out: List[Violation] = []
    for mod in modules:
        if mod.rel in EXEMPT_MODULES:
            continue
        # collect sorted-arg subtrees FIRST (the sorted() wrapper may be
        # visited after the listing call it exempts): every node under a
        # sorted() first argument is order-erased
        scan = _Scan(mod)
        for n in ast.walk(mod.tree):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id == "sorted" and n.args):
                for sub in ast.walk(n.args[0]):
                    scan.sorted_args.add(id(sub))
        scan.visit(mod.tree)
        out.extend(scan.out)
    return out
