"""Registry-drift checker: every registered name appears in its doc table.

The metrics page already has a runtime drift gate (hack/metrics_gen.py
--check renders docs/metrics.md from the live registry). This checker
extends the discipline to ALL three registries, statically -- no imports,
so it runs in a bare container and catches names in modules the doc
generator's import list missed:

- ``registry/metric-undocumented``    -- every metric family registered
  via ``REGISTRY.counter/gauge/histogram("karpenter_...")`` must appear
  in docs/metrics.md.
- ``registry/failpoint-undocumented`` -- every failpoint site evaluated
  in code (``failpoints.eval/corrupt/live("site")``) must appear in the
  site table in docs/operations.md.
- ``registry/feature-undocumented``   -- every RPC feature flag the
  server advertises (the ``features`` list in solver/rpc.py, plus
  conditional ``features.append``) must appear somewhere under docs/.
- ``registry/seam-unfailpointed``     -- every ``LADDER_SEAMS`` entry
  (checkers/errflow.py) must name a failpoint site that actually exists
  as a ``failpoints.eval/corrupt/live`` call in the package: a degrade
  seam without a chaos drill is a contract nothing exercises.

Metric and failpoint names match backtick-exact (`` `name` ``) against
their doc tables -- a plain substring test would let a name that merely
prefixes a documented one pass. Feature flags match as substrings across
docs/ (they appear in prose, not a canonical table).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from karpenter_tpu.analysis.base import REPO_ROOT, Module, Violation

METRICS_DOC = "docs/metrics.md"
FAILPOINTS_DOC = "docs/operations.md"
_REGISTER_METHODS = {"counter", "gauge", "histogram"}
_SITE_FUNCS = {"eval", "corrupt", "live", "hits", "fires"}


def _doc_text(rel: str) -> str:
    p = REPO_ROOT / rel
    return p.read_text() if p.exists() else ""


def _collect_metric_families(modules: List[Module]) -> List[Tuple[Module, ast.Call, str]]:
    out = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _REGISTER_METHODS or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str) \
                    and first.value.startswith("karpenter_"):
                out.append((mod, node, first.value))
    return out


def _collect_failpoint_sites(modules: List[Module]) -> List[Tuple[Module, ast.Call, str]]:
    out = []
    for mod in modules:
        if mod.rel == "karpenter_tpu/failpoints.py":
            continue  # the framework's own docstring examples
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            is_site_call = (
                isinstance(f, ast.Attribute)
                and f.attr in _SITE_FUNCS
                and isinstance(f.value, ast.Name)
                and f.value.id in ("failpoints", "FAILPOINTS")
            )
            if not is_site_call:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                out.append((mod, node, first.value))
    return out


def _collect_feature_flags(modules: List[Module]) -> List[Tuple[Module, ast.AST, str]]:
    out = []
    for mod in modules:
        if mod.rel != "karpenter_tpu/solver/rpc.py":
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "features" \
                    and isinstance(node.value, (ast.List, ast.Tuple)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        out.append((mod, elt, elt.value))
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "features" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.append((mod, arg, arg.value))
    return out


def check(modules: List[Module]) -> List[Violation]:
    out: List[Violation] = []
    metrics_doc = _doc_text(METRICS_DOC)
    ops_doc = _doc_text(FAILPOINTS_DOC)
    docs_all = "\n".join(
        p.read_text() for p in sorted((REPO_ROOT / "docs").glob("*.md")))

    seen: Set[Tuple[str, str]] = set()
    for mod, node, name in _collect_metric_families(modules):
        if ("metric", name) in seen:
            continue
        seen.add(("metric", name))
        # backtick-exact, like the failpoint check below: a plain substring
        # test would let a name that PREFIXES a documented family pass
        # (e.g. karpenter_journal_writes inside karpenter_journal_writes_total)
        if f"`{name}`" not in metrics_doc:
            out.append(mod.violation(
                "registry/metric-undocumented", node,
                f"metric family {name} is not in {METRICS_DOC}; run "
                "`python hack/metrics_gen.py` (and add its module to the "
                "generator's import list if it is new)"))
    for mod, node, site in _collect_failpoint_sites(modules):
        if ("site", site) in seen:
            continue
        seen.add(("site", site))
        if f"`{site}`" not in ops_doc:
            out.append(mod.violation(
                "registry/failpoint-undocumented", node,
                f"failpoint site {site} is not in the site table in "
                f"{FAILPOINTS_DOC}"))
    for mod, node, flag in _collect_feature_flags(modules):
        if ("feature", flag) in seen:
            continue
        seen.add(("feature", flag))
        if flag not in docs_all:
            out.append(mod.violation(
                "registry/feature-undocumented", node,
                f"RPC feature flag {flag!r} is advertised by the server but "
                "documented nowhere under docs/"))

    # every degrade-ladder seam must have a live chaos drill: the
    # failpoint site its LADDER_SEAMS entry names has to exist in code
    from karpenter_tpu.analysis.checkers.errflow import LADDER_SEAMS

    code_sites = {site for _, _, site in _collect_failpoint_sites(modules)}
    by_rel = {m.rel: m for m in modules}
    for seam in LADDER_SEAMS:
        mod = by_rel.get(seam.rel)
        if mod is None:
            continue  # fixture runs carry partial trees
        if not seam.failpoint:
            out.append(mod.violation(
                "registry/seam-unfailpointed", 1,
                f"LADDER_SEAMS entry {seam.key} declares no failpoint "
                "site: a degrade seam needs a chaos drill"))
        elif seam.failpoint not in code_sites:
            out.append(mod.violation(
                "registry/seam-unfailpointed", 1,
                f"LADDER_SEAMS entry {seam.key} names failpoint site "
                f"{seam.failpoint!r}, but no failpoints.eval/corrupt/live "
                "call evaluates that site anywhere in the package"))
    return out
