"""Resource-lifecycle checker: release on every path, error edges included.

The degrade ladder's crash drills lean on ``shm.cleanup_stale`` to
reclaim segments a dead process left behind -- but a LIVE process that
leaks a socket per reconnect attempt, an fd per failed attach, or an
unjoined thread per breaker trip degrades just as surely, and no chaos
schedule asserts "zero leaked fds". This checker is the static analogue:
every resource ALLOCATION site in the package (sockets, shm
segments/mmaps, raw fds, files, tempfiles, threads) is discovered, and
release is verified on the error edges, not just the fall-through.

Discovery: a call to a known factory (``socket.socket``/
``create_connection``, ``ShmSegment.create/attach``, ``mmap.mmap``,
``os.open``/``os.fdopen``, builtin ``open``, ``tempfile.*``,
``threading.Thread``) assigned to a plain local name. Out-of-scope by
design (ownership moved, not leaked): allocation directly in a ``with``
item, a value returned/yielded, stored into ``self``/a container (the
class lifecycle rule below takes over), passed to another call, or
aliased away. A rebind through a call taking the old value
(``sock = ctx.wrap_socket(sock)``) is the SAME resource continued.

Rules:

- ``reslife/unreleased``     -- a local resource with no release verb
  (``close``/``destroy``/``shutdown``/``join``/``stop``/...) on any
  path and no ownership escape: a leak even on the happy path.
- ``reslife/leak-on-error``  -- a local resource whose release happens
  only in straight-line code: every release site sits outside any
  ``finally``/``except`` body and outside a ``with``, while a call
  between allocation and release can raise past it. The sanctioned
  shapes are exactly the repo's idioms: ``try/finally: x.close()``,
  ``except: x.close(); raise`` (the ``_conn``/``_try_shm``/
  ``_op_shm_open`` shape), or a with-statement.
- ``reslife/unjoined-thread`` -- a local non-daemon ``threading.Thread``
  that is started but never joined and never escapes: interpreter
  shutdown blocks on it, and nothing owns its lifetime.
- ``reslife/self-unreleased`` -- a resource stored into ``self.X``
  where no method of the class ever releases ``self.X``: the instance
  holds an fd/thread no lifecycle method can free (the class-held
  analogue of ``unreleased``; ``cleanup_stale`` cannot reclaim a
  mapping owned by a live process).

Daemon threads (``daemon=True``) are exempt -- dying with the process
is their lifecycle. ``tempfile.mkstemp``/``mkdtemp`` results are
tracked like fds (the unlink/rmtree verbs release them).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.analysis.base import Module, Violation
from karpenter_tpu.analysis.base import dotted as _dotted

# factory dotted-name SUFFIXES -> resource kind (matched against the
# resolved call chain's last two components, so `shm_mod.ShmSegment.attach`
# and `ShmSegment.attach` both land)
_FACTORIES: Dict[str, str] = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "ShmSegment.create": "shm-segment",
    "ShmSegment.attach": "shm-segment",
    "mmap.mmap": "mmap",
    "os.open": "fd",
    "os.fdopen": "file",
    "os.pipe": "fd",
    "tempfile.NamedTemporaryFile": "tempfile",
    "tempfile.TemporaryDirectory": "tempfile",
    "tempfile.mkstemp": "tempfile",
    "tempfile.mkdtemp": "tempfile",
    "threading.Thread": "thread",
}
_BUILTIN_FACTORIES = {"open": "file"}

_RELEASE_VERBS = frozenset({
    "close", "destroy", "shutdown", "join", "stop", "release", "cleanup",
    "unlink", "terminate", "kill", "rmtree", "remove", "detach",
})


@dataclass
class _Alloc:
    name: str
    kind: str
    node: ast.AST        # the allocation statement
    lineno: int
    daemon: bool = False


def _factory_kind(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    if d is None:
        return None
    if d in _BUILTIN_FACTORIES:
        return _BUILTIN_FACTORIES[d]
    parts = d.split(".")
    for span in (3, 2):
        if len(parts) >= span:
            key = ".".join(parts[-2:])
            hit = _FACTORIES.get(key)
            if hit:
                return hit
    return None


def _is_daemon_thread(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _name_reads(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               and isinstance(n.ctx, ast.Load) for n in ast.walk(node))


class _FnScan:
    """One function's allocation/release/escape accounting."""

    def __init__(self, mod: Module, fn: ast.AST):
        self.mod = mod
        self.fn = fn
        self.allocs: List[_Alloc] = []
        # name -> release statements (and whether each is on a protected
        # position: inside a finalbody or an ExceptHandler body)
        self.releases: Dict[str, List[Tuple[ast.AST, bool]]] = {}
        self.escaped: Set[str] = set()
        # name -> line where ownership first left this function (a
        # self-store, a return, an argument pass): the error window the
        # leak-on-error rule judges ENDS there -- after the transfer the
        # new owner's lifecycle (class rule, caller) takes over
        self.escape_line: Dict[str, int] = {}
        self.joined: Set[str] = set()
        self.withed: Set[str] = set()
        # (id(call-node), name) pairs the generic argument-pass escape
        # must skip: a rebind-through-call (`sock = ctx.wrap_socket(sock)`)
        # CONTINUES the resource under the same name -- without the
        # exemption the value-call's own argument walk would mark it
        # escaped and the rebind special case would be dead code
        self._rebind_exempt: Set[Tuple[int, str]] = set()
        self._scan()

    def _escape(self, name: str, lineno: int) -> None:
        self.escaped.add(name)
        if name not in self.escape_line:
            self.escape_line[name] = lineno

    def _scan(self) -> None:
        fn = self.fn

        def handle_assign(node: ast.AST, protected: bool) -> None:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                return
            t = node.targets[0]
            value = node.value
            if isinstance(t, ast.Tuple) and isinstance(value, ast.Call):
                # fd, path = tempfile.mkstemp(): track the first element
                kind = _factory_kind(value)
                if kind and t.elts and isinstance(t.elts[0], ast.Name):
                    self.allocs.append(_Alloc(t.elts[0].id, kind, node,
                                              node.lineno))
                return
            if not isinstance(t, ast.Name):
                # self.X = FACTORY() is the class-lifecycle rule's domain;
                # an assign whose target is a subscript escapes ownership
                if isinstance(value, ast.Name):
                    self._escape(value.id, node.lineno)
                return
            if isinstance(value, ast.Call):
                kind = _factory_kind(value)
                if kind is not None:
                    if any(a.name == t.id for a in self.allocs):
                        # re-allocation into the same name: judged as one
                        return
                    self.allocs.append(_Alloc(
                        t.id, kind, node, node.lineno,
                        daemon=(kind == "thread" and _is_daemon_thread(value))))
                    return
                # rebind through a call CONSUMING the old value keeps the
                # resource alive under the same name (ssl wrap_socket);
                # passing a tracked name to any OTHER call escapes it
                consumed = {a.id for a in ast.walk(value)
                            if isinstance(a, ast.Name)
                            and isinstance(a.ctx, ast.Load)}
                for alloc in self.allocs:
                    if alloc.name not in consumed:
                        continue
                    if alloc.name == t.id:
                        # same-name rebind through a consuming call: the
                        # SAME resource continues under this name
                        self._rebind_exempt.add((id(value), alloc.name))
                    else:
                        self._escape(alloc.name, node.lineno)
                return
            if isinstance(value, ast.Name):
                # plain alias: ownership is ambiguous -- out of scope
                self._escape(value.id, node.lineno)

        def walk(node: ast.AST, protected: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                # nested defs capture names; treat captured resources as
                # escaped (a closure owns them now)
                for n in ast.walk(node):
                    if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                        self.escaped.add(n.id)
                return
            if isinstance(node, ast.Try):
                for s in node.body:
                    walk(s, protected)
                for h in node.handlers:
                    for s in h.body:
                        walk(s, True)
                for s in node.orelse:
                    walk(s, protected)
                for s in node.finalbody:
                    walk(s, True)
                return
            if isinstance(node, ast.With):
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name):
                        self.withed.add(ce.id)
                    if isinstance(ce, ast.Call):
                        # with closing(sock) / contextlib shapes
                        for a in ce.args:
                            if isinstance(a, ast.Name):
                                self.withed.add(a.id)
                for s in node.body:
                    walk(s, protected)
                return
            handle_assign(node, protected)
            if isinstance(node, ast.Call):
                f = node.func
                d = _dotted(f)
                if d in ("os.close", "os.unlink", "os.remove", "os.rmdir",
                         "shutil.rmtree"):
                    # fd-style release: the resource is the ARGUMENT
                    if node.args and isinstance(node.args[0], ast.Name):
                        self.releases.setdefault(node.args[0].id, []).append(
                            (node, protected))
                    return
                if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                    if f.attr in _RELEASE_VERBS:
                        self.releases.setdefault(f.value.id, []).append(
                            (node, protected))
                        if f.attr == "join":
                            self.joined.add(f.value.id)
                # a tracked name passed as an ARGUMENT escapes ownership
                # (unless this very call is a same-name rebind, above)
                for a in list(node.args) + [kw.value for kw in node.keywords]:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                                and (id(node), n.id) not in self._rebind_exempt:
                            self._escape(n.id, node.lineno)
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                v = getattr(node, "value", None)
                if v is not None:
                    for n in ast.walk(v):
                        if isinstance(n, ast.Name):
                            self._escape(n.id, node.lineno)
            for child in ast.iter_child_nodes(node):
                walk(child, protected)

        for stmt in getattr(fn, "body", ()):
            walk(stmt, False)

    # -- judgments ------------------------------------------------------------
    def violations(self) -> List[Violation]:
        out: List[Violation] = []
        for alloc in self.allocs:
            if alloc.name in self.withed:
                continue
            if alloc.name in self.escaped:
                # ownership leaves this function -- but the window UP TO
                # the transfer is still this function's responsibility:
                # a call in it can raise with the resource unowned
                if alloc.kind == "thread":
                    continue
                rels = self.releases.get(alloc.name, [])
                if any(protected for _, protected in rels):
                    continue
                xfer = self.escape_line.get(alloc.name, alloc.lineno)
                risky = self._calls_between(alloc, xfer)
                if risky is not None:
                    out.append(self.mod.violation(
                        "reslife/leak-on-error", alloc.lineno,
                        f"{alloc.kind} {alloc.name!r} in "
                        f"{getattr(self.fn, 'name', '?')}() is handed off on "
                        f"line {xfer}, but the call on line {risky} can "
                        "raise first and nothing on that edge releases it: "
                        "close on the except edge and re-raise (the _conn "
                        "shape)"))
                continue
            if alloc.kind == "thread":
                if alloc.daemon:
                    continue
                if alloc.name not in self.joined:
                    out.append(self.mod.violation(
                        "reslife/unjoined-thread", alloc.lineno,
                        f"non-daemon Thread {alloc.name!r} in "
                        f"{getattr(self.fn, 'name', '?')}() is never joined "
                        "and never escapes: interpreter shutdown blocks on "
                        "it and nothing owns its lifetime (daemon=True or "
                        "join it)"))
                continue
            rels = self.releases.get(alloc.name, [])
            if not rels:
                out.append(self.mod.violation(
                    "reslife/unreleased", alloc.lineno,
                    f"{alloc.kind} {alloc.name!r} in "
                    f"{getattr(self.fn, 'name', '?')}() is never released "
                    "on any path (no close/destroy/... and no ownership "
                    "escape)"))
                continue
            if any(protected for _, protected in rels):
                continue  # finally / except-edge release: error-safe
            # straight-line-only release: any call between the allocation
            # and the first release can raise past the close
            first_rel = min(r.lineno for r, _ in rels)
            risky = self._calls_between(alloc, first_rel)
            if risky:
                out.append(self.mod.violation(
                    "reslife/leak-on-error", alloc.lineno,
                    f"{alloc.kind} {alloc.name!r} in "
                    f"{getattr(self.fn, 'name', '?')}() is released only on "
                    f"the fall-through path (line {first_rel}), but the "
                    f"call on line {risky} can raise past the release: use "
                    "with/try-finally, or close on the except edge and "
                    "re-raise"))
        return out

    def _calls_between(self, alloc: _Alloc, release_line: int) -> Optional[int]:
        """Line of the first Call strictly between the allocation
        statement and the release, excluding calls that are part of the
        allocation statement itself, calls inside except handlers or
        raise statements (error-edge code, not the happy-path window),
        and release verbs; None when that region is call-free."""
        lo, hi = alloc.lineno, release_line
        alloc_lines = {n.lineno for n in ast.walk(alloc.node)
                       if hasattr(n, "lineno")}
        skip_lines: Set[int] = set()
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.ExceptHandler, ast.Raise)):
                skip_lines.update(n.lineno for n in ast.walk(node)
                                  if hasattr(n, "lineno"))
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Call) and hasattr(node, "lineno"):
                if lo <= node.lineno < hi and node.lineno not in alloc_lines \
                        and node.lineno not in skip_lines:
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _RELEASE_VERBS:
                        continue
                    return node.lineno
        return None


def _class_lifecycle(mod: Module) -> List[Violation]:
    """reslife/self-unreleased: self.X = FACTORY() with no method of the
    class releasing self.X (or delegating to a method whose name is a
    release verb)."""
    out: List[Violation] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        methods = [m for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        stores: Dict[str, Tuple[int, str, bool]] = {}  # attr -> (line, kind, daemon)
        released: Set[str] = set()
        for m in methods:
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and isinstance(sub.value, ast.Call)):
                        kind = _factory_kind(sub.value)
                        if kind:
                            stores.setdefault(t.attr, (
                                sub.lineno, kind,
                                kind == "thread"
                                and _is_daemon_thread(sub.value)))
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _RELEASE_VERBS
                            and isinstance(f.value, ast.Attribute)
                            and isinstance(f.value.value, ast.Name)
                            and f.value.value.id == "self"):
                        released.add(f.value.attr)
                    # os.close(self._fd)-style: the resource is the arg
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _RELEASE_VERBS and sub.args
                            and isinstance(sub.args[0], ast.Attribute)
                            and isinstance(sub.args[0].value, ast.Name)
                            and sub.args[0].value.id == "self"):
                        released.add(sub.args[0].attr)
        for attr, (line, kind, daemon) in sorted(stores.items()):
            if attr in released or daemon:
                continue
            out.append(mod.violation(
                "reslife/self-unreleased", line,
                f"{node.name}.{attr} holds a {kind} no method of the class "
                "ever releases: the instance pins an fd/mapping/thread for "
                "its whole lifetime with no lifecycle seam to free it"))
    return out


def check(modules: List[Module]) -> List[Violation]:
    out: List[Violation] = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_FnScan(mod, node).violations())
        out.extend(_class_lifecycle(mod))
    return out
